"""Continuous-batching decode server for the GPT family.

The reference serves exactly one request per pipeline traversal — a
single stateless forward with no decode at all (SURVEY §3.2-3.3,
/root/reference/node.py:137-200). `runtime/generate.py` already rebuilds
batch decode; this module adds the modern serving layer on top:
CONTINUOUS BATCHING — a fixed pool of decode slots over one static-shape
KV cache, where requests enter (prefill into a free slot) and leave
(EOS / token budget) independently while the other slots keep decoding.
Throughput stays at full batch width without waiting for stragglers.

TPU-first mechanics (everything static under jit, THREE compiled
programs total — chunk prefill, prefill finish, decode step):

  * ONE decode step program for the whole pool: every slot advances one
    token per call. Per-slot sequence positions live in a (B,) vector;
    K/V writes land at each row's own position (vmap'd dynamic update —
    rows are independent), attention masks each row against its own
    length, inactive slots are fully masked no-ops.
  * ONE prefill-chunk program: prompts prefill as full prompt_pad-sized
    chunks plus one right-padded tail, each at its absolute position —
    any prompt length (up to max_len - max_new) reuses the same compiled
    chunk. Tail pad positions write garbage K/V that is never attended
    (the per-row position mask stops at the true length) and is
    overwritten as the sequence grows through it; a second small program
    samples the first token from the true last prompt row and installs
    the finished slot-row cache into the pool.
  * Slot bookkeeping (which request owns which slot, emitted tokens, EOS)
    is plain host Python — it changes per request, so it must not live
    inside the compiled graphs.

Numerics are the same ops as `make_generate` (same embed/block/head
path), so a greedy slot's token stream is identical to a solo batch-1 run
of the same prompt — the parity contract `tests/test_serving.py` pins.
Isolation holds for sampling too: every request gets its own rng stream,
derived from (server seed, request id) and stepped per generated token,
so one request's tokens never depend on what else shares the pool or
when it arrived. (A sampled stream matches `make_generate`'s only in
distribution, not token-for-token — the solo decoder uses one batch-wide
key sequence.)
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnn_tpu import obs
from dnn_tpu.obs.profile import annotation_ctx as _prof_annotation
from dnn_tpu.models.gpt import GPTConfig, head
from dnn_tpu.utils.metrics import Throughput, labeled
from dnn_tpu.ops.attention import merge_heads
from dnn_tpu.ops.nn import gelu, layer_norm, linear
from dnn_tpu.runtime.generate import (
    TOP_P_PREFILTER_K,
    _NEG_BIG,
    _qkv_heads,
    _sample_rows,
    apply_repetition_penalty,
    forward_with_cache,
    init_cache,
    logit_bias_row,
)
from dnn_tpu.runtime.kvcache import codec_for_cache


def _decode_block_rows(bp, x, layer_cache, pos, write, *, cfg, compute_dtype,
                       codec, ffn=None):
    """One block over x (B,1,C) with per-row positions. `write` (B,) bool
    gates the cache update (inactive slots must not touch their rows).
    The cache codec (float or int8 — dnn_tpu/runtime/kvcache.py) owns the
    per-row write/attend; `ffn(bp, h)` overrides the dense MLP (MoE
    serving, dnn_tpu/runtime/generate_moe.moe_cache_ffn)."""
    h = layer_norm(bp["ln_1"], x, eps=cfg.ln_eps)
    q, k, v = _qkv_heads(bp, h, cfg=cfg, compute_dtype=compute_dtype)
    layer_cache = codec.write_rows(layer_cache, k, v, pos, write)
    y = codec.attend_rows(q, layer_cache, pos)
    x = x + linear(bp["attn"]["proj"], merge_heads(y.astype(x.dtype)),
                   compute_dtype=compute_dtype)
    h = layer_norm(bp["ln_2"], x, eps=cfg.ln_eps)
    if ffn is None:
        m = linear(bp["mlp"]["proj"], gelu(linear(bp["mlp"]["fc"], h, compute_dtype=compute_dtype)),
                   compute_dtype=compute_dtype)
    else:
        m = ffn(bp, h).astype(x.dtype)
    return x + m, layer_cache


def install_dense_row(cache, row, slot):
    """Install a finished transient row cache into `slot` of a dense
    pool, CLAMPED at the pool's own position count — the row is
    chunk-rounded and may overhang the pool, and a dynamic update whose
    operand exceeds the target would clamp the start index back onto
    real positions and corrupt the cache (the prefill_finish lesson).
    The one shared implementation for every finish/install program
    (convoy finish, fused interleaved finish, the speculative draft
    installs) so the clamp invariant cannot drift per path."""
    return {
        kk: lax.dynamic_update_slice_in_dim(
            cache[kk],
            lax.slice_in_dim(row[kk], 0, cache[kk].shape[3], axis=3),
            slot, axis=1)
        for kk in cache
    }


class GPTFamilyRows:
    """The GPT family's per-slot decode hooks — the default
    `ContinuousBatcher` family adapter. A family adapter supplies three
    things: the cache layout, the padded-prompt prefill forward, and the
    per-row decode forward (per-slot positions); everything else —
    slot bookkeeping, sampling streams, retirement — is family-agnostic
    and lives in the batcher. Other families plug in the same way
    (LLaMA: dnn_tpu/models/llama.LlamaFamilyRows — RoPE positions and a
    KV-head-width cache; MoE stays a GPT block with `ffn` overridden)."""

    def __init__(self, cfg, *, compute_dtype=None, ffn=None,
                 attn_kernel="auto", unroll_layers: bool = False):
        self.cfg = cfg
        self.compute_dtype = compute_dtype
        self.ffn = ffn
        # cache-attention routing (prefill chunks + decode rows): True =
        # always the Pallas streaming kernel, False = always the einsum,
        # "auto" (default) = the length-aware policy — kernel only on TPU
        # against caches >= kvcache.AUTO_KERNEL_MIN_S positions
        self.attn_kernel = attn_kernel
        # unroll_layers=True unrolls the DECODE-step layer scan into
        # straight-line code: the CPU backend then updates each layer's
        # cache slice truly in place instead of copying the scan-carried
        # cache state around the while loop (the PR-1 "three full-cache
        # copies per step" lowering — measured 1.6x step wall-clock at
        # long context, benchmarks/decode_mbu_probe.py). Costs one body
        # copy per layer at compile time, so it is opt-in; prefill and
        # verify keep the scan (not per-token-hot, and the chunk program
        # compiles per prompt bucket already). TPU while-loops alias
        # loop state natively, so this knob is a CPU-lowering lever.
        self.unroll_layers = bool(unroll_layers)

    def init_cache(self, batch, max_len, dtype):
        return init_cache(self.cfg, batch, max_len, dtype)

    def prefill(self, prepared, padded, row_cache, start_pos=0):
        """One (1, P) prompt chunk at positions [start_pos, start_pos+P)
        -> (logits (1, P, V), row_cache). Long prompts prefill as several
        full chunks + one padded tail (the batcher's chunk loop)."""
        return forward_with_cache(
            prepared, padded, row_cache, start_pos, cfg=self.cfg,
            compute_dtype=self.compute_dtype, ffn=self.ffn,
            attn_kernel=self.attn_kernel)

    def verify_rows(self, prepared, cache, chunk, pos, active, codec):
        """A (B, T) token block at PER-ROW start positions pos (B,):
        writes K/V for positions pos..pos+T-1 of each active row, attends
        with per-row within-block causality (codec.attend_rows_causal),
        returns (logits (B, T, V), cache). The speculative batcher's
        target-scoring / draft-sync program — row t's logits predict the
        token at position pos+t+1."""
        cfg, compute_dtype = self.cfg, self.compute_dtype
        b, t = chunk.shape
        positions = pos[:, None] + jnp.arange(t)  # (B, T)
        x = jnp.take(prepared["wte"]["embedding"], chunk, axis=0) + \
            jnp.take(prepared["wpe"]["embedding"], positions, axis=0)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)

        def layer(carry, layer_in):
            bp, layer_cache = layer_in
            h = layer_norm(bp["ln_1"], carry, eps=cfg.ln_eps)
            q, kk, vv = _qkv_heads(bp, h, cfg=cfg,
                                   compute_dtype=compute_dtype)
            layer_cache = codec.write_rows(layer_cache, kk, vv, pos, active)
            y = codec.attend_rows_causal(q, layer_cache, pos)
            carry = carry + linear(bp["attn"]["proj"],
                                   merge_heads(y.astype(carry.dtype)),
                                   compute_dtype=compute_dtype)
            h = layer_norm(bp["ln_2"], carry, eps=cfg.ln_eps)
            if self.ffn is None:
                m = linear(bp["mlp"]["proj"],
                           gelu(linear(bp["mlp"]["fc"], h,
                                       compute_dtype=compute_dtype)),
                           compute_dtype=compute_dtype)
            else:
                m = self.ffn(bp, h).astype(carry.dtype)
            return carry + m, layer_cache

        x, new_cache = lax.scan(layer, x, (prepared["blocks"], cache))
        logits = head(prepared, x.astype(jnp.float32), cfg=cfg,
                      compute_dtype=compute_dtype)
        return logits, new_cache

    def decode_rows(self, prepared, cache, tok, pos, active, codec):
        """One per-slot decode step: tok/pos/active (B,) ->
        (logits (B, V), cache)."""
        cfg, compute_dtype = self.cfg, self.compute_dtype
        x = jnp.take(prepared["wte"]["embedding"], tok[:, None], axis=0) + \
            prepared["wpe"]["embedding"][pos][:, None, :]
        if compute_dtype is not None:
            x = x.astype(compute_dtype)

        def layer(carry, layer_in):
            bp, layer_cache = layer_in
            y, layer_cache = _decode_block_rows(
                bp, carry, layer_cache, pos, active, cfg=cfg,
                compute_dtype=compute_dtype, codec=codec, ffn=self.ffn,
            )
            return y, layer_cache

        x, new_cache = lax.scan(layer, x, (prepared["blocks"], cache),
                                unroll=cfg.n_layer if self.unroll_layers
                                else 1)
        logits = head(prepared, x.astype(jnp.float32), cfg=cfg,
                      compute_dtype=compute_dtype)
        return logits[:, -1], new_cache


class ContinuousBatcher:
    """Slot-pool decode server. `slots` concurrent sequences over one
    static cache of `max_len` positions; prompts prefill in
    `prompt_pad`-sized chunks (one prefill compilation for all requests,
    any prompt length).

    Usage:
        srv = ContinuousBatcher(cfg, prepared, slots=4, max_len=96)
        rid = srv.submit(prompt_ids, max_new_tokens=32)   # needs a free slot
        srv.step()       # every active slot advances one token
        srv.drain()      # run to completion -> {rid: np.ndarray tokens}
    """

    # class-level capability: variants that commit >1 token per step
    # (SpeculativeBatcher) override this to False — per-token grammar
    # masks cannot gate a verified chunk. A class attribute (not an
    # instance flag set around super().__init__) so there is no
    # initialization-order hazard to refactor away.
    _constraints_ok = True

    def __init__(self, cfg: GPTConfig, prepared, *, slots: int = 4,
                 max_len: Optional[int] = None, prompt_pad: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, min_p: Optional[float] = None,
                 repetition_penalty: Optional[float] = None,
                 compute_dtype=None, eos_id: Optional[int] = None, seed: int = 0,
                 ffn=None, kv_dtype=None, family=None,
                 attn_kernel="auto", prefix_cache: int = 0,
                 decode_buckets=False,
                 logprobs_k: int = 0,
                 kv: Optional[str] = None,
                 paged_blocks: int = 0, block_len: int = 16,
                 lora_adapters=None, lora_alphas=None,
                 allow_logit_bias: bool = False,
                 allow_constraints: bool = False,
                 constraint_rows: int = 1024,
                 unroll_layers: bool = False,
                 prefill_chunk_tokens: int = 0,
                 overlap: bool = False):
        self.cfg = cfg
        self.prepared = prepared
        self.slots = slots
        # multi-LoRA serving: `lora_adapters` is a list of adapter trees
        # (lora.init_lora/load_lora against THIS prepared layout); each
        # request picks one by index at submit(adapter=i) or serves the
        # base model by default. One set of base weights, per-slot
        # low-rank deltas applied inside ops.nn.linear via a param VIEW
        # (lora.lora_view) — the compiled step programs are shared by
        # every adapter mix.
        self._lora = None
        self._n_adapters = 0
        if lora_adapters:
            from dnn_tpu.lora import stack_loras, transpose_lora_stack

            # transpose layer-stacked slabs to scan order ONCE — per-view
            # construction below is then pure host dict surgery
            self._lora = transpose_lora_stack(
                stack_loras(list(lora_adapters), alphas=lora_alphas))
            self._n_adapters = len(lora_adapters)
        self._aid = np.zeros((slots,), np.int32)  # 0 = base model
        self._decode_view = None
        self._pf_views: dict = {}  # aid -> memoized single-row prefill view
        self.max_len = min(max_len or cfg.block_size, cfg.block_size)
        self.prompt_pad = prompt_pad or min(64, self.max_len)
        self.eos_id = eos_id
        self._seed = seed
        # constructor values become the per-request DEFAULTS; submit() may
        # override any of them per request (per-slot parameter vectors
        # below — same compiled step program for every mix)
        self._default_temp = float(temperature)
        self._default_topk = int(top_k) if top_k else 0
        self._default_topp = float(top_p) if top_p else 0.0
        self._default_minp = float(min_p) if min_p else 0.0
        self._default_rep = (float(repetition_penalty)
                             if repetition_penalty else 1.0)
        # logprobs_k > 0 compiles the step/finish programs to also emit
        # the chosen token's logprob + the top-k (ids, logprobs) per step;
        # a CONSTRUCTION-time choice so the program count stays fixed
        self._logprobs_k = int(logprobs_k)
        # `family` supplies the model-specific cache/prefill/decode hooks
        # (default: the GPT block family; LLaMA passes LlamaFamilyRows).
        # With an explicit family, the model math runs at the FAMILY's
        # compute_dtype — a diverging batcher-level knob would silently
        # lose, so it is rejected, and the cache default follows the
        # family's dtype.
        if family is not None:
            if ffn is not None:
                raise ValueError(
                    "pass ffn on the family adapter, not alongside family=")
            if attn_kernel not in ("auto", False):
                raise ValueError(
                    "pass attn_kernel on the family adapter, not alongside "
                    "family= (the adapter owns its attention path)")
            if unroll_layers:
                raise ValueError(
                    "pass unroll_layers on the family adapter, not "
                    "alongside family= (the adapter owns its layer scan)")
            fam_dtype = getattr(family, "compute_dtype", None)
            if compute_dtype is not None and fam_dtype != compute_dtype:
                raise ValueError(
                    f"compute_dtype mismatch: batcher={compute_dtype} vs "
                    f"family adapter={fam_dtype} — set it on the adapter")
            compute_dtype = fam_dtype
        self.family = family or GPTFamilyRows(
            cfg, compute_dtype=compute_dtype, ffn=ffn,
            attn_kernel=attn_kernel, unroll_layers=unroll_layers)
        # kv_dtype picks the cache storage codec (None follows
        # compute_dtype; "int8" = quantized cache, kvcache.Int8KV)
        cache_dtype = kv_dtype if kv_dtype is not None else (compute_dtype or jnp.float32)
        self._cache_dtype = cache_dtype

        # `kv` picks the cache layout by NAME — the serving-path selector
        # ("--kv=paged|dense" at the daemon edge):
        #   * None (legacy): paged iff paged_blocks > 0 (the pre-flag
        #     contract, kept for direct constructors and old tests);
        #   * "dense": the per-slot dense pool, rejecting a contradictory
        #     paged_blocks;
        #   * "paged": the block pool; paged_blocks=0 auto-sizes it to
        #     the dense pool's capacity (slots x max_len positions + the
        #     reserved junk block), so flipping the flag never shrinks
        #     admission capacity — it only adds block-granular packing;
        #   * "auto" (the LMServer default): "paged" whenever this
        #     configuration can page, else the dense fallback — recorded
        #     as a `kv_fallback_dense` flight event so the operator can
        #     see WHY the default didn't engage.
        if kv not in (None, "dense", "paged", "auto"):
            raise ValueError(
                f"kv must be 'paged', 'dense' or 'auto', got {kv!r}")
        if kv == "dense" and paged_blocks:
            raise ValueError(
                "kv='dense' contradicts paged_blocks="
                f"{paged_blocks}; drop one of them")
        if kv in ("paged", "auto"):
            blocker = None
            if decode_buckets:
                blocker = ("decode_buckets is a dense-pool feature (the "
                           "paged pool is already length-proportional)")
            elif (getattr(self.family, "softcap", None) is not None
                    or getattr(self.family, "alt_window", False)):
                blocker = ("softcapped / alternating-window families "
                           "have no paged channel")
            elif (getattr(self.family, "window", None) is not None
                    and prefix_cache > 0):
                blocker = ("windowed paged pools do not compose with "
                           "the prefix cache")
            elif self.max_len % block_len or self.prompt_pad % block_len:
                blocker = (f"max_len {self.max_len} / prompt_pad "
                           f"{self.prompt_pad} must tile block_len "
                           f"{block_len}")
            if blocker is None:
                if not paged_blocks:
                    paged_blocks = slots * (self.max_len // block_len) + 1
            elif kv == "paged" or paged_blocks:
                # an explicit paged_blocks is an explicit ask for the
                # pool — silently discarding its sizing on the auto path
                # would swap the cache layout under a misconfigured
                # deployment that used to fail loud here
                raise ValueError(
                    f"kv={kv!r}"
                    + (f" with paged_blocks={paged_blocks}" if paged_blocks
                       else "")
                    + f" is not available: {blocker}")
            else:  # auto, nothing explicit: dense fallback, visibly
                obs.flight.record("kv_fallback_dense", reason=blocker)

        # device state (functional updates). paged_blocks > 0 swaps the
        # per-slot dense cache for the shared block pool + per-slot block
        # tables (runtime/paged_kvcache.py): admission is then by ACTUAL
        # request length (sum of blocks), not slots x max_len.
        self._paged = int(paged_blocks) > 0
        # decode bucketing (runtime/decode_buckets.py): the dense pool is
        # allocated at the smallest ladder bucket covering the longest
        # LIVE position and grown bucket-by-bucket as sequences advance,
        # so decode bytes/step track the pool's live context instead of
        # the max_len allocation. Opt-in (`decode_buckets=True` for the
        # power-of-two ladder, or an explicit ascending tuple): a
        # bucketed pool compiles its three programs once PER LIVE BUCKET
        # — a bounded relaxation of the three-program contract.
        self._buckets = None
        self._cache_len = self.max_len
        if decode_buckets:
            if self._paged:
                raise ValueError(
                    "decode_buckets applies to the dense per-slot cache; "
                    "the paged pool is already length-proportional "
                    "(blocks held track actual request length)")
            from dnn_tpu.runtime.decode_buckets import (
                bucket_ladder, normalize_ladder, pad_cache_to,
            )

            self._buckets = (bucket_ladder(self.max_len)
                             if decode_buckets is True
                             else normalize_ladder(decode_buckets,
                                                   self.max_len))
            self._cache_len = self._buckets[0]
            # no donation: a pad's output never fits the input buffer
            self._grow_cache = jax.jit(pad_cache_to, static_argnums=(1,))
        self._allocator = None
        self._paged_window = None
        if self._paged:
            fam_window = getattr(self.family, "window", None)
            if (getattr(self.family, "softcap", None) is not None
                    or getattr(self.family, "alt_window", False)):
                raise ValueError(
                    "softcapped / alternating-window families are not "
                    "supported with the paged pool (PagedKV has no "
                    "softcap or per-layer window channel; use the dense "
                    "per-slot cache)")
            if fam_window is not None and prefix_cache > 0:
                raise ValueError(
                    "windowed paged pools do not compose with the prefix "
                    "cache: rolled-out blocks are reclaimed mid-request, "
                    "which would free blocks a prefix entry still shares "
                    "— serve windowed families with prefix_cache=0")
            self._paged_window = fam_window
            from dnn_tpu.runtime.paged_kvcache import (
                BlockAllocator, PagedKV, init_paged_cache,
            )

            if self.max_len % block_len:
                raise ValueError(
                    f"max_len {self.max_len} must tile block_len "
                    f"{block_len}")
            if self.prompt_pad % block_len:
                raise ValueError(
                    f"prompt_pad {self.prompt_pad} must tile block_len "
                    f"{block_len} (prefill rows install whole blocks)")
            # pool head width follows the FAMILY's cache (GQA families
            # store KV heads — llama.LlamaFamilyRows sets kv_heads)
            self.cache = init_paged_cache(
                cfg, slots, self.max_len, n_blocks=paged_blocks,
                block_len=block_len, dtype=cache_dtype,
                kv_heads=getattr(self.family, "kv_heads", None))
            self._allocator = BlockAllocator(paged_blocks)
            self._block_len = block_len
            # the family's attn_kernel policy routes paged decode through
            # the fused flash-decode kernel (paged_decode_attention): the
            # "auto" ladder rung for block pools — TPU + long slots
            # stream table-chased blocks, everything else stays on the
            # gather_view einsum (PagedKV._kernel_on)
            codec = PagedKV(block_len, window=fam_window,
                            use_kernel=getattr(self.family, "attn_kernel",
                                               False))

            def gather_row(cache, ids_row):
                """Rebuild a transient prefill row from pool blocks (the
                prefix-hit path: remaining chunks attend the shared
                prefix through this row). Junk beyond the prefix is never
                attended (chunk attention masks at its positions).
                Rank-agnostic: K/V blocks (…, bp, D) and int8 scale
                blocks (…, bp) alike."""
                out = {}
                for kk in cache:
                    if kk == "tables":
                        continue
                    g = jnp.take(cache[kk], ids_row, axis=1)
                    l_, nb, h, bl = g.shape[:4]  # (L, nb_max, H, bp[, D])
                    rest = g.shape[4:]
                    r = jnp.moveaxis(g, 1, 2).reshape(l_, h, nb * bl, *rest)
                    pad = self._row_len - nb * bl
                    if pad:
                        r = jnp.pad(r, [(0, 0), (0, 0), (0, pad)]
                                    + [(0, 0)] * len(rest))
                    out[kk] = r[:, None]  # (L, 1, H, row_len[, D])
                return out

            self._gather_row = jax.jit(gather_row)
        else:
            self.cache = self.family.init_cache(slots, self._cache_len,
                                                cache_dtype)
            use_k = getattr(self.family, "attn_kernel", False)
            if self._buckets is not None and use_k == "auto":
                # bucketing IS the length-aware path: letting "auto"
                # switch einsum -> kernel when the pool grows past
                # AUTO_KERNEL_MIN_S would change attention
                # implementations mid-stream and break the bucketed==
                # unbucketed token-identity contract
                use_k = False
            codec = codec_for_cache(
                self.cache,
                use_kernel=use_k,
                window=getattr(self.family, "window", None),
                softcap=getattr(self.family, "softcap", None))
        self.pos = jnp.zeros((slots,), jnp.int32)      # next write position
        self.tok = jnp.zeros((slots,), jnp.int32)      # last sampled token
        self.active = jnp.zeros((slots,), bool)
        # per-slot rng keys: each request's stream derives from
        # (server seed, request id) alone — pool-independent sampling
        self.keys = jnp.zeros((slots, 2), jnp.uint32)
        # per-slot sampling parameters (set at submit; plain dynamic args
        # of the one decode program — no recompiles across mixes)
        self._temp = jnp.zeros((slots,), jnp.float32)
        self._topk = jnp.zeros((slots,), jnp.int32)
        self._topp = jnp.zeros((slots,), jnp.float32)
        self._minp = jnp.zeros((slots,), jnp.float32)
        self._rep = jnp.ones((slots,), jnp.float32)  # 1.0 = no penalty
        # per-slot vocabulary seen-mask for the repetition penalty: prompt
        # tokens scatter in at submit, each committed token per step.
        # slots x V bools — trivial next to one block of K/V
        self._seen = jnp.zeros((slots, cfg.vocab_size), bool)
        # per-slot additive logit bias (OpenAI-style force/ban) — a
        # CONSTRUCTION-time capability like logprobs_k: the dense
        # (slots, V) buffer and its per-step add only exist when
        # allow_logit_bias=True (at large-vocab, many-slot servers the
        # buffer alone is tens of MB), so the default programs/memory
        # are unchanged. The LM daemon enables it (its clients choose
        # options per request).
        self._allow_user_bias = bool(allow_logit_bias)
        self._allow_constraints = bool(allow_constraints)
        self._allow_bias = self._allow_user_bias
        self._bias = (jnp.zeros((slots, cfg.vocab_size), jnp.float32)
                      if self._allow_bias
                      else jnp.zeros((slots, 0), jnp.float32))
        # constrained decoding (runtime/constrain.TokenConstraint) rides
        # DEVICE-RESIDENT table pools: each grammar uploads ONCE into
        #   * `_ctable` (S, V) bool mask rows — what the decode program
        #     gathers per slot to ban off-grammar logits (row 0 reserved
        #     all-True = unconstrained), and
        #   * `_ctrans` (S, V) int32 next-state rows in GLOBAL pool
        #     coordinates — the DFA walk itself, so the decode program
        #     advances each slot's state `crow' = ctrans[crow, sampled]`
        #     in the same dispatch that sampled the token (row 0 all-zero
        #     = the unconstrained self-loop).
        # The per-slot state vector `_crow` is CARRIED DEVICE STATE,
        # donated through the step exactly like pos/tok/keys — there is
        # NO per-step host->device constraint traffic at all, which is
        # what lets constrained requests ride the interleaved/overlap
        # hot path (the host still mirrors the walk per committed token
        # for finish detection, off the dispatch critical path).
        # `constraint_rows` bounds both pools (bytes: rows x vocab x 1
        # bool + rows x vocab x 4 int32 — 1024 x 50257 ≈ 51 + 206 MB);
        # entries are refcounted by live slots, evicted LRU when
        # unreferenced.
        self._ctab_rows = int(constraint_rows) if self._allow_constraints \
            else 0
        if self._allow_constraints:
            if self._ctab_rows < 2:
                raise ValueError(
                    f"constraint_rows must be >= 2, got {constraint_rows}")
            self._ctable = jnp.ones(
                (self._ctab_rows, cfg.vocab_size), jnp.bool_)
            self._ctrans = jnp.zeros(
                (self._ctab_rows, cfg.vocab_size), jnp.int32)
            from collections import OrderedDict as _OD

            # id(constraint) -> {"off", "n", "refs", "c"} in LRU order
            self._ctab_entries: dict = _OD()
        else:
            self._ctable = jnp.ones((1, 0), jnp.bool_)
            self._ctrans = jnp.zeros((1, 0), jnp.int32)
        self._crow = jnp.zeros((slots,), jnp.int32)

        # host bookkeeping
        self._next_rid = 0
        self._slot_req: List[Optional[dict]] = [None] * slots
        # observability (dnn_tpu/obs): windowed tokens/sec for the
        # serving.tokens_per_sec gauge; all per-step bookkeeping below is
        # gated on obs.metrics() so DNN_TPU_OBS=off costs one None check
        self._tps = Throughput()
        self._bucket_keys: Dict[int, str] = {}
        # live goodput accounting (obs/goodput.GoodputTracker): fed from
        # the same obs-gated blocks as the series above, so it costs one
        # attribute read when unset and nothing when the gate is off.
        # Set post-construction (`pool.goodput = tracker`) — LMServer
        # auto-builds one from its model config.
        self.goodput = None
        # step-timeline attribution (obs/timeline.StepClock): splits
        # every decode step into named phases (admit/host/dispatch/
        # wait/commit/obs) for the /stepz endpoint and the item-4
        # host-serialization ratchet. Attached post-construction like
        # goodput (`pool.step_clock = StepClock().install()` — LMServer
        # auto-builds one); unset it costs one attribute read per step,
        # and the clock itself gates on DNN_TPU_OBS (begin() returns
        # None when off).
        self.step_clock = None
        # live slots holding a grammar constraint — pushed to the
        # StepClock's constrained_slots gauge at admit/retire (one attr
        # store per transition, nothing per step)
        self._n_constrained = 0
        # scrape-time callable gauges, (re-)registered with every bulk
        # update below: the most recently ACTIVE pool owns the series —
        # a once-only registration would let a dead pool keep reporting,
        # and would never recover from a registry clear(). WEAKLY bound:
        # the process-global registry must not pin a closed pool (and
        # its slots x max_len KV cache) for the process lifetime — a
        # collected pool's gauges read 0, which is what "no pool" means.
        import weakref

        pool_ref = weakref.ref(self)

        def _weak_gauge(method_name):
            def read():
                pool = pool_ref()
                return getattr(pool, method_name)() if pool is not None \
                    else 0.0
            return read

        self._obs_gauges = {
            "serving.tokens_per_sec": _weak_gauge("_tps_read"),
            "serving.batch_occupancy": _weak_gauge("_occupancy_read"),
            "serving.kv_slot_utilization": _weak_gauge("_kv_util_read"),
            # memory watermarks (obs/mem.py naming): "how close did the
            # pool come to full" survives the burst that set it
            "serving.kv_live_positions_high_water":
                _weak_gauge("_kv_live_hw_read"),
            "serving.active_slots_high_water":
                _weak_gauge("_active_hw_read"),
            # allocated KV bytes, QUANTIZATION-AWARE: int8 payloads price
            # at 1 byte/element and int4 at their packed HALF byte (plus
            # the f32 scale leaves, which ride the same pytree) — an
            # itemsize walk would overstate an int4 pool 2x
            # (obs/mem.logical_nbytes owns the dtype pricing)
            "serving.kv_cache_bytes": _weak_gauge("_kv_bytes_read"),
        }
        self._kv_live_hw = 0
        self._active_hw = 0
        # step-obs accumulator (see _obs_flush): the per-step registry
        # bulk (lock + counter updates + reservoir + gauge check) was
        # the single largest line in the obs_overhead bill, so steps
        # batch into plain fields and land every _OBS_FLUSH_STEPS
        # steps, on a bucket switch, or when the pool goes idle (end
        # of every drain — tests and scrapes that look after traffic
        # see exact totals). Producer-thread only, no locks.
        self._obs_acc_steps = 0
        self._obs_acc_tokens = 0
        self._obs_acc_bk: Optional[str] = None
        self._obs_acc_samples: list = []
        self._pool_exhausted_episode = False  # latch: one flight event /
        # counter tick per shortage episode, cleared when blocks return
        # to the pool (retire/cancel/window reclaim) or a paged admission
        # succeeds — NOT only on re-admission of the held request, which
        # never happens if its caller deadline-cancels while held
        if self._paged:
            self._obs_gauges.update({
                "serving.paged_blocks_used": _weak_gauge("_paged_used_read"),
                "serving.paged_blocks_free": _weak_gauge("_paged_free_read"),
                "serving.paged_blocks_high_water":
                    _weak_gauge("_paged_hw_read"),
            })
        self.results: Dict[int, np.ndarray] = {}
        self.finish_reasons: Dict[int, str] = {}
        self.token_logprobs: Dict[int, dict] = {}

        # prefix cache (`prefix_cache` = capacity; 0 disables). Two
        # implementations by cache layout:
        #   * DENSE pools: the legacy exact-prefix LRU (OrderedDict
        #     keyed on the token bytes of every completed full-chunk
        #     boundary; values are COPIES of the transient row — the
        #     live row is donated through the chunk loop);
        #   * PAGED pools: the RADIX prefix store (dnn_tpu/kvtier) — a
        #     trie over block_len token chunks mapped onto the shared
        #     BlockAllocator. Longest-prefix-match returns a run of
        #     refcounted physical blocks (copy-free sharing), mid-block
        #     divergence copy-on-writes ONLY the boundary block, and
        #     eviction is leaf-LRU under refcount protection; capacity
        #     counts resident BLOCKS. The store is also the fleet
        #     tier's substrate: stage_prefix/kvtier_export/kvtier_adopt
        #     below move its blocks between replicas (kvtier/migrate).
        # Either way: same compiled admission programs — hits/puts are
        # host bookkeeping + block-sized device work, never new shapes.
        # LoRA caveat (paged): radix entries are base-model KV and the
        # trie has no adapter axis, so adapted submissions on a PAGED
        # pool run UNCACHED (full prefill every time) — a documented
        # regression vs the removed per-adapter paged LRU; adapter-
        # heavy prefix workloads should serve dense pools, whose LRU
        # still keys by (adapter, tokens).
        from collections import OrderedDict

        self._prefix_store = None
        self._prefix_cache: "Optional[OrderedDict]" = None
        if prefix_cache > 0:
            if self._paged:
                from dnn_tpu.kvtier.store import PrefixStore

                self._prefix_store = PrefixStore(
                    self._allocator, self._block_len, prefix_cache)
            else:
                self._prefix_cache = OrderedDict()
        self._prefix_cap = prefix_cache
        self.prefix_hits = 0       # submissions that reused >= 1 chunk
        self.prefix_misses = 0     # lookups that reused nothing (the
        # denominator half serving.prefix_hits_total always lacked — a
        # hit counter alone can't say whether 100 hits is a hot cache
        # or a rounding error against 1e6 misses)
        self.prefix_evictions = 0
        self.prefill_chunks_run = 0  # chunk programs actually executed
        if self._prefix_cache is not None or self._prefix_store is not None:
            # scrape-time effectiveness ratio (ROADMAP item 2's metric):
            # hits / (hits + misses) over the pool's lifetime, weakly
            # bound like every pool gauge
            self._obs_gauges["dnn_tpu_prefix_hit_ratio"] = _weak_gauge(
                "_prefix_ratio_read")
        if self._prefix_store is not None:
            # KV-tier residency + cross-replica effectiveness: resident
            # radix blocks, and the fraction of block-granular hits
            # served from ADOPTED (migrated-in) blocks — the fleet
            # tier's whole point, asserted by benchmarks/kv_tier_probe
            self._obs_gauges["dnn_tpu_kvtier_blocks"] = _weak_gauge(
                "_kvtier_blocks_read")
            self._obs_gauges["dnn_tpu_kvtier_remote_hit_ratio"] = \
                _weak_gauge("_kvtier_remote_ratio_read")
        # memory-economy observatory (obs/kvlens.py): reuse-distance
        # sampling + miss-ratio curves + block-lifetime forensics over
        # the radix store. Attached only when the obs gate is ON at
        # construction — a gate-off process pays exactly one
        # `lens is not None` check per store hook. The lens itself
        # re-checks the gate per call, so runtime flips (the overhead
        # probe's on/off interleave) stop recording immediately.
        self._kvlens = None
        if self._prefix_store is not None and obs.enabled():
            from dnn_tpu.obs.kvlens import KVLens

            try:
                per_block = int(self._kv_bytes_read()) // max(
                    1, self._allocator.n_blocks)
            except Exception:  # noqa: BLE001 — pricing is advisory
                per_block = 0
            # curve axis = the EFFECTIVE pool: with auto-sized
            # paged_blocks the allocator (minus the reserved null
            # block) can be smaller than the prefix_cache knob, and
            # the allocator is what actually bounds residency — a 1x
            # label pinned to the nominal knob would mis-scale every
            # multiplier
            eff_pool = min(int(prefix_cache),
                           self._allocator.n_blocks - 1)
            self._kvlens = KVLens(
                eff_pool, self._block_len, seed=seed,
                bytes_per_block=per_block)
            self._prefix_store.lens = self._kvlens
            # curve + thrash as weak scrape-time gauges next to the
            # kvtier residency pair (fleet.py rolls these up per stage)
            self._obs_gauges.update(self._kvlens.prom_gauges())

        logprobs_k = self._logprobs_k

        def _lp_outputs(logits, chosen):
            """(chosen logprob (B,), top-k logprobs (B, K), ids (B, K))
            from the step's logits — only compiled in when the server was
            constructed with logprobs_k > 0."""
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            chosen_lp = jnp.take_along_axis(lsm, chosen[:, None], axis=-1)[:, 0]
            top_lp, top_ids = lax.top_k(lsm, logprobs_k)
            return chosen_lp, top_lp, top_ids.astype(jnp.int32)

        def _decode_core(prepared, cache, pos, tok, active, keys,
                         temp, tk, tp, mp, rep, seen, bias, crow, ctable,
                         ctrans):
            """Advance every active slot one token (per-slot sampling
            parameters — see _sample_rows; `rep`/`seen` drive the
            repetition penalty, `mp` the min-p cutoff, `bias` (B, V) the
            per-slot additive logit bias, `crow` (B,) the per-slot
            constraint-table row index into the device-resident bool
            mask pool `ctable` — row 0 is the reserved all-allowed
            row, so unconstrained slots add nothing). The grammar walk
            happens HERE too: `ctrans` holds each grammar's next-state
            rows in global pool coordinates, so the step returns
            `crow' = ctrans[crow, sampled]` as donated carried state —
            no host sync between steps, which is what admits
            constrained requests to the interleaved/overlap hot path.
            Shared by the plain decode step and the MIXED step (decode
            + one interleaved prefill chunk in the same compiled
            program), so the two paths' decode math is identical by
            construction — the mixed==convoy token-parity contract."""
            logits, new_cache = self.family.decode_rows(
                prepared, cache, tok, pos, active, codec)
            # repetition penalty on raw logits (HF order: before the
            # temperature/filters inside _sample_rows); rows at the
            # neutral 1.0 pass through bit-identically. ONE formula for
            # solo and pool paths: generate.apply_repetition_penalty
            b = logits.shape[0]
            rp_on = rep != 1.0
            lg = apply_repetition_penalty(
                logits, rp_on[:, None] & seen, rep[:, None])
            if self._allow_bias:
                lg = lg + bias
            if self._allow_constraints:
                lg = jnp.where(ctable[crow], lg, _NEG_BIG)
            # advance each slot's own stream; sample each row with its key
            split = jax.vmap(jax.random.split)(keys)  # (B, 2, 2)
            new_keys, subs = split[:, 0], split[:, 1]
            # inactive slots sample greedy (result discarded below): a
            # RETIRED sampled request's stale temperature must not keep
            # an otherwise-greedy pool on the filtered-sampling branch
            nxt = _sample_rows(lg, subs,
                               temperature=jnp.where(active, temp, 0.0),
                               top_k=tk, top_p=tp, min_p=mp)
            nxt = jnp.where(active, nxt, tok)
            new_keys = jnp.where(active[:, None], new_keys, keys)
            seen_upd = seen.at[jnp.arange(b), nxt].set(True)
            new_seen = jnp.where(active[:, None], seen_upd, seen)
            if self._allow_constraints:
                # device DFA walk: self-loop closure (trans_table) makes
                # the gather total over masked-off tokens AND eos, so a
                # stale overlap step replays to the same state
                new_crow = jnp.where(active, ctrans[crow, nxt], crow)
            else:
                new_crow = crow
            out = (new_cache, pos + active.astype(jnp.int32), nxt, new_keys,
                   new_seen, new_crow)
            if logprobs_k:
                # logprobs report the MODEL's distribution (pre-penalty,
                # pre-temperature — the usual serving-API convention)
                out += _lp_outputs(logits, nxt)
            return out

        def decode_step(prepared, cache, pos, tok, active, keys,
                        temp, tk, tp, mp, rep, seen, bias, crow, ctable,
                        ctrans):
            return _decode_core(prepared, cache, pos, tok, active, keys,
                                temp, tk, tp, mp, rep, seen, bias, crow,
                                ctable, ctrans)

        def mixed_step(prepared, pf_prepared, cache, pos, tok, active,
                       keys, temp, tk, tp, mp, rep, seen, bias, crow,
                       ctable, ctrans, row, chunk, chunk_start):
            """One INTERLEAVED step (ISSUE 12): the decode leg advances
            every active slot exactly as decode_step, and the same
            compiled program folds one prompt chunk of an admitting
            request into its transient row cache — admission rides the
            decode cadence instead of convoying it behind a separate
            prefill program. The legs touch disjoint buffers (pool
            cache vs transient row), so the decode math — and every
            slot's token stream — is bit-identical to the convoy path.
            `pf_prepared` is the admitting request's prefill param view
            (its LoRA adapter when multi-LoRA is on; the same tree as
            `prepared` otherwise)."""
            out = _decode_core(prepared, cache, pos, tok, active, keys,
                               temp, tk, tp, mp, rep, seen, bias, crow,
                               ctable, ctrans)
            pf_logits, new_row = self.family.prefill(
                pf_prepared, chunk, row, chunk_start)
            return out + (pf_logits, new_row)

        def prefill_chunk(prepared, row, chunk, chunk_start):
            """One (1, prompt_pad) chunk of a prompt into the slot-row
            cache at positions [chunk_start, chunk_start+P). Long prompts
            loop this (full chunks + one padded tail) — ONE compiled
            program for any prompt length. Pad positions in the tail write
            K/V that the per-row position mask never attends."""
            return self.family.prefill(prepared, chunk, row, chunk_start)

        def prefill_finish(cache, row, logits, last_local, slot, rng,
                           temp, tk, tp, mp, rep, seen_row, bias_row,
                           install_ids, crow, ctable):
            """Sample the first token from the final chunk's true-last
            logit row and install the finished row cache into `slot`.
            `seen_row` (V,) marks the prompt's tokens so the repetition
            penalty applies to the FIRST sample too. `install_ids` (paged
            mode): the per-logical-block physical install targets — shared
            prefix blocks routed to junk block 0 (dense mode receives an
            empty placeholder). `crow` (scalar) indexes this request's
            start-state row in the constraint mask pool (0 =
            unconstrained) so the FIRST token obeys the grammar too."""
            lg = logits[:, last_local][0:1]  # (1, V)
            raw = lg
            lg = apply_repetition_penalty(
                lg, (rep != 1.0) & seen_row[None, :], rep)
            if self._allow_bias:
                lg = lg + bias_row[None, :]
            if self._allow_constraints:
                lg = jnp.where(ctable[crow][None, :], lg, _NEG_BIG)
            first = _sample_rows(
                lg, rng[None], temperature=temp[None], top_k=tk[None],
                top_p=tp[None], min_p=mp[None],
            )[0]
            # the row cache is chunk-rounded (possibly > the pool); only
            # the pool's own position count installs — the overhang holds
            # nothing but tail-pad garbage (real prompt tokens always fit:
            # submit() bounds the prompt by max_len and, on a bucketed
            # pool, grows the pool past the prompt before finishing)
            if self._paged:
                cache = codec.install_row(cache, row, install_ids)
            else:
                cache = install_dense_row(cache, row, slot)
            if logprobs_k:
                # raw model distribution, as in decode_step
                return (cache, first) + _lp_outputs(raw, first[None])
            return cache, first

        # the transient slot-row cache rounds max_len UP to whole chunks:
        # a tail chunk starting at (n_chunks-1)*prompt_pad must never have
        # its write clamped back onto real prompt positions (dynamic
        # updates clamp silently — an unrounded row corrupts the cache
        # whenever max_len % prompt_pad != 0)
        self._row_len = -(-self.max_len // self.prompt_pad) * self.prompt_pad
        self._new_row = lambda: self.family.init_cache(1, self._row_len, cache_dtype)
        # donate the caches: without aliasing, every token would copy the
        # whole (L, B, H, S, D) cache (hundreds of MB of HBM traffic per
        # step at real sizes). The call sites reassign from the results,
        # so the donated inputs are never reused. Alongside the cache:
        # every per-slot state vector the step RETURNS (pos, tok, keys,
        # seen — and `crow` on constrained servers, where the DFA walk
        # makes it carried device state) — `active`, `bias`, `ctable`
        # and `ctrans` are read-only through the step (host-updated
        # between calls) and must NOT be donated; on UNconstrained
        # servers `crow` is a read-only pass-through too (the core
        # returns it untouched), so donating it would be an un-aliasable
        # copy. Full aliasing of every donated leaf is a standing
        # invariant, asserted statically by the analysis gate
        # (dnn_tpu/analysis/program.audit_serving_decode via
        # hlo_audit.count_aliased).
        self._decode_donate = (1, 2, 3, 5, 11) + (
            (13,) if self._allow_constraints else ())
        self._decode = jax.jit(decode_step,
                               donate_argnums=self._decode_donate)
        self._prefill_chunk = jax.jit(prefill_chunk, donate_argnums=(1,))
        # the transient row (arg 1) is SLICED into the pool, never
        # returned whole — donating it aliases nothing (an unusable
        # donation that warned on every prefill); only the pool cache
        # donation is real
        self._prefill_finish = jax.jit(prefill_finish, donate_argnums=(0,))

        # KV-tier device programs (dnn_tpu/kvtier) — only compiled-in
        # when the radix store is on:
        #   * _cow_copy: the copy-on-write boundary — duplicate ONE
        #     physical block (all leaves: K/V and, on quantized pools,
        #     their scale blocks) so a divergent request can extend a
        #     shared prefix mid-block without scribbling the original;
        #   * _kv_put_block: block-granular ingest for migration — one
        #     migrated block's leaves scattered at a physical id;
        #   * _kvtier_install: install a staged transient row into pool
        #     blocks WITHOUT a slot (stage_prefix: the prefill-replica
        #     half of block migration computes KV straight into the
        #     store; no finish, no sampling, no slot scatter). The row
        #     is sliced per block, never returned whole — only the pool
        #     cache donation is real (the prefill_finish lesson).
        self._cow_copy = None
        self._kv_put_block = None
        self._kvtier_install = None
        self._kv_get_block = None
        if self._prefix_store is not None:
            def cow_copy(cache, src, dst):
                out = {"tables": cache["tables"]}
                for kk in cache:
                    if kk != "tables":
                        out[kk] = cache[kk].at[:, dst].set(
                            cache[kk][:, src])
                return out

            def kv_put_block(cache, vals, dst):
                out = {"tables": cache["tables"]}
                for kk in cache:
                    if kk != "tables":
                        out[kk] = cache[kk].at[:, dst].set(
                            vals[kk].astype(cache[kk].dtype))
                return out

            def kvtier_install(cache, row, install_ids):
                return codec.install_row(cache, row, install_ids)

            def kv_get_block(cache, idx):
                # read-only (no donation): one block's leaves, int4
                # widened to int8 values for the host trip
                out = {}
                for kk in cache:
                    if kk != "tables":
                        x = lax.dynamic_index_in_dim(
                            cache[kk], idx, axis=1, keepdims=False)
                        if x.dtype == jnp.int4:
                            x = x.astype(jnp.int8)
                        out[kk] = x
                return out

            self._cow_copy = jax.jit(cow_copy, donate_argnums=(0,))
            self._kv_put_block = jax.jit(kv_put_block,
                                         donate_argnums=(0,))
            self._kvtier_install = jax.jit(kvtier_install,
                                           donate_argnums=(0,))
            self._kv_get_block = jax.jit(kv_get_block)

        # --------------------------------------------------------------
        # overlap & fusion (ISSUE 12): interleaved chunked prefill + the
        # one-step double-buffered dispatch pipeline
        # --------------------------------------------------------------
        # prefill_chunk_tokens > 0 switches ADMISSION from the convoy
        # path (submit() runs the whole chunk loop + finish inline,
        # stalling every decode slot for the prefill's duration — the
        # 0.54 admit fraction PR 10's StepClock measured) to the MIXED
        # step: submit() only validates, allocates and enqueues, and
        # each subsequent decode step folds ONE prompt chunk of that
        # width into the same compiled program. The fused finish then
        # installs the row, samples the first token ON DEVICE with the
        # request's own params/rng, and scatters the slot state — one
        # dispatch, no per-admit device->host sync (the first token's
        # readback rides the NEXT step's commit).
        self._ilv = int(prefill_chunk_tokens or 0)
        if self._ilv < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0, got "
                f"{prefill_chunk_tokens}")
        if self._ilv:
            if self._ilv > self.max_len:
                raise ValueError(
                    f"prefill_chunk_tokens {self._ilv} exceeds max_len "
                    f"{self.max_len} — a chunk wider than the pool can "
                    "never install")
            if self._paged and self._ilv % self._block_len:
                raise ValueError(
                    f"prefill_chunk_tokens {self._ilv} must tile "
                    f"block_len {self._block_len} (prefill rows install "
                    "whole blocks)")
            # allow_constraints composes with interleaved admission
            # since the DFA walk moved on device: the fused finish masks
            # the first token with the grammar's start row and seeds the
            # slot's crow in-program — no admission-time host walk, no
            # sync. (It used to reject loud here.)
            if self._prefix_cache is not None \
                    or self._prefix_store is not None:
                raise ValueError(
                    "prefill_chunk_tokens does not compose with the "
                    "prefix cache (dense entries are keyed/shaped on "
                    "the convoy path's chunk geometry, and the radix "
                    "store's resume/COW/insert bookkeeping lives on "
                    "the convoy admission path) — prefix-heavy "
                    "workloads keep convoy admission")
        # overlap=True runs a ONE-STEP dispatch pipeline: step() DISPATCHES
        # step N and commits step N-1's tokens, so the host slot loop
        # (commit/obs, and the next admission's bookkeeping) runs while
        # the device executes step N — the dispatch_slack headroom the
        # StepClock measured, actually spent. Tokens surface one step()
        # call later; drain()/flush_overlap() commit the trailing step.
        self._overlap = bool(overlap)
        # allow_constraints composes with the one-step pipeline since
        # the DFA walk moved on device: step N+1's mask row comes from
        # the crow that step N's program computed and carried — never
        # one state stale. The one garbage step dispatched past a
        # retirement replays through trans_table's self-loop closure
        # and is overwritten at commit, like tok/active.
        self._pending_q: List[int] = []   # slots awaiting interleaved
        # prefill, FIFO (one chunk folds per step)
        self._inflight = None             # overlap: the dispatched,
        # not-yet-committed step — (step_idx, token refs, logprob refs)
        self._step_idx = 0                # monotonically counts dispatches;
        # install_step gating keys off it (a slot's decode tokens exist
        # only for steps dispatched AFTER its fused finish)
        # interleaved transient rows round max_len up to whole chunks of
        # the INTERLEAVE width (same clamp-protection argument as
        # _row_len above)
        self._ilv_row_len = (-(-self.max_len // self._ilv) * self._ilv
                             if self._ilv else 0)
        self._ilv_new_row = (
            (lambda: self.family.init_cache(1, self._ilv_row_len,
                                            cache_dtype))
            if self._ilv else None)
        self._mixed = None
        self._ilv_finish = None
        self._ilv_finish_core = None
        if self._ilv:
            # donate the decode leg's state exactly as _decode does, plus
            # the prefill leg's transient row — audited like every other
            # decode program (analysis/program.audit_serving_decode)
            self._mixed_donate = (2, 3, 4, 6, 12, 17) + (
                (14,) if self._allow_constraints else ())
            self._mixed = jax.jit(mixed_step,
                                  donate_argnums=self._mixed_donate)

            def ilv_finish(cache, row, logits, last_local, slot, rng,
                           slot_key, pos, tok, active, keys, temp_v,
                           tk_v, tp_v, mp_v, rep_v, seen, bias_buf,
                           t, k, p, mp_, rp, seen_row, b_row,
                           prompt_len, install_ids, crow, c_row,
                           ctable, ctrans):
                """Fused admission finish: sample the first token from
                the final chunk's true-last logit row (the request's own
                temperature/top-k/top-p/min-p/repetition params and rng
                stream — the same math as the convoy prefill_finish, so
                sampled streams agree draw-for-draw), install the row
                cache into `slot`, and scatter EVERY per-slot state
                vector (pos/tok/active/keys/sampling params/seen/bias
                — and the slot's DFA state: `c_row` (scalar) is the
                grammar's global start row, masking the FIRST token and
                seeding `crow[slot] = ctrans[c_row, first]` on device,
                so constrained interleaved admission never syncs). Only
                the sampled token id (+ logprobs when compiled in) ever
                crosses to host, and even that readback is deferred to
                the next step's commit — admission costs zero blocking
                syncs."""
                lg = logits[:, last_local][0:1]  # (1, V)
                raw = lg
                lg = apply_repetition_penalty(
                    lg, (rp != 1.0) & seen_row[None, :], rp)
                if self._allow_bias:
                    lg = lg + b_row[None, :]
                if self._allow_constraints:
                    lg = jnp.where(ctable[c_row][None, :], lg, _NEG_BIG)
                first = _sample_rows(
                    lg, rng[None], temperature=t[None], top_k=k[None],
                    top_p=p[None], min_p=mp_[None],
                )[0]
                if self._paged:
                    cache = codec.install_row(cache, row, install_ids)
                else:
                    cache = install_dense_row(cache, row, slot)
                pos = pos.at[slot].set(prompt_len)
                tok = tok.at[slot].set(first)
                active = active.at[slot].set(True)
                keys = keys.at[slot].set(slot_key)
                temp_v = temp_v.at[slot].set(t)
                tk_v = tk_v.at[slot].set(k)
                tp_v = tp_v.at[slot].set(p)
                mp_v = mp_v.at[slot].set(mp_)
                rep_v = rep_v.at[slot].set(rp)
                seen = seen.at[slot].set(seen_row.at[first].set(True))
                if self._allow_bias:
                    bias_buf = bias_buf.at[slot].set(b_row)
                if self._allow_constraints:
                    crow = crow.at[slot].set(ctrans[c_row, first])
                out = (cache, pos, tok, active, keys, temp_v, tk_v,
                       tp_v, mp_v, rep_v, seen, bias_buf, crow, first)
                if logprobs_k:
                    out += _lp_outputs(raw, first[None])
                return out

            # the speculative variant composes its own fused finish from
            # this core (serving_spec.SpeculativeBatcher)
            self._ilv_finish_core = ilv_finish
            # donate the pool cache and every returned per-slot vector
            # (active included — the finish RETURNS it, unlike the decode
            # step where it is host-updated between calls); the transient
            # row is sliced, never returned whole (the prefill_finish
            # lesson), the bias buffer only when it is real, and crow
            # only on constrained servers (unconstrained finishes return
            # it untouched — an un-aliasable donation)
            donate = [0, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16]
            if self._allow_bias:
                donate.append(17)
            if self._allow_constraints:
                donate.append(27)
            self._ilv_finish_donate = tuple(donate)
            self._ilv_finish = jax.jit(
                ilv_finish, donate_argnums=self._ilv_finish_donate)

        # the decode step's param argument: a lora_view when multi-LoRA is
        # on (rebuilt whenever a slot's adapter assignment changes — same
        # structure, so the same compiled program), plain prepared when off
        self._decode_view = self._lora_prepared(self._aid)

    def jit_programs(self):
        """The batcher's compiled entry points — what a long-lived server
        counts toward its compile-cache budget (lm_server's
        CompileCacheGuard). Variants with extra programs
        (SpeculativeBatcher) extend this."""
        fns = [self._decode, self._prefill_chunk, self._prefill_finish]
        if self._paged:
            fns.append(self._gather_row)
        if self._buckets is not None:
            fns.append(self._grow_cache)
        if self._mixed is not None:
            fns += [self._mixed, self._ilv_finish]
        if self._prefix_store is not None:
            fns += [self._cow_copy, self._kv_put_block,
                    self._kvtier_install, self._kv_get_block]
        return fns

    # ------------------------------------------------------------------

    def _lora_prepared(self, aids):
        """Param view selecting each row's adapter (lora.lora_view);
        plain prepared when multi-LoRA is off. `aids` indexes the stacked
        adapter axis (0 = the all-zero base adapter)."""
        if self._lora is None:
            return self.prepared
        from dnn_tpu.lora import lora_view

        sel = jax.nn.one_hot(jnp.asarray(aids, jnp.int32),
                             self._n_adapters + 1, dtype=jnp.float32)
        return lora_view(self.prepared, self._lora, sel, transposed=True)

    def _lora_prefill_view(self, aid: int):
        """Memoized single-row prefill view for one adapter id — at most
        N+1 builds over the server's lifetime, then pure dict reuse."""
        if self._lora is None:
            return self.prepared
        view = self._pf_views.get(aid)
        if view is None:
            view = self._lora_prepared(np.asarray([aid], np.int32))
            self._pf_views[aid] = view
        return view

    # ------------------------------------------------------------------

    def free_slots(self) -> int:
        return sum(r is None for r in self._slot_req)

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self._slot_req)

    def submit(self, prompt, max_new_tokens: int,
               seed: Optional[int] = None, *,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               min_p: Optional[float] = None,
               repetition_penalty: Optional[float] = None,
               logit_bias: Optional[dict] = None,
               stop: Optional[list] = None,
               logprobs: bool = False,
               adapter: Optional[int] = None,
               constraint=None, prefilled=None, trace=None) -> int:
        """Prefill `prompt` (1-D int array) into a free slot; returns the
        request id. The first token is sampled during prefill and counts
        toward max_new_tokens. `seed` names the request's private rng
        stream (default: the request id) — a seeded sampled request
        reproduces the same tokens regardless of pool contents or arrival
        order.

        Per-request options (None = the server constructor's defaults;
        the pool mixes them freely within the same compiled programs):
        `temperature` (0 = greedy), `top_k` (clamped to the static
        prefilter width, generate.TOP_P_PREFILTER_K), `top_p` (nucleus),
        `min_p` (drop tokens below min_p x the top probability),
        `repetition_penalty` (HF/CTRL semantics over this request's
        prompt + generated tokens, tracked in a per-slot seen-mask),
        `logit_bias` ({token_id: additive bias} — +big forces, -big
        bans, binding for greedy rows too);
        `stop` — list of token-id sequences: generation retires when the
        emitted stream ends with any of them, the result is trimmed to
        exclude the match, and `finish_reasons[rid]` records "stop"
        (vs "eos" / "length" — the reference has no stop mechanism at
        all, its one forward can't, node.py:137-200); `logprobs=True`
        records the chosen token's logprob and the top-k alternatives per
        step into `token_logprobs[rid]` (server must be constructed with
        logprobs_k > 0); `adapter` — index into the constructor's
        `lora_adapters` list (None = the base model): this request's
        prefill and every decode step apply that adapter's low-rank
        delta while other slots apply theirs; `constraint` — a
        runtime/constrain.TokenConstraint (compiled regex/JSON grammar):
        every emitted token is masked to the grammar's continuations,
        EOS is only reachable in accepting states, and when a match
        completes with no possible continuation the request retires with
        finish_reason "constraint" (server must be constructed with
        allow_constraints=True); `trace` — an obs span (dnn_tpu/obs) to
        parent this request's span tree under: submit records an "admit"
        span with a nested "prefill", and each step maintains a
        per-bucket "decode" span until the request retires. None (the
        default) skips all span work; metrics counters are recorded
        either way when observability is on.

        `prefilled` (disaggregated serving, dnn_tpu/control): a
        PREFILL replica's `export_prefill` payload — this request's
        transient row cache plus the final chunk's true-last logit
        row. Admission then ADOPTS the handed-off KV instead of
        running the chunk loop: same slot install, same
        `_prefill_finish` program, same rng derivation, so tokens
        agree draw-for-draw with a locally-prefilled submission of the
        same seed. Requires matching geometry on both replicas (model
        config, max_len, prompt_pad, kv dtype — mismatches fail loud);
        rejects interleaved admission (`prefill_chunk_tokens` — the
        convoy install path IS the adoption path) and `adapter` (the
        exported row was computed against the prefill replica's base
        weights)."""
        # step-timeline: this submit's whole wall (validation, slot
        # install, prefill chunks, first-token sample) is the "admit"
        # phase, attached to the NEXT step's record in note_admit
        _sc = self.step_clock
        _t_sub = time.perf_counter() if _sc is not None else 0.0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_len {self.max_len}"
            )
        temp = self._default_temp if temperature is None else float(temperature)
        tk = self._default_topk if top_k is None else int(top_k)
        tp = self._default_topp if top_p is None else float(top_p)
        mp = self._default_minp if min_p is None else float(min_p)
        rp = (self._default_rep if repetition_penalty is None
              else float(repetition_penalty))
        if temp < 0:
            raise ValueError(f"temperature must be >= 0, got {temp}")
        if tk < 0:
            raise ValueError(f"top_k must be >= 0, got {tk}")
        if not 0.0 <= tp <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {tp}")
        if not 0.0 <= mp <= 1.0:
            raise ValueError(f"min_p must be in [0, 1], got {mp}")
        if rp <= 0:
            raise ValueError(f"repetition_penalty must be > 0, got {rp}")
        if logit_bias and not self._allow_user_bias:
            raise ValueError(
                "logit_bias requires allow_logit_bias=True at construction "
                "(the per-slot bias buffer is a construction-time choice)")
        if constraint is not None:
            if not self._allow_constraints:
                raise ValueError(
                    "constraint= requires allow_constraints=True at "
                    "construction (the per-slot bias buffer is a "
                    "construction-time choice)")
            if not self._constraints_ok:
                raise ValueError(
                    "this batcher variant commits multiple tokens per "
                    "step and cannot honor per-token constraints")
            if constraint.vocab_size != self.cfg.vocab_size:
                raise ValueError(
                    f"constraint compiled for vocab "
                    f"{constraint.vocab_size} != model vocab "
                    f"{self.cfg.vocab_size}")
            if (self.eos_id is not None
                    and constraint.allowed[constraint.reachable,
                                           self.eos_id].any()):
                # the eos override in mask_row would ban a byte token the
                # grammar NEEDS (and an emitted one would retire as "eos"
                # mid-match) — fail fast instead of either wrong behavior.
                # Quantified over REACHABLE states only: multi-byte (BPE)
                # tokens can jump OVER byte-DFA states, leaving states no
                # token path ever enters — eos aliasing confined to those
                # is harmless. (On single-byte vocabs every state is token
                # -reachable and the quantifier changes nothing: a byte
                # vocab whose eos_id is a grammar-consumable byte is still
                # rejected — use an eos outside the grammar's alphabet,
                # e.g. below ByteTokenizer's offset.)
                raise ValueError(
                    f"eos_id {self.eos_id} maps to bytes this constraint's "
                    "grammar can consume; serve constrained requests with "
                    "a dedicated special token as eos")
        b_row = logit_bias_row(logit_bias, self.cfg.vocab_size)
        if b_row is None:
            b_row = jnp.zeros(
                (self.cfg.vocab_size if self._allow_bias else 0,),
                jnp.float32)
        c_off = None
        if constraint is not None:
            # a grammar matching ONLY the empty string is legal when eos
            # can express it (accepting start + eos override): the first
            # sample is forced to eos and the request retires with a
            # valid empty match
            if not (constraint.allowed[constraint.start].any()
                    or (self.eos_id is not None
                        and constraint.is_accepting(constraint.start))):
                raise ValueError(
                    "constraint permits no first token (empty language "
                    "over this vocab)")
            # upload the grammar's mask table once (pool hit = free);
            # the user's logit_bias rides self._bias unchanged — the
            # device composes bias + table row per step
            c_off = self._ctab_register(constraint)
        tk = min(tk, TOP_P_PREFILTER_K)
        stop_seqs = []
        for s in (stop or []):
            s = np.asarray(s, np.int32).reshape(-1)
            if len(s) == 0:
                raise ValueError("empty stop sequence")
            stop_seqs.append(s)
        if logprobs and not self._logprobs_k:
            raise ValueError(
                "logprobs requested but the server was constructed with "
                "logprobs_k=0")
        aid = 0
        if adapter is not None:
            if self._lora is None:
                raise ValueError(
                    "adapter= requires lora_adapters at construction")
            if not 0 <= int(adapter) < self._n_adapters:
                raise ValueError(
                    f"adapter {adapter} out of range "
                    f"[0, {self._n_adapters})")
            aid = int(adapter) + 1  # stack row 0 is the base model
        if prefilled is not None:
            if self._ilv:
                raise ValueError(
                    "prefilled= does not compose with "
                    "prefill_chunk_tokens: interleaved admission folds "
                    "chunks into decode steps — KV adoption rides the "
                    "convoy install path")
            if adapter is not None:
                raise ValueError(
                    "prefilled= does not compose with adapter=: the "
                    "handed-off row was computed against the prefill "
                    "replica's base weights")
        try:
            slot = self._slot_req.index(None)
        except ValueError:
            raise RuntimeError("no free slot; call step()/drain() first") from None

        # longest cached prefix (host lookup). K/V rows depend on the
        # WEIGHTS that produced them, so dense entries are keyed by
        # (adapter, tokens) and the paged RADIX store serves only
        # base-model requests (adapted submissions bypass it).
        p_pad = self.prompt_pad
        key_ns = np.int32(aid).tobytes()
        n_chunks = -(-len(prompt) // p_pad)
        hit_c, hit_entry = 0, None
        if self._prefix_cache is not None and prefilled is None:
            for c in range(len(prompt) // p_pad, 0, -1):
                e = self._prefix_cache.get(
                    key_ns + prompt[: c * p_pad].tobytes())
                if e is not None:
                    self._prefix_cache.move_to_end(
                        key_ns + prompt[: c * p_pad].tobytes())
                    hit_c, hit_entry = c, e
                    break
        # radix lookup (paged + kvtier store): longest block-aligned
        # run of resident blocks, plus the copy-on-write boundary — the
        # cached block whose first `cow_tokens` positions this prompt
        # still agrees with past the last full-block match
        kv_hit = None
        use_radix = (self._prefix_store is not None and prefilled is None
                     and aid == 0)
        if use_radix:
            kv_hit = self._prefix_store.lookup(prompt)

        paged_taken, install_ids, n_shared = None, None, 0
        cow_src, cow_tok = -1, 0
        if self._paged:
            from dnn_tpu.runtime.paged_kvcache import InsufficientBlocks

            # admission by ACTUAL length: this request holds
            # ceil((prompt + budget) / block_len) pool blocks for its
            # lifetime — a free slot alone is not enough. A prefix hit is
            # COPY-FREE: the entry's blocks are shared by reference
            # (refcounted), so only the tail is allocated.
            bp = self._block_len
            n_need = -(-(len(prompt) + max_new_tokens) // bp)
            if n_need > self._allocator.n_blocks - 1:
                # permanent: this request can NEVER fit the pool — fail it
                # (a transient InsufficientBlocks would wait forever)
                raise ValueError(
                    f"request needs {n_need} blocks but the pool only has "
                    f"{self._allocator.n_blocks - 1} allocatable")
            if kv_hit is not None:
                shared_ids = list(kv_hit.shared)[:n_need]
                if len(shared_ids) == len(kv_hit.shared):
                    cow_src, cow_tok = kv_hit.cow_src, kv_hit.cow_tokens
            else:
                # no radix store consulted (prefix_cache off, an
                # adapted request, or prefilled= adoption): paged
                # admission shares nothing — the dense LRU never
                # serves paged pools
                shared_ids = []
            n_shared = len(shared_ids)
            # ref the shared prefix (and the COW source) BEFORE any
            # eviction below can run: the hit entry itself may be
            # evicted while we hunt for tail blocks, and without our
            # reference its blocks could recycle into this very
            # allocation (aliasing the prefix)
            ref_ids = shared_ids + ([cow_src] if cow_tok > 0 else [])
            if ref_ids:
                self._allocator.ref(ref_ids)
            try:
                owned = self._allocator.alloc(n_need - n_shared)
                while owned is None and self._evictable_prefix():
                    # entry-pinned blocks must never starve admission
                    # (livelock: entries only evict on insertion, which
                    # needs a successful prefill): evict LRU entries until
                    # the tail fits. Entries whose blocks live slots still
                    # share free nothing (refcount) — keep evicting.
                    self._evict_prefix_entry()
                    owned = self._allocator.alloc(n_need - n_shared)
                if owned is None:
                    # ONE event/count per exhaustion episode, not per
                    # retry: the lm_server worker re-submits its held
                    # request every decode step, and a minutes-long
                    # shortage at ms cadence would otherwise flood the
                    # flight ring (evicting the post-mortem context it
                    # exists to keep) and turn the "admissions held
                    # back" counter into a retry counter
                    if not self._pool_exhausted_episode:
                        self._pool_exhausted_episode = True
                        m = obs.metrics()
                        if m is not None:
                            m.inc("serving.pool_exhausted_total")
                        obs.flight.record(
                            "pool_exhausted", need=n_need - n_shared,
                            free=self._allocator.n_free,
                            high_water=self._allocator.high_water)
                    raise InsufficientBlocks(
                        f"insufficient free cache blocks: need "
                        f"{n_need - n_shared}, have "
                        f"{self._allocator.n_free} "
                        f"(pool {self._allocator.n_blocks}, block {bp} "
                        f"pos)")
            except BaseException:
                if ref_ids:
                    self._allocator.free(ref_ids)
                raise
            self._pool_exhausted_episode = False  # blocks came free
            paged_taken = shared_ids + owned
            nb_max = self.cache["tables"].shape[-1]
            ids_row = np.zeros((nb_max,), np.int32)
            ids_row[:n_need] = paged_taken
            self.cache["tables"] = self.cache["tables"].at[:, slot].set(
                jnp.asarray(ids_row))
            if cow_tok > 0:
                # copy-on-write at the divergence boundary: duplicate
                # the ONE cached block this prompt still partially
                # agrees with into this request's first owned block
                # (logical index n_shared); prefill then resumes
                # MID-BLOCK after the agreed tokens instead of
                # recomputing the whole block. The original stays
                # intact for its own holders — the temporary reference
                # taken above kept it alive through the eviction hunt,
                # and is dropped now that the copy is enqueued
                # (in-order backends run the copy before any later
                # write could recycle the source).
                try:
                    self.cache = self._cow_copy(
                        self.cache, jnp.int32(cow_src),
                        jnp.int32(owned[0]))
                finally:
                    # the temporary reference drops either way — a
                    # failed dispatch must not strand the source block
                    self._allocator.free([cow_src])
            # install must NOT touch shared blocks (another request's live
            # prefix): their install targets are routed to junk block 0
            inst = ids_row.copy()
            inst[:n_shared] = 0
            install_ids = jnp.asarray(inst)
            if kv_hit is not None and (n_shared or cow_tok):
                # admission HOLDS the blocks now — record the reuse
                # (post-truncation, post-allocation: the ratio the
                # kv_tier probe floors must never count blocks the
                # request didn't actually get)
                self._prefix_store.note_reuse(
                    n_shared + (1 if cow_tok > 0 else 0),
                    kv_hit.remote_used(n_shared, cow_tok > 0),
                    cow=cow_tok > 0)

        if self._buckets is not None:
            # the installed prompt must fit the pool AND the first decode
            # write (at position len(prompt)) must have a column
            self._ensure_cache_len(len(prompt) + 1)

        # span tree (only when the caller passed a trace handle): "admit"
        # covers slot install end-to-end, "prefill" the device work inside
        adm = trace.child("admit", slot=slot, prompt_len=len(prompt)) \
            if trace else obs.NULL_SPAN
        try:
            rid = self._next_rid
            self._next_rid += 1
            # this request's private stream: (server seed, namespace, request
            # seed) — independent of what else is in the pool or when this
            # arrived. The namespace fold keeps auto-assigned rids and explicit
            # seeds from colliding (rid=3 vs seed=3 must be distinct streams).
            base = jax.random.fold_in(
                jax.random.PRNGKey(self._seed), 0 if seed is None else 1
            )
            req_key = jax.random.fold_in(base, rid if seed is None else seed)
            prefill_key, slot_key = jax.random.split(req_key)

            if self._ilv:
                # interleaved admission (ISSUE 12): NO device work here.
                # The prompt's chunks fold into subsequent decode steps
                # (mixed_step), the fused finish samples the first token
                # on device, and its readback rides a later step's
                # commit — submit() is host bookkeeping only, so the
                # prefill convoy never forms. rng derivation above is
                # identical to the convoy path, so greedy AND sampled
                # streams agree token-for-token across the two paths.
                p_c = self._ilv
                n_c = -(-len(prompt) // p_c)
                padded_i = np.zeros((1, n_c * p_c), np.int32)
                padded_i[0, : len(prompt)] = prompt
                seen_np = np.zeros((self.cfg.vocab_size,), bool)
                seen_np[prompt] = True
                if self._lora is not None and self._aid[slot] != aid:
                    self._aid[slot] = aid
                    self._decode_view = self._lora_prepared(self._aid)
                req = {"rid": rid, "emitted": [],
                       "budget": max_new_tokens, "stop": stop_seqs,
                       "logprobs": logprobs and self._logprobs_k,
                       "blocks": paged_taken,
                       "prompt_len": len(prompt), "freed": 0,
                       "t_last": None,
                       "pending": {
                           "padded": padded_i, "n_chunks": n_c,
                           "next": 0, "row": self._ilv_new_row(),
                           "aid": aid,
                           "last_local":
                               len(prompt) - 1 - (n_c - 1) * p_c,
                           "prefill_key": prefill_key,
                           "slot_key": slot_key,
                           "t": temp, "k": tk, "p": tp, "mp": mp,
                           "rp": rp,
                           "seen_row": jnp.asarray(seen_np),
                           "b_row": b_row,
                           "install_ids": install_ids
                           if install_ids is not None
                           else jnp.zeros((0,), jnp.int32),
                           # the grammar's global start row: the fused
                           # finish masks the first token with it and
                           # seeds crow[slot] on device (0 = the
                           # reserved unconstrained row)
                           "c_row": (0 if c_off is None
                                     else c_off + constraint.start),
                       }}
                if constraint is not None:
                    req["constraint"] = constraint
                    req["c_state"] = constraint.start
                    req["c_off"] = c_off
                    self._note_constrained(+1)
                if req["logprobs"]:
                    req["lp"] = []
                    req["lp_top"] = []
                if trace:
                    req["trace"] = trace
                self._slot_req[slot] = req
                self._pending_q.append(slot)
                return rid

            # chunked prefill: full prompt_pad-sized chunks + one padded tail,
            # each at its absolute start position — prompts of ANY length (up
            # to max_len - max_new) reuse the one compiled chunk program
            padded = np.zeros((1, n_chunks * p_pad), np.int32)
            padded[0, : len(prompt)] = prompt
            # prefilled (KV adoption): the row arrives from the prefill
            # replica — never allocate (or compute) one here
            row = self._new_row() if prefilled is None else None
            logits = None
            start_chunk = 0
            prefix_hit_flag = False
            prefix_lookup_ran = prefilled is None and (
                self._prefix_cache is not None or use_radix)
            if use_radix:
                prefix_hit_flag = n_shared > 0 or cow_tok > 0
            elif hit_c:
                prefix_hit_flag = True
            if prefix_lookup_ran:
                if prefix_hit_flag:
                    self.prefix_hits += 1
                else:
                    self.prefix_misses += 1
            if hit_c:
                # dense-LRU hit (the radix store replaces this path on
                # paged pools): copy out — the live row is donated
                # through the chunk loop and must not invalidate the
                # cached entry
                start_chunk = hit_c
                last_logit_row = hit_entry[1]
                row = jax.tree.map(jnp.copy, hit_entry[0])
                if hit_c == n_chunks:
                    # whole prompt cached: rebuild a chunk-shaped logits
                    # array with the stored last row in place (position
                    # p_pad-1 == the true last prompt token of an exact
                    # full-chunk prompt) so _prefill_finish keeps its one
                    # compiled shape
                    logits = jnp.zeros(
                        (1, p_pad, last_logit_row.shape[-1]),
                        last_logit_row.dtype,
                    ).at[0, p_pad - 1].set(last_logit_row)
            pf_prepared = self._lora_prefill_view(aid)
            sp_pf = adm.child("prefill", chunks=n_chunks - start_chunk,
                              prompt_len=len(prompt))
            t_pf = time.perf_counter()  # the PREFILL interval only —
            # submit-entry-to-here is validation/slot/host bookkeeping,
            # which belongs to the admit span, not this metric
            chunks_before = self.prefill_chunks_run
            last_local = len(prompt) - 1 - (n_chunks - 1) * p_pad
            kv_boundary_rows: dict = {}
            if prefilled is not None:
                # KV ADOPTION (disaggregated serving, dnn_tpu/control):
                # the prefill replica already ran this chunk loop;
                # rebuild its transient row + the finish-shaped logits
                # and fall through to the SAME _prefill_finish install
                # below — the decode replica spends zero prompt FLOPs
                row, logits = self._adopt_prefilled(prefilled, prompt)
            elif use_radix:
                row, logits, last_local = self._radix_prefill(
                    prompt, slot, pf_prepared, row, kv_hit, n_shared,
                    cow_tok, kv_boundary_rows)
            else:
                for c in range(start_chunk, n_chunks):
                    with _prof_annotation("serving.prefill_chunk"):
                        logits, row = self._prefill_chunk(
                            pf_prepared, row,
                            jnp.asarray(
                                padded[:, c * p_pad:(c + 1) * p_pad]),
                            jnp.int32(c * p_pad),
                        )
                    self.prefill_chunks_run += 1
                    if self._prefix_cache is not None \
                            and (c + 1) * p_pad <= len(prompt):
                        key = key_ns + prompt[: (c + 1) * p_pad].tobytes()
                        # scan-resistant insertion: evict the current
                        # LRU first, then park the NEW entry at the LRU
                        # end — only a HIT promotes to MRU. A long novel
                        # prompt therefore cycles its own one-shot
                        # chunks through the LRU slot instead of
                        # flushing the hot shared-prefix entries it
                        # never matches.
                        while len(self._prefix_cache) >= self._prefix_cap:
                            self._evict_prefix_entry()
                        self._prefix_cache[key] = (
                            jax.tree.map(jnp.copy, row),
                            jnp.copy(logits[0, -1]))
                        self._prefix_cache.move_to_end(key, last=False)
            t_arr = jnp.float32(temp)
            k_arr = jnp.int32(tk)
            p_arr = jnp.float32(tp)
            seen_np = np.zeros((self.cfg.vocab_size,), bool)
            seen_np[prompt] = True
            seen_row = jnp.asarray(seen_np)
            fin = self._prefill_finish(
                self.cache, row, logits, last_local, slot, prefill_key,
                t_arr, k_arr, p_arr, jnp.float32(mp), jnp.float32(rp),
                seen_row, b_row,
                install_ids if install_ids is not None
                else jnp.zeros((0,), jnp.int32),
                jnp.int32(0 if c_off is None
                          else c_off + constraint.start),
                self._ctable,
            )
            if use_radix:
                # insert this prompt's full-block path now that the
                # install has populated the owned blocks. The store
                # refs every NEWLY resident block (existing nodes are
                # reused untouched); the slot keeps its own references
                # until retirement, so the trie and the live request
                # share blocks exactly as two requests would. Origins
                # propagate per block: re-creating an evicted ADOPTED
                # node must not launder it local (the cross-replica
                # accounting would decay with cache churn).
                n_cover = len(prompt) // self._block_len
                kv_borig = list(kv_hit.origins[:n_shared]) \
                    if kv_hit is not None else []
                if n_cover:
                    self._prefix_store.insert(
                        prompt[: n_cover * self._block_len],
                        [int(x) for x in paged_taken[:n_cover]],
                        logit_rows=kv_boundary_rows, origin=kv_borig)
            if self._logprobs_k:
                self.cache, first, c_lp, t_lp, t_ids = fin
            else:
                self.cache, first = fin
            first = int(first)  # blocks until the prefill really finished
            sp_pf.end()
            m = obs.metrics()
            if m is not None:
                # the FIRST token commits here (sampled during prefill),
                # so it is credited here — counting only in step() would
                # under-report every request by one token (and budget-1
                # requests, which never reach step(), entirely)
                self._tps.add(1)
                counters = {
                    "serving.tokens_total": 1,
                    "serving.prefill_chunks_total":
                        self.prefill_chunks_run - chunks_before,
                }
                if prefix_hit_flag:
                    counters["serving.prefix_hits_total"] = 1
                    if use_radix:
                        # block-granular effectiveness (the radix
                        # extension of the hit/miss pair): how many
                        # physical blocks this admission reused, and
                        # how many of them arrived by MIGRATION from a
                        # sibling replica (origin="adopted") — the
                        # cross-replica number the kv_tier probe
                        # floors. Post-truncation counts, matching
                        # note_reuse above.
                        counters["serving.prefix_blocks_reused_total"] \
                            = n_shared + (1 if cow_tok > 0 else 0)
                        remote_used = kv_hit.remote_used(
                            n_shared, cow_tok > 0)
                        if remote_used:
                            counters[
                                "serving.kvtier_remote_block_hits_total"
                            ] = remote_used
                elif prefix_lookup_ran:
                    # the lookup ran (prefilled= adoptions skip it) and
                    # reused nothing — the other half of the ratio
                    counters["serving.prefix_misses_total"] = 1
                m.bulk(
                    counters=counters,
                    observations={"serving.prefill_seconds":
                                  [time.perf_counter() - t_pf]},
                    gauge_fns=self._obs_gauges,
                )
                if (g := self.goodput) is not None:
                    g.on_prefill(len(prompt))
                if self._kvlens is not None:
                    # the thrash detector's price signal: what ONE
                    # prefill chunk costs on this host right now — an
                    # evict→refetch bills this EMA per re-run chunk
                    self._kvlens.note_prefill(
                        self.prefill_chunks_run - chunks_before,
                        time.perf_counter() - t_pf)
            self.pos = self.pos.at[slot].set(len(prompt))
            self.tok = self.tok.at[slot].set(first)
            self.active = self.active.at[slot].set(True)
            self.keys = self.keys.at[slot].set(slot_key)
            self._temp = self._temp.at[slot].set(temp)
            self._topk = self._topk.at[slot].set(tk)
            self._topp = self._topp.at[slot].set(tp)
            self._minp = self._minp.at[slot].set(mp)
            self._rep = self._rep.at[slot].set(rp)
            self._seen = self._seen.at[slot].set(
                seen_row.at[first].set(True))
            self._bias = self._bias.at[slot].set(b_row)
            if self._lora is not None and self._aid[slot] != aid:
                self._aid[slot] = aid
                self._decode_view = self._lora_prepared(self._aid)
            req = {"rid": rid, "emitted": [first], "budget": max_new_tokens,
                   "stop": stop_seqs, "logprobs": logprobs and self._logprobs_k,
                   "blocks": paged_taken, "prompt_len": len(prompt),
                   "freed": 0}
            if use_radix:
                # retire-time store insertion needs the token ids and
                # the per-block provenance (adopted blocks re-inserted
                # after eviction must stay adopted)
                req["ptoks"] = prompt
                req["borig"] = kv_borig
            if constraint is not None:
                req["constraint"] = constraint
                req["c_state"] = constraint.start
                req["c_off"] = c_off
                self._note_constrained(+1)
            if req["logprobs"]:
                req["lp"] = [float(np.asarray(c_lp)[0])]
                req["lp_top"] = [(np.asarray(t_ids)[0], np.asarray(t_lp)[0])]
            if trace:
                req["trace"] = trace  # step() hangs decode spans off this
            req["t_last"] = time.perf_counter()  # inter-token clock
            if self._overlap and self._inflight is not None:
                # the uncommitted in-flight step was dispatched while
                # this slot was still free: its row of that step's
                # tokens is garbage and must not commit (the same
                # install gating the interleaved path uses; the first
                # token here is already in `emitted`)
                req["install_step"] = self._step_idx - 1
            self._slot_req[slot] = req
            if constraint is not None:
                # convoy admission is the one place the host seeds the
                # device walk: the first token was sampled by
                # _prefill_finish (masked with the grammar's start row)
                # and read back above, so mirror-walk it and install
                # the post-first-token state — every later advance
                # happens inside the decode program. Prefix-cache /
                # kvtier / prefilled adoption changes nothing: the
                # grammar constrains GENERATED tokens only, so the
                # adopted prefix's state is still `start`.
                self._constraint_advance(slot, first)
                self._crow = self._crow.at[slot].set(
                    jnp.int32(c_off + req["c_state"]))
            # a prompt longer than the window rolls blocks out at install
            self._free_rolled_blocks(slot)
            self._retire_if_done(slot)
            return rid
        except BaseException:
            # a failure ANYWHERE in the prefill path must return this
            # request's pool blocks (and un-point its table row) or the
            # pool shrinks permanently on every such failure — same for
            # its constraint-table reference. For windowed pools,
            # _free_rolled_blocks may ALREADY have returned the rolled
            # -out prefix (it runs before _retire_if_done): free only
            # the remainder, and release the slot if the req landed.
            if paged_taken:
                req_now = self._slot_req[slot]
                skip = (req_now["freed"]
                        if isinstance(req_now, dict)
                        and req_now.get("blocks") is paged_taken else 0)
                self._allocator.free(paged_taken[skip:])
                self.cache["tables"] = \
                    self.cache["tables"].at[:, slot].set(0)
            # the slot was free at entry, so it must end inactive on ANY
            # failure — active may have been set before the req landed,
            # and a True-active/None-req slot would spin drain() forever
            self._slot_req[slot] = None
            self.active = self.active.at[slot].set(False)
            if c_off is not None:
                self._ctab_release(constraint)
            raise
        finally:
            adm.end()
            if _sc is not None:
                _sc.note_admit(_t_sub)

    def _ensure_cache_len(self, need: int):
        """Grow the bucketed dense pool to the smallest ladder bucket
        covering `need` live positions (no-op when already covered, or on
        unbucketed pools). Grow-only by design: shrinking mid-flight
        would thrash the jit cache on every retire; an idle server that
        wants the small allocation back reconstructs."""
        if self._buckets is None or need <= self._cache_len:
            return
        from dnn_tpu.runtime.decode_buckets import bucket_for

        target = bucket_for(self._buckets, need)
        self.cache = self._grow_cache(self.cache, target)
        self._cache_len = target
        m = obs.metrics()
        if m is not None:
            m.inc("serving.decode_bucket_grow_total")

    # -- disaggregated prefill/decode (dnn_tpu/control) -----------------

    def handoff_fingerprint(self) -> dict:
        """The geometry both sides of a KV handoff must agree on. The
        adopt path re-verifies leaf-by-leaf anyway (shapes + dtypes vs
        this pool's own row structure); the fingerprint exists so a
        kvput against a mismatched replica fails at INGEST with a
        readable diff instead of at admission."""
        leaves = jax.tree_util.tree_flatten(self._row_shape())[0]
        return {
            "family": type(self.family).__name__,
            "vocab_size": int(self.cfg.vocab_size),
            "prompt_pad": int(self.prompt_pad),
            "row_len": int(self._row_len),
            "row_leaves": [[list(x.shape), str(x.dtype)] for x in leaves],
        }

    def _row_shape(self):
        """ShapeDtypeStruct pytree of the transient row cache (no
        allocation) — the adoption path's geometry oracle."""
        struct = getattr(self, "_row_struct", None)
        if struct is None:
            struct = jax.eval_shape(self._new_row)
            self._row_struct = struct
        return struct

    def export_prefill(self, prompt, *, max_new_tokens: int = 1):
        """PREFILL-replica half of the disaggregated split: run ONLY
        the chunk loop for `prompt` — no slot held, no install, no
        sampling — and return the handoff payload a decode replica
        adopts via `submit(prefilled=...)`: the transient row cache's
        leaves (host arrays) plus the final chunk's true-last logit
        row. `max_new_tokens` only sizes the length check (the decode
        side re-validates with the request's real budget).

        Prices like any prefill: the chunk counter, the prefill-
        seconds series and the goodput tracker's prefill FLOPs all
        tick here, so MFU/MBU on the prefill replica account the work
        it actually does (the handoff's wire cost is priced by the
        router's handoff gauges)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("prompt must have at least one token")
        if len(prompt) + max(int(max_new_tokens), 1) > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens "
                f"{max_new_tokens} exceeds max_len {self.max_len}")
        p_pad = self.prompt_pad
        n_chunks = -(-len(prompt) // p_pad)
        padded = np.zeros((1, n_chunks * p_pad), np.int32)
        padded[0, : len(prompt)] = prompt
        # the row is built the convoy way — _new_row + the chunk
        # program — even on an interleaved-admission server (the chunk
        # program is compiled unconditionally), so ANY replica can
        # take the prefill role
        row = self._new_row()
        logits = None
        t_pf = time.perf_counter()
        for c in range(n_chunks):
            with _prof_annotation("serving.prefill_chunk"):
                logits, row = self._prefill_chunk(
                    self.prepared, row,
                    jnp.asarray(padded[:, c * p_pad:(c + 1) * p_pad]),
                    jnp.int32(c * p_pad),
                )
            self.prefill_chunks_run += 1
        last_local = len(prompt) - 1 - (n_chunks - 1) * p_pad
        logits_row = np.asarray(logits[0, last_local])
        leaves = [np.asarray(x) for x in jax.tree_util.tree_flatten(row)[0]]
        m = obs.metrics()
        if m is not None:
            m.bulk(
                counters={"serving.prefill_chunks_total": n_chunks},
                observations={"serving.prefill_seconds":
                              [time.perf_counter() - t_pf]},
            )
            if (g := self.goodput) is not None:
                g.on_prefill(len(prompt))
        return {"row": leaves, "logits_row": logits_row,
                "prompt_len": len(prompt),
                "fingerprint": self.handoff_fingerprint()}

    def _adopt_prefilled(self, prefilled, prompt) -> tuple:
        """Decode-replica half: verify the handed-off payload against
        THIS pool's row geometry, rebuild the row pytree and the
        finish-shaped logits array (the stored true-last row placed at
        `last_local`, exactly like a whole-prompt prefix hit). Every
        mismatch is a loud ValueError — adopting mis-shaped KV would
        generate plausible garbage."""
        struct = self._row_shape()
        want, treedef = jax.tree_util.tree_flatten(struct)
        got = prefilled.get("row") if isinstance(prefilled, dict) else None
        if not isinstance(got, (list, tuple)):
            raise ValueError(
                "prefilled= expects an export_prefill payload dict "
                "with a 'row' leaf list")
        if len(got) != len(want):
            raise ValueError(
                f"handoff row has {len(got)} leaves but this pool's "
                f"row cache has {len(want)} — prefill and decode "
                "replicas must share model config and kv dtype")
        for i, (w, h) in enumerate(zip(want, got)):
            h = np.asarray(h)
            if tuple(h.shape) != tuple(w.shape) \
                    or str(h.dtype) != str(np.dtype(w.dtype)):
                raise ValueError(
                    f"handoff row leaf {i} is {h.dtype}{h.shape} but "
                    f"this pool expects {w.dtype}{tuple(w.shape)} — "
                    "prefill and decode replicas must share model "
                    "config, max_len, prompt_pad and kv dtype")
        plen = prefilled.get("prompt_len")
        if plen is not None and int(plen) != len(prompt):
            raise ValueError(
                f"handoff was exported for a {plen}-token prompt but "
                f"this request's prompt has {len(prompt)} tokens")
        row = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(x) for x in got])
        lr = np.asarray(prefilled.get("logits_row"))
        if lr.shape != (self.cfg.vocab_size,):
            raise ValueError(
                f"handoff logits_row has shape {lr.shape}, expected "
                f"({self.cfg.vocab_size},)")
        p_pad = self.prompt_pad
        n_chunks = -(-len(prompt) // p_pad)
        last_local = len(prompt) - 1 - (n_chunks - 1) * p_pad
        lr_j = jnp.asarray(lr)
        logits = jnp.zeros((1, p_pad, lr_j.shape[0]), lr_j.dtype
                           ).at[0, last_local].set(lr_j)
        m = obs.metrics()
        if m is not None:
            m.inc("serving.kv_adoptions_total")
        return row, logits

    # -- fleet KV tier (dnn_tpu/kvtier): stage / export / adopt ---------

    def _require_store(self):
        if self._prefix_store is None:
            raise ValueError(
                "the KV tier needs the radix prefix store: construct "
                "with kv='paged' (or paged_blocks>0) and prefix_cache>0")

    def kvtier_fingerprint(self) -> dict:
        """Block geometry both sides of a block migration must share —
        checked at adopt with a readable diff, exactly like the
        row-handoff fingerprint. int4 pools report their true dtype
        (blocks cross the host boundary as int8 values and re-pack on
        ingest)."""
        self._require_store()
        leaves = {}
        for kk in self.cache:
            if kk == "tables":
                continue
            shp = list(self.cache[kk].shape)
            # one block's leaf: drop the n_blocks axis (axis 1)
            leaves[kk] = [[shp[0]] + shp[2:], str(self.cache[kk].dtype)]
        return {"family": type(self.family).__name__,
                "vocab_size": int(self.cfg.vocab_size),
                "block_len": int(self._block_len),
                "leaves": leaves}

    def _read_block(self, block_id: int) -> dict:
        """One physical block's leaves on host — fixed-shape jitted
        gather (a per-run-length gather would compile per length).
        int4 payloads widen to int8 VALUES for the host trip (native
        int4 has no stable host view; the wire codec nibble-packs
        them back to half a byte)."""
        got = self._kv_get_block(self.cache, jnp.int32(block_id))
        return {kk: np.asarray(v) for kk, v in got.items()}

    def kvtier_export(self, tokens):
        """Donor half of block migration: the longest resident run of
        full blocks matching `tokens`, read off the pool. Returns the
        payload dict `kvtier_adopt` ingests (kvtier/migrate.py packs it
        for the wire), or None when nothing is resident. Worker-thread
        only (reads pool leaves between steps)."""
        self._require_store()
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        nodes = self._prefix_store.nodes_for(tokens)
        if not nodes:
            return None
        blocks = [self._read_block(n.block) for n in nodes]
        leaves = {kk: np.stack([b[kk] for b in blocks], axis=1)
                  for kk in blocks[0]}
        logit_rows = {i: np.asarray(n.logit_row)
                      for i, n in enumerate(nodes)
                      if n.logit_row is not None}
        bp = self._block_len
        return {"tokens": tokens[: len(nodes) * bp],
                "block_len": bp, "leaves": leaves,
                "logit_rows": logit_rows,
                "fingerprint": self.kvtier_fingerprint()}

    def kvtier_adopt(self, payload, *, origin: str = "adopted") -> int:
        """Adopter half: ingest a sibling's exported block run — verify
        geometry, allocate fresh LOCAL blocks for the non-resident
        suffix (never aliasing anything live: a dying donor cannot
        corrupt an adopter, because nothing of the donor's is mapped),
        scatter the payload in block-by-block, and insert the radix
        path with origin="adopted" so hit accounting knows these
        blocks crossed replicas. Returns blocks actually migrated
        (0 = everything was already resident). Worker-thread only."""
        from dnn_tpu.runtime.paged_kvcache import InsufficientBlocks

        self._require_store()
        mine = self.kvtier_fingerprint()
        theirs = payload.get("fingerprint") or {}
        if theirs and theirs != mine:
            diff = {k: (theirs.get(k), mine.get(k))
                    for k in set(theirs) | set(mine)
                    if theirs.get(k) != mine.get(k)}
            raise ValueError(
                f"kvtier geometry mismatch (theirs, mine): {diff} — "
                "donor and adopter must share model config, block_len "
                "and kv dtype")
        tokens = np.asarray(payload["tokens"], np.int32).reshape(-1)
        bp = self._block_len
        n_total = tokens.size // bp
        if n_total == 0:
            return 0
        have = self._prefix_store.nodes_for(tokens)
        n_have = len(have)
        if n_have >= n_total:
            return 0
        n_missing = n_total - n_have
        # ref the matched resident run BEFORE the make-room loop: the
        # eviction hunt below may otherwise evict those very nodes,
        # free their blocks, and recycle them into `owned` — the
        # insert would then map two trie paths onto one physical block
        # (the same aliasing hazard submit() guards against)
        have_ids = [n.block for n in have]
        if have_ids:
            self._allocator.ref(have_ids)
        try:
            owned = self._allocator.alloc(n_missing)
            while owned is None and self._evictable_prefix():
                self._evict_prefix_entry()
                owned = self._allocator.alloc(n_missing)
        except BaseException:
            if have_ids:
                self._allocator.free(have_ids)
            raise
        if owned is None:
            if have_ids:
                self._allocator.free(have_ids)
            raise InsufficientBlocks(
                f"kvtier adopt needs {n_missing} free blocks, have "
                f"{self._allocator.n_free}")
        try:
            for j, dst in zip(range(n_have, n_total), owned):
                vals = {kk: jnp.asarray(np.ascontiguousarray(
                    payload["leaves"][kk][:, j]))
                    for kk in payload["leaves"]}
                self.cache = self._kv_put_block(self.cache, vals,
                                                jnp.int32(dst))
            ids = have_ids + owned
            lrs = {int(i): jnp.asarray(r)
                   for i, r in (payload.get("logit_rows") or {}).items()}
            self._prefix_store.insert(tokens[: n_total * bp], ids,
                                      logit_rows=lrs, origin=origin)
        finally:
            # the store now holds its own reference per inserted node;
            # dropping ours (owned allocs + the matched-run guards)
            # frees exactly the blocks that did NOT make it in (cap
            # pressure, or an exception mid-scatter)
            self._allocator.free(owned + have_ids)
        m = obs.metrics()
        if m is not None:
            m.inc("serving.kvtier_blocks_adopted_total", n_missing)
        if self._kvlens is not None:
            # migration forensics: blocks that crossed the wire, priced
            # in payload bytes when the transport recorded them
            self._kvlens.on_migrate(
                n_missing, int(payload.get("_wire_bytes") or 0))
        return n_missing

    def stage_prefix(self, prompt) -> dict:
        """Prefill `prompt`'s full blocks STRAIGHT INTO the radix store
        — no slot held, no sampling, no install into any request's
        table: the prefill-replica half of disaggregated block
        migration (the router stages here, then tells the decode
        replica to pull), and a warm-up hook. Resumes at the first
        non-resident block like any admission; a fully resident prompt
        is a no-op. Worker-thread only."""
        from dnn_tpu.runtime.paged_kvcache import InsufficientBlocks

        self._require_store()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        bp = self._block_len
        p_pad = self.prompt_pad
        n_cover = prompt.size // bp
        stats = {"covered_blocks": n_cover, "staged_blocks": 0,
                 "computed_chunks": 0}
        if n_cover == 0:
            return stats
        nodes = self._prefix_store.nodes_for(prompt[: n_cover * bp])
        n_shared = len(nodes)
        if n_shared >= n_cover:
            return stats
        shared_ids = [n.block for n in nodes]
        if shared_ids:
            self._allocator.ref(shared_ids)
        owned = None
        try:
            owned = self._allocator.alloc(n_cover - n_shared)
            while owned is None and self._evictable_prefix():
                self._evict_prefix_entry()
                owned = self._allocator.alloc(n_cover - n_shared)
            if owned is None:
                raise InsufficientBlocks(
                    f"stage_prefix needs {n_cover - n_shared} free "
                    f"blocks, have {self._allocator.n_free}")
            nb_max = self.cache["tables"].shape[-1]
            ids_row = np.zeros((nb_max,), np.int32)
            ids_row[:n_cover] = shared_ids + owned
            end = n_cover * bp
            resume = n_shared * bp
            if resume + (-(-(end - resume) // p_pad)) * p_pad \
                    > self._row_len:
                resume = (resume // p_pad) * p_pad
            row = (self._gather_row(self.cache, jnp.asarray(ids_row))
                   if resume else self._new_row())
            n_k = -(-(end - resume) // p_pad)
            padded = np.zeros((1, n_k * p_pad), np.int32)
            padded[0, : end - resume] = prompt[resume:end]
            boundary: dict = {}
            logits = None
            t_pf = time.perf_counter()
            for i in range(n_k):
                start = resume + i * p_pad
                with _prof_annotation("serving.prefill_chunk"):
                    logits, row = self._prefill_chunk(
                        self.prepared, row,
                        jnp.asarray(padded[:, i * p_pad:(i + 1) * p_pad]),
                        jnp.int32(start))
                self.prefill_chunks_run += 1
                for b in range(start // bp, n_cover):
                    pos = (b + 1) * bp - 1
                    if pos >= start + p_pad:
                        break
                    if pos >= start:
                        boundary[b] = jnp.copy(logits[0, pos - start])
            inst = ids_row.copy()
            inst[:n_shared] = 0
            self.cache = self._kvtier_install(self.cache, row,
                                              jnp.asarray(inst))
            self._prefix_store.insert(
                prompt[:end], [int(x) for x in ids_row[:n_cover]],
                logit_rows=boundary)
            m = obs.metrics()
            if m is not None:
                m.bulk(counters={"serving.prefill_chunks_total": n_k},
                       observations={"serving.prefill_seconds":
                                     [time.perf_counter() - t_pf]},
                       gauge_fns=self._obs_gauges)
                if (g := self.goodput) is not None:
                    g.on_prefill(end - resume)
                if self._kvlens is not None:
                    self._kvlens.note_prefill(
                        n_k, time.perf_counter() - t_pf)
            stats.update(staged_blocks=n_cover - n_shared,
                         computed_chunks=n_k)
            return stats
        finally:
            # transient references only: the store refs what it keeps
            if shared_ids:
                self._allocator.free(shared_ids)
            if owned:
                self._allocator.free(owned)

    def _evictable_prefix(self) -> bool:
        """Whether the admission make-room loop has anything left to
        evict — either prefix-cache form."""
        if self._prefix_store is not None:
            return self._prefix_store.n_blocks > 0
        return bool(self._prefix_cache)

    def _evict_prefix_entry(self, cause: str = "capacity"):
        """Drop the LRU prefix entry — the dense dict's LRU head, or
        the radix store's LRU LEAF (interior nodes carry every
        descendant's prefix). Either way blocks still shared by live
        slots survive via refcount until those retire. `cause`
        attributes the eviction ("capacity" = admission pressure; the
        kvput-TTL and lease-reclaim sweeps are separate paths that
        label their own events) — the unlabeled total stays as-is, the
        by-cause family rides alongside so forensics can tell real
        pressure from housekeeping."""
        if self._prefix_store is not None:
            if not self._prefix_store.evict_one(cause=cause):
                return
            self.prefix_evictions += 1
            left = self._prefix_store.n_blocks
        else:
            _, _entry = self._prefix_cache.popitem(last=False)
            self.prefix_evictions += 1
            left = len(self._prefix_cache)
        m = obs.metrics()
        if m is not None:
            m.inc("serving.prefix_evictions_total")
            m.inc(labeled("serving.prefix_evictions_cause_total",
                          cause=cause))
        obs.flight.record("prefix_evict", entries_left=left, cause=cause)

    def _radix_prefill(self, prompt, slot, pf_prepared, row, kv_hit,
                       n_shared, cow_tok, boundary_rows):
        """The radix-store admission prefill: resume the chunk loop at
        the first non-cached position instead of chunk 0.

        Returns (row, logits, last_local). Three regimes:

          * FULL HIT — the prompt is exactly the shared block run and
            the final node stored its logit row: zero chunks, rebuild
            the finish-shaped logits with the stored row in place;
          * PARTIAL — resume at `n_shared * block_len + cow_tok` (the
            copy-on-write boundary block, already duplicated into this
            request's first owned block, covers the agreed mid-block
            tokens); the transient row is GATHERED from the slot's
            table so later chunks attend the shared prefix, then
            full-width chunks run from the (block- or mid-block-
            aligned) resume position — `chunk_start` is a dynamic
            scalar, so unaligned starts reuse the one compiled chunk
            program;
          * capacity BACKOFF — a resume point whose remaining chunks
            would overhang the transient row is rounded down to its
            chunk boundary (a dynamic-update overhang would CLAMP the
            write onto real positions — the standing row-rounding
            lesson); backing into already-shared territory only
            recomputes values the install then routes to junk.

        Boundary logit rows (the model's logits after each completed
        block) are collected into `boundary_rows` for the store insert
        — what makes a later exactly-block-aligned prompt a zero-chunk
        full hit."""
        p_len = len(prompt)
        bp = self._block_len
        p_pad = self.prompt_pad
        if kv_hit.logit_row is not None and p_len == n_shared * bp \
                and cow_tok == 0:
            lr = jnp.asarray(kv_hit.logit_row)
            last_local = (p_len - 1) % p_pad
            logits = jnp.zeros((1, p_pad, lr.shape[-1]), lr.dtype
                               ).at[0, last_local].set(lr)
            return row, logits, last_local
        resume = min(n_shared * bp + cow_tok, p_len - 1)
        if resume + (-(-(p_len - resume) // p_pad)) * p_pad \
                > self._row_len:
            # overhang only ever comes from an UNALIGNED resume near a
            # full row; rounding down to the chunk boundary always fits
            # (end <= ceil(p/P)*P <= row_len), at the price of
            # recomputing at most one chunk's worth of already-shared
            # positions — whose installs route to junk, never corrupt
            resume = (resume // p_pad) * p_pad
        if resume:
            row = self._gather_row(self.cache,
                                   self.cache["tables"][0, slot])
        n_k = -(-(p_len - resume) // p_pad)
        padded_r = np.zeros((1, n_k * p_pad), np.int32)
        padded_r[0, : p_len - resume] = prompt[resume:]
        logits = None
        for i in range(n_k):
            start = resume + i * p_pad
            with _prof_annotation("serving.prefill_chunk"):
                logits, row = self._prefill_chunk(
                    pf_prepared, row,
                    jnp.asarray(padded_r[:, i * p_pad:(i + 1) * p_pad]),
                    jnp.int32(start))
            self.prefill_chunks_run += 1
            for b in range(start // bp, p_len // bp):
                pos = (b + 1) * bp - 1
                if pos >= start + p_pad:
                    break
                if pos >= start:
                    boundary_rows[b] = jnp.copy(logits[0, pos - start])
        last_local = (p_len - resume - 1) - (n_k - 1) * p_pad
        return row, logits, last_local

    @staticmethod
    def _stop_match(emitted: list, stop_seqs: list):
        """Length of the stop sequence the emitted stream ends with, else 0."""
        for s in stop_seqs:
            n = len(s)
            if len(emitted) >= n and emitted[-n:] == list(map(int, s)):
                return n
        return 0

    def _ctab_register(self, c) -> int:
        """Place a constraint's (S, V) mask table in the device pool,
        returning its row offset. A pool hit just bumps the refcount; a
        miss allocates a gap (evicting LRU unreferenced entries as
        needed) and uploads the bool table ONCE. Raises when the grammar
        cannot fit even an empty pool — size `constraint_rows` to the
        grammar set (json_regex(2) needs ~900 rows)."""
        key = id(c)
        e = self._ctab_entries.get(key)
        if e is not None:
            e["refs"] += 1
            self._ctab_entries.move_to_end(key)
            return e["off"]
        n = c.table.shape[0]
        if n > self._ctab_rows - 1:
            raise ValueError(
                f"constraint has {n} DFA states but the device mask pool "
                f"holds {self._ctab_rows - 1} rows — construct the server "
                f"with constraint_rows >= {n + 1}")

        def _free_gap():
            # first gap >= n after reserved row 0, between sorted entries
            taken = sorted((v["off"], v["off"] + v["n"])
                           for v in self._ctab_entries.values())
            at = 1
            for lo, hi in taken:
                if lo - at >= n:
                    return at
                at = max(at, hi)
            return at if self._ctab_rows - at >= n else None

        off = _free_gap()
        while off is None:
            victim = next((k for k, v in self._ctab_entries.items()
                           if v["refs"] == 0), None)
            if victim is None:
                raise ValueError(
                    f"constraint mask pool exhausted: {n} rows needed, "
                    f"all {self._ctab_rows - 1} allocatable rows occupied "
                    "by live requests — construct the server with a "
                    "larger constraint_rows")
            del self._ctab_entries[victim]
            off = _free_gap()
        self._ctable = self._ctable.at[off:off + n].set(
            jnp.asarray(c.mask_table(self.eos_id)))
        # transition rows upload in GLOBAL pool coordinates (local next
        # state + this grammar's offset), so the decode program's walk
        # `ctrans[crow, tok]` needs no per-grammar rebase — and the
        # functional .at[].set means an in-flight overlap step keeps
        # its own (pre-upload) buffers untouched
        self._ctrans = self._ctrans.at[off:off + n].set(
            jnp.asarray(c.trans_table(self.eos_id) + np.int32(off)))
        self._ctab_entries[key] = {"off": off, "n": n, "refs": 1, "c": c}
        return off

    def _ctab_release(self, c):
        e = self._ctab_entries.get(id(c))
        if e is not None and e["refs"] > 0:
            e["refs"] -= 1  # entry stays cached for reuse until evicted

    def _free_rolled_blocks(self, slot: int):
        """Windowed paged pools reclaim FULLY rolled-out blocks while
        the request still runs: block j (positions [j*bp, (j+1)*bp)) is
        dead once its last position <= attend_limit - window — the band
        mask excludes it at this and every later step, so its physical
        block returns to the allocator (a long stream holds O(window)
        blocks, the pool form of the rolling cache's win) and its table
        entry points at junk block 0, whose content the mask never
        admits. No-op for dense/unwindowed pools."""
        w = self._paged_window
        req = self._slot_req[slot]
        if w is None or req is None or not req["blocks"]:
            return
        bp = self._block_len
        limit = req["prompt_len"] + len(req["emitted"]) - 1
        n_dead = min(max(0, limit - w + 1) // bp, len(req["blocks"]))
        freed = req["freed"]
        if n_dead <= freed:
            return
        self._allocator.free(req["blocks"][freed:n_dead])
        self._pool_exhausted_episode = False  # blocks came free
        self.cache["tables"] = \
            self.cache["tables"].at[:, slot, freed:n_dead].set(0)
        req["freed"] = n_dead

    def _constraint_advance(self, slot: int, token: int):
        """HOST MIRROR of the device DFA walk, for finish detection
        only: the device already advanced `crow[slot]` in the step (or
        fused finish) that sampled `token` — this walks the same
        transition on host bookkeeping so retirement logic can ask
        "is the match complete with no continuation?". Sets `c_done`
        when nothing can extend the match and EOS can't express the
        stop (retires as "constraint" — the grammar, not the budget,
        ended the stream). Runs at commit, OFF the dispatch critical
        path: zero per-step host->device constraint traffic."""
        req = self._slot_req[slot]
        c = req.get("constraint")
        if c is None or (self.eos_id is not None and token == self.eos_id):
            return
        ns = c.advance(req["c_state"], token)
        if ns < 0:
            # unreachable when masking works (the sampled token was
            # allowed); defensive stop rather than emitting off-grammar
            req["c_done"] = True
            return
        req["c_state"] = ns
        if not c.has_continuation(ns) and (
                self.eos_id is None or not c.is_accepting(ns)):
            # nothing can extend the match and EOS can't express the stop
            req["c_done"] = True

    # ------------------------------------------------------------------
    # observability helpers (dnn_tpu/obs) — shared by the dense step and
    # the speculative override (serving_spec.SpeculativeBatcher.step)
    # ------------------------------------------------------------------

    def _obs_commit(self, req, m, t_now, n_new: int = 1,
                    samples: Optional[list] = None):
        """Per-slot bookkeeping after committing `n_new` tokens: the
        inter-token clock (a speculative chunk spreads its gap over the
        chunk; samples accumulate into `samples` for the step's ONE bulk
        registry update) and the per-BUCKET decode span — one child per
        cache-view rung a request decodes through (a single span on
        unbucketed pools), closed with token/reason attrs at retire."""
        if m is not None:
            tl = req.get("t_last")
            if tl is not None and samples is not None:
                samples.append((t_now - tl) / max(n_new, 1))
            req["t_last"] = t_now
        else:
            # gate off: clear the clock so a runtime re-enable
            # (obs.set_enabled) doesn't observe the whole disabled gap
            # as one giant inter-token sample
            req["t_last"] = None
        tr = req.get("trace")
        if tr is not None and req.get("b_bucket") != self._cache_len:
            bs = req.get("b_span")
            if bs is not None:
                bs.end(tokens=len(req["emitted"]) - n_new)
            req["b_span"] = tr.child("decode", bucket=self._cache_len)
            req["b_bucket"] = self._cache_len

    def _bucket_key(self) -> str:
        """Memoized labeled() key for the current bucket — the string
        formatting is measurable on the per-step path."""
        key = self._bucket_keys.get(self._cache_len)
        if key is None:
            key = self._bucket_keys[self._cache_len] = labeled(
                "serving.decode_bucket_dispatch_total",
                bucket=self._cache_len)
        return key

    def _obs_step_end(self, m, n_adv: int, samples: Optional[list] = None):
        """Pool-level series for one completed device step (`n_adv` =
        tokens committed across all slots): counters/samples land in ONE
        bulk registry update, and the pool gauges are CALLABLE — read at
        scrape time from host state. Both choices are load-bearing:
        per-series locking measurably taxes a sub-ms CPU decode step
        (benchmarks/obs_overhead_probe.py), and stored gauges freeze at
        the last step's value on an idle pool (throughput would never
        decay, occupancy would report the retired batch forever)."""
        if m is None:
            return
        # memory high-waters, maintained at step end (slots is small, so
        # this stays inside the bulk-update budget): the gauges above
        # read them at scrape time. One pass over the slots for both
        # live positions and the active count — this runs every step,
        # and the obs_overhead contract prices a second genexpr sweep.
        live = 0
        n_act = 0
        for r in self._slot_req:
            if r is not None:
                live += r["prompt_len"] + len(r["emitted"])
                n_act += 1
        if live > self._kv_live_hw:
            self._kv_live_hw = live
        if n_act > self._active_hw:
            self._active_hw = n_act
        # batched registry feed (fields documented at construction): a
        # bucket switch flushes first so the whole batch shares one
        # dispatch-counter key; an idle pool flushes so totals are
        # exact the moment a drain returns
        bk = self._bucket_key()
        if bk is not self._obs_acc_bk:
            self._obs_flush(m)
            self._obs_acc_bk = bk
        self._obs_acc_steps += 1
        self._obs_acc_tokens += n_adv
        if samples:
            self._obs_acc_samples.extend(samples)
        if self._obs_acc_steps >= self._OBS_FLUSH_STEPS or n_act == 0:
            self._obs_flush(m)
        if (g := self.goodput) is not None:
            # live MFU/MBU numerators + the inter-token SLO window
            # (obs/goodput.py) — `live` is the summed live positions the
            # high-water bookkeeping above already computed
            g.on_decode_step(n_adv, live)
            if samples:
                g.on_inter_token(samples)

    #: step-obs batching cadence — same idea (and number) as
    #: StepClock.FLUSH_EVERY and goodput's _FLUSH_STEPS: a 60 s rate
    #: window and a human scrape cannot resolve a <100 ms batching
    #: delay, and the per-step bulk was the obs bill's largest line
    _OBS_FLUSH_STEPS = 32

    def _obs_flush(self, m):
        """Land the accumulated step counters / inter-token samples in
        ONE bulk registry update. Called by _obs_step_end every
        _OBS_FLUSH_STEPS steps, on a bucket switch (the batch shares
        one dispatch-counter key — _bucket_key memoizes, so the `is`
        check in the caller is exact), and whenever the pool goes idle
        (every drain ends flushed). Producer-thread only."""
        n = self._obs_acc_steps
        if not n:
            return
        if self._obs_acc_tokens:
            self._tps.add(self._obs_acc_tokens)
        samples = self._obs_acc_samples
        m.bulk(
            counters={"serving.decode_steps_total": n,
                      "serving.tokens_total": self._obs_acc_tokens,
                      self._obs_acc_bk: n},
            observations={"serving.inter_token_seconds": samples}
            if samples else None,
            gauge_fns=self._obs_gauges,
        )
        self._obs_acc_steps = 0
        self._obs_acc_tokens = 0
        if samples:
            self._obs_acc_samples = []

    def _tps_read(self) -> float:
        return self._tps.per_sec

    def _occupancy_read(self) -> float:
        return self.n_active / self.slots

    def _kv_util_read(self) -> float:
        # live KV positions over the current allocation; reads host
        # bookkeeping concurrently with the worker — transiently stale
        # values are fine for a gauge, and CPython list iteration over
        # `_slot_req` is safe against its element assignments
        live = sum(r["prompt_len"] + len(r["emitted"])
                   for r in self._slot_req if r is not None)
        return live / (self.slots * self._cache_len)

    def _kv_live_hw_read(self) -> float:
        return float(self._kv_live_hw)

    def _kv_bytes_read(self) -> float:
        # shape/dtype walk only — a scrape must never force a device sync
        from dnn_tpu.obs.mem import logical_nbytes

        return logical_nbytes(self.cache)

    def _active_hw_read(self) -> float:
        return float(self._active_hw)

    def _prefix_ratio_read(self) -> float:
        # lifetime hit ratio of the prefix-cache LOOKUP (prefilled=
        # adoptions never consult it); 0.0 before the first lookup —
        # what "no evidence yet" reads as on every other pool gauge
        looked = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / looked if looked else 0.0

    def _kvtier_blocks_read(self) -> float:
        s = self._prefix_store
        return float(s.n_blocks) if s is not None else 0.0

    def _kvtier_remote_ratio_read(self) -> float:
        # of all block-granular hits, the fraction served from blocks
        # MIGRATED in from a sibling replica — the fleet tier's working
        # number (0.0 on a replica that has never adopted anything)
        s = self._prefix_store
        if s is None or not s.block_hits:
            return 0.0
        return s.remote_block_hits / s.block_hits

    def _paged_used_read(self) -> float:
        return float(self._allocator.n_used)

    def _paged_free_read(self) -> float:
        return float(self._allocator.n_free)

    def _paged_hw_read(self) -> float:
        return float(self._allocator.high_water)

    def _obs_retire(self, req, reason: str):
        """Close a leaving request's decode span + outcome counter +
        flight event — the one block _retire_if_done and cancel share."""
        bs = req.get("b_span")
        if bs is not None:
            bs.end(tokens=len(req["emitted"]), reason=reason)
        m = obs.metrics()
        if m is not None:
            m.inc(labeled("serving.requests_total", outcome=reason))
            if (g := self.goodput) is not None:
                # availability SLO: a natural retirement (eos/stop/
                # length/constraint) served its caller; "cancelled"
                # covers both client abandonment and deadline eviction —
                # count it against the budget (the conservative side: a
                # burn alert on mass cancellation is signal, not noise)
                g.on_outcome(ok=reason != "cancelled")
        tr = req.get("trace")
        obs.flight.record("retire", rid=req["rid"], reason=reason,
                          tokens=len(req["emitted"]),
                          trace_id=tr.trace_id if tr else None)

    def _retire_if_done(self, slot: int):
        req = self._slot_req[slot]
        reason = None
        if self.eos_id is not None and req["emitted"][-1] == self.eos_id:
            reason = "eos"
        elif (n_stop := self._stop_match(req["emitted"], req["stop"])):
            reason = "stop"
        elif req.get("c_done"):
            reason = "constraint"
        elif len(req["emitted"]) >= req["budget"]:
            reason = "length"
        if reason is None:
            return
        emitted = req["emitted"]
        if reason == "stop":
            emitted = emitted[:-n_stop]  # the match itself is not returned
        rid = req["rid"]
        self.results[rid] = np.asarray(emitted, np.int32)
        self.finish_reasons[rid] = reason
        if req["logprobs"]:
            n = len(emitted)
            self.token_logprobs[rid] = {
                "chosen": np.asarray(req["lp"][:n], np.float32),
                "top_ids": np.stack([t[0] for t in req["lp_top"][:n]])
                if n else np.zeros((0, self._logprobs_k), np.int32),
                "top_logprobs": np.stack([t[1] for t in req["lp_top"][:n]])
                if n else np.zeros((0, self._logprobs_k), np.float32),
            }
        if self._prefix_store is not None \
                and req.get("ptoks") is not None and req["blocks"] \
                and not req["freed"]:
            # retire-time insertion (the chat-follow-up win): this
            # request's transcript KV — prompt plus every FED decode
            # token (the last sampled token was never fed, so its
            # position holds nothing) — is sitting in blocks about to
            # be released. Inserting the full-block path into the
            # radix store keeps them resident, so turn N+1's prompt
            # (= turn N's transcript + the new user message) adopts
            # them instead of re-prefilling the whole conversation.
            fed = req["prompt_len"] + len(req["emitted"]) - 1
            n_cover = min(fed // self._block_len, len(req["blocks"]))
            if n_cover:
                toks = np.concatenate([
                    np.asarray(req["ptoks"], np.int32),
                    np.asarray(req["emitted"][:-1], np.int32)])
                self._prefix_store.insert(
                    toks[: n_cover * self._block_len],
                    req["blocks"][:n_cover],
                    origin=req.get("borig") or [])
        if req["blocks"]:
            # windowed pools already reclaimed the rolled-out prefix
            self._allocator.free(req["blocks"][req["freed"]:])
            self._pool_exhausted_episode = False  # blocks came free
        self._release_slot_constraint(slot, req)
        self._slot_req[slot] = None
        self.active = self.active.at[slot].set(False)
        self._obs_retire(req, reason)

    def _note_constrained(self, delta: int):
        """Track the live constrained-slot count and mirror it onto the
        attached StepClock's scrape-time gauge (obs/timeline.py)."""
        self._n_constrained += delta
        sc = self.step_clock
        if sc is not None:
            sc.constrained_slots = self._n_constrained

    def _release_slot_constraint(self, slot: int, req: dict):
        """Drop a retiring slot's constraint: refcount down, device
        state-row back to the reserved all-allowed row 0 (a functional
        edit of the CURRENT crow buffer — under overlap that is the
        in-flight step's OUTPUT, already unpacked at dispatch, so the
        reset lands before the next dispatch reads it)."""
        c = req.get("constraint")
        if c is None:
            return
        self._ctab_release(c)
        self._crow = self._crow.at[slot].set(0)
        self._note_constrained(-1)

    def claim(self, rid: int):
        """Pop a finished (or cancelled) request's COMPLETE record —
        (tokens or None, finish_reason, token_logprobs or None) —
        releasing all host-side bookkeeping for it. Long-running servers
        (the LM daemon) must claim rather than read `results` directly,
        or the per-request dicts grow without bound. A cancelled rid
        yields (None, "cancelled", None). KeyError for an
        unknown/unfinished rid."""
        tokens = self.results.pop(rid, None)
        reason = self.finish_reasons.pop(rid, None)
        lps = self.token_logprobs.pop(rid, None)
        if tokens is None and reason is None:
            raise KeyError(rid)
        return tokens, reason or "length", lps

    def first_token(self, rid: int):
        """The token sampled during a request's prefill (the first entry of
        its emitted stream), or None for an unknown rid — the streaming
        front needs it before the first step() (budget == 1 requests are
        already retired into results by then)."""
        if rid in self.results:
            return int(self.results[rid][0])
        for req in self._slot_req:
            if req is not None and req["rid"] == rid:
                if not req["emitted"]:
                    # interleaved admission: still prefilling, or the
                    # fused finish's first token has not committed yet —
                    # the caller picks it up from a later step()'s output
                    return None
                return int(req["emitted"][0])
        return None

    def cancel(self, rid: int) -> bool:
        """Retire a request's slot WITHOUT producing a result — the slot
        re-enters the free pool immediately (the next admit overwrites its
        cache rows; nothing needs clearing because inactive slots are fully
        masked in the decode program). Safe between step() calls (host
        bookkeeping only). Returns True if the request was live (slot
        freed) or still unclaimed in results (result dropped); False for
        an unknown/already-claimed rid."""
        for slot, req in enumerate(self._slot_req):
            if req is not None and req["rid"] == rid:
                if req.get("pending") is not None:
                    # cancelled while its interleaved prefill waited:
                    # drop the queue entry too (the chunk folder skips
                    # dead slots defensively, but never growing the
                    # queue with corpses is cheaper)
                    self._pending_q = [s for s in self._pending_q
                                       if s != slot]
                if req["blocks"]:
                    self._allocator.free(req["blocks"][req["freed"]:])
                    self._pool_exhausted_episode = False  # blocks came free
                self._release_slot_constraint(slot, req)
                self._slot_req[slot] = None
                self.active = self.active.at[slot].set(False)
                self.finish_reasons[rid] = "cancelled"
                self._obs_retire(req, "cancelled")
                return True
        if rid in self.results:
            # cancelling an already-finished, unclaimed request drops its
            # WHOLE record (reason + logprobs too, or they leak forever)
            del self.results[rid]
            self.finish_reasons.pop(rid, None)
            self.token_logprobs.pop(rid, None)
            return True
        return False

    def _ilv_next(self):
        """Front pending slot's next-chunk descriptor, or None. Skips
        (and dequeues) slots whose pending request was cancelled while
        it waited."""
        while self._pending_q:
            slot = self._pending_q[0]
            req = self._slot_req[slot]
            if req is None or req.get("pending") is None:
                self._pending_q.pop(0)
                continue
            p = req["pending"]
            c = p["next"]
            p_c = self._ilv
            return {"slot": slot, "req": req, "p": p,
                    "chunk": jnp.asarray(
                        p["padded"][:, c * p_c:(c + 1) * p_c]),
                    "start": jnp.int32(c * p_c),
                    "last": c + 1 == p["n_chunks"]}
        return None

    def _ilv_after_chunk(self, ilv, pf_logits, new_row, s_idx):
        """Bookkeeping after a mixed step's prefill leg: stash the grown
        row, or — on the final chunk — dispatch the FUSED finish
        (install + on-device first-token sample + slot-state scatter,
        one program) and defer the first token's readback to the next
        step's commit: admission never blocks on a device->host sync."""
        req, p, slot = ilv["req"], ilv["p"], ilv["slot"]
        self.prefill_chunks_run += 1
        m = obs.metrics()
        if m is not None:
            m.inc("serving.prefill_chunks_total")
        if not ilv["last"]:
            p["row"] = new_row
            p["next"] += 1
            return
        self._pending_q.pop(0)
        fin = self._ilv_finish(
            self.cache, new_row, pf_logits,
            jnp.int32(p["last_local"]), jnp.int32(slot),
            p["prefill_key"], p["slot_key"],
            self.pos, self.tok, self.active, self.keys,
            self._temp, self._topk, self._topp, self._minp, self._rep,
            self._seen, self._bias,
            jnp.float32(p["t"]), jnp.int32(p["k"]), jnp.float32(p["p"]),
            jnp.float32(p["mp"]), jnp.float32(p["rp"]),
            p["seen_row"], p["b_row"],
            jnp.int32(req["prompt_len"]), p["install_ids"],
            self._crow, jnp.int32(p["c_row"]),
            self._ctable, self._ctrans)
        (self.cache, self.pos, self.tok, self.active, self.keys,
         self._temp, self._topk, self._topp, self._minp, self._rep,
         self._seen, self._bias, self._crow, first) = fin[:14]
        req["first_dev"] = (first, fin[14:] if req["logprobs"] else None)
        req["install_step"] = s_idx
        del req["pending"]

    def _commit_step(self, s_idx, toks, c_lp, t_lp, t_ids, rec, sc):
        """Commit one completed step's tokens to host bookkeeping.
        `s_idx` names the DISPATCH this data came from: a slot whose
        fused admission finish landed at install_step >= s_idx had no
        decode leg in that dispatch, so its row of `toks` is garbage
        and is skipped; the first commit past the install materializes
        the deferred first token (and its logprobs) ahead of the
        step's own token. Returns {rid: token | [tokens]} (a list when
        the deferred first commits together with a decode token)."""
        m = obs.metrics()
        t_now = time.perf_counter() if m is not None else 0.0
        n_adv = 0
        it_samples: list = []
        out = {}
        for slot, req in enumerate(self._slot_req):
            if req is None or req.get("pending") is not None:
                continue
            inst = req.get("install_step")
            committed: list = []
            if inst is not None:
                if s_idx <= inst:
                    continue  # this step's dispatch predates the install
                del req["install_step"]
                fd = req.pop("first_dev", None)
                if fd is not None:  # deferred interleaved first token
                    first, f_lp = fd
                    tok0 = int(np.asarray(first))
                    req["emitted"].append(tok0)
                    if req["logprobs"]:
                        req["lp"].append(float(np.asarray(f_lp[0])[0]))
                        req["lp_top"].append(
                            (np.asarray(f_lp[2])[0],
                             np.asarray(f_lp[1])[0]))
                    committed.append(tok0)
                    if m is not None and (g := self.goodput) is not None:
                        # prefill goodput is credited when its first
                        # token commits (the convoy path: at submit)
                        g.on_prefill(req["prompt_len"])
                    if "constraint" in req:
                        # host mirror of the walk the fused finish
                        # already did on device (finish detection only)
                        self._constraint_advance(slot, tok0)
                    self._free_rolled_blocks(slot)
                    self._retire_if_done(slot)
            if self._slot_req[slot] is req:
                token = int(toks[slot])
                req["emitted"].append(token)
                if req["logprobs"]:
                    req["lp"].append(float(c_lp[slot]))
                    req["lp_top"].append((t_ids[slot], t_lp[slot]))
                committed.append(token)
                self._obs_commit(req, m, t_now, n_new=len(committed),
                                 samples=it_samples)
                if "constraint" in req:
                    # host mirror of the device walk — finish
                    # detection only, never a device write
                    self._constraint_advance(slot, token)
                self._free_rolled_blocks(slot)  # windowed pools reclaim
                self._retire_if_done(slot)
            if committed:
                n_adv += len(committed)
                out[req["rid"]] = (committed[0] if len(committed) == 1
                                   else committed)
        if rec is not None:
            rec.marks.append(("commit", time.perf_counter()))
        self._obs_step_end(m, n_adv, it_samples)
        if rec is not None:
            rec.marks.append(("obs", time.perf_counter()))
            sc.end(rec, n_adv)
        return out

    def _lp_host(self, lp_refs):
        if lp_refs is None:
            return None, None, None
        return (np.asarray(lp_refs[0]), np.asarray(lp_refs[1]),
                np.asarray(lp_refs[2]))

    def _uncommitted_need(self, lag_per_step: int) -> int:
        """Furthest position count the next dispatch's writes need
        covered, including tokens the host has NOT committed yet: a
        deferred interleaved first token, plus `lag_per_step` positions
        per uncommitted in-flight step under overlap (1 for the dense
        step, spec_k+1 for a speculative chunk). One definition shared
        by both step loops — an under-grown bucket silently clamps the
        device write, so this formula must not drift per batcher.
        Returns 0 when nothing decodes (pending-only pools)."""
        need = 0
        for req in self._slot_req:
            if req is None or req.get("pending") is not None:
                continue
            u = 1 if "first_dev" in req else 0
            need = max(need, req["prompt_len"] + len(req["emitted"]) + u)
        if need and self._inflight is not None:
            need += lag_per_step
        return need

    def _pipeline_fill_end(self, rec, sc):
        """Close a step record for a pipeline-FILLING dispatch (the
        overlap pipeline's first call: a step went out, nothing commits
        yet) — shared by the dense and speculative step loops so the
        StepClock phase protocol stays identical across batchers."""
        if rec is not None:
            t = time.perf_counter()
            rec.marks.append(("wait", t))
            rec.marks.append(("commit", t))
        self._obs_step_end(obs.metrics(), 0, None)
        if rec is not None:
            rec.marks.append(("obs", time.perf_counter()))
            sc.end(rec, 0)
        return {}

    def flush_overlap(self) -> Dict[int, int]:
        """Commit the trailing in-flight step (overlap mode); {} and a
        no-op otherwise. drain() calls it once the pool empties, and
        the idle lm_server worker calls it so the final dispatched
        step's bookkeeping (its StepClock record, tokens past
        retirement) never dangles across an idle period."""
        if self._inflight is None:
            return {}
        sc = self.step_clock
        rec = sc.begin() if sc is not None else None
        p_idx, p_tok, p_lps = self._inflight
        self._inflight = None
        toks = np.asarray(p_tok)
        c_lp, t_lp, t_ids = self._lp_host(p_lps)
        if rec is not None:
            rec.marks.append(("wait", time.perf_counter()))
        return self._commit_step(p_idx, toks, c_lp, t_lp, t_ids, rec, sc)

    def step(self) -> Dict[int, int]:
        """One decode step for every active slot. Returns {rid: token}
        for slots that advanced ({rid: [tokens]} when an interleaved
        admission's deferred first token commits in the same call);
        finished requests move to .results. With overlap=True the call
        DISPATCHES step N and commits step N-1 — tokens surface one
        call later (drain()/flush_overlap() commit the trailing step)."""
        if self.n_active == 0:
            return self.flush_overlap()
        # step-timeline phase clock (obs/timeline.py): rec is None when
        # no clock is attached OR the obs gate is off — every later
        # site is one None check
        sc = self.step_clock
        rec = sc.begin() if sc is not None else None
        if self._buckets is not None:
            # this step writes each active slot's next position; cover
            # the furthest one, host-uncommitted tokens included
            # (_uncommitted_need: deferred interleaved firsts + one
            # position per in-flight step under overlap)
            need = self._uncommitted_need(1)
            if need:
                self._ensure_cache_len(need)
        ilv = self._ilv_next() if self._ilv else None
        if rec is not None:
            rec.marks.append(("host", time.perf_counter()))
        # host annotation: a POST /profilez capture shows each pool step
        # as a named block on the host track (obs/profile.annotation_ctx
        # — the non-generator form; ~6 µs on / ~0.2 µs off, inside the
        # <2% obs budget)
        # one shared positional block for both dispatch forms — the
        # mixed program's decode leg takes the decode step's exact
        # argument order (donate_argnums indices align by construction)
        state = (self.cache, self.pos, self.tok, self.active, self.keys,
                 self._temp, self._topk, self._topp, self._minp,
                 self._rep, self._seen, self._bias, self._crow,
                 self._ctable, self._ctrans)
        with _prof_annotation("serving.decode_step"):
            if ilv is None:
                res = self._decode(self._decode_view, *state)
            else:
                res = self._mixed(
                    self._decode_view,
                    self._lora_prefill_view(ilv["p"]["aid"]), *state,
                    ilv["p"]["row"], ilv["chunk"], ilv["start"])
                res, pf_logits, new_row = res[:-2], res[-2], res[-1]
        # drop the tuple's references to the just-donated buffers NOW:
        # holding them to frame teardown makes their deletion run after
        # the step record closes, and deleting a donated-but-pending
        # buffer blocks on the in-flight computation — measured as ~a
        # device-step of unattributed dark time per call (the step
        # timeline probe's coverage assert caught it)
        del state
        if rec is not None:
            rec.marks.append(("dispatch", time.perf_counter()))
            rec.mixed = ilv is not None
        lp_refs = None
        if self._logprobs_k:
            (self.cache, self.pos, self.tok, self.keys, self._seen,
             self._crow, c_lp_d, t_lp_d, t_ids_d) = res
            lp_refs = (c_lp_d, t_lp_d, t_ids_d)
        else:
            (self.cache, self.pos, self.tok, self.keys, self._seen,
             self._crow) = res
        s_idx = self._step_idx
        self._step_idx += 1
        if ilv is not None:
            self._ilv_after_chunk(ilv, pf_logits, new_row, s_idx)
        if self._overlap:
            if sc is not None:
                sc.overlap_depth = 1
            # snapshot THIS step's committed tokens before the next
            # dispatch donates their buffer: jnp.copy enqueues its read
            # ahead of the donation, and in-order device execution
            # makes the copied value safe. The logprob outputs are
            # never fed back (hence never donated) — bare refs suffice.
            keep = (s_idx, jnp.copy(self.tok), lp_refs)
            prev, self._inflight = self._inflight, keep
            if prev is None:
                return self._pipeline_fill_end(rec, sc)
            p_idx, p_tok, p_lps = prev
            toks = np.asarray(p_tok)
            c_lp, t_lp, t_ids = self._lp_host(p_lps)
            if rec is not None:
                # with the pipeline live, "wait" is only the RESIDUAL
                # unhidden device time of step N-1 — the hiding the
                # dispatch_slack gauge predicted, verified here
                rec.marks.append(("wait", time.perf_counter()))
            return self._commit_step(p_idx, toks, c_lp, t_lp, t_ids,
                                     rec, sc)
        toks = np.asarray(self.tok)
        c_lp, t_lp, t_ids = self._lp_host(lp_refs)
        if rec is not None:
            # the np.asarray above is the per-token device->host sync:
            # dispatch-return -> committed-tokens-on-host is the "wait"
            rec.marks.append(("wait", time.perf_counter()))
        return self._commit_step(s_idx, toks, c_lp, t_lp, t_ids, rec, sc)

    def drain(self) -> Dict[int, np.ndarray]:
        """Run until every submitted request finishes; returns .results."""
        while self.n_active:
            self.step()
        self.flush_overlap()
        return self.results
