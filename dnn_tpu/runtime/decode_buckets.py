"""Length-aware bucketed decode: cache views sized to the LIVE context.

Decode is bandwidth-bound, and the XLA decode step reads the whole
preallocated cache every step — at `max_len` allocation with a short live
context, bytes/step are proportional to the ALLOCATION, not the position
(the prime suspect behind the 13%-MBU long-context row, BASELINE.md).
The Pallas decode kernel fixes this on TPU by clamping its cache fetches
at the live limit (ops/pallas/cached_attention.decode_attention); this
module is the portable XLA-side counterpart:

  * compile the decode step against a small LADDER of contiguous
    cache-view lengths (powers of two up to `max_len`);
  * HOST-side dispatch picks the smallest bucket covering the batch's
    furthest live position, pads the cache up when a sequence grows
    through a bucket edge, and runs the step program compiled for that
    bucket — so per-step cache bytes track the live context;
  * token identity is preserved across bucket boundaries by construction:
    a bucket view differs from the full allocation only in columns BEYOND
    every row's position limit, and the band mask already zeroes those
    columns' probability mass exactly (appended zero terms in the
    contractions change no partial sum), so greedy streams are
    bit-identical to the unbucketed program (tests/test_decode_buckets.py
    pins this for f32, bf16, and int8 caches, through a bucket edge).

Two consumers: `make_bucketed_generate` (the solo host-loop decoder —
also the `decode_bucketing` benchmark's subject, benchmarks/run_all.py)
and `ContinuousBatcher(decode_buckets=...)` (runtime/serving.py), whose
pool grows bucket-by-bucket as its slots advance. Compiled-program count
is bounded by the ladder length (one step program per live bucket), a
deliberate, bounded relaxation of the serving three-program contract.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["DEFAULT_MIN_BUCKET", "bucket_ladder", "bucket_for",
           "normalize_ladder", "pad_cache_to", "make_bucketed_generate"]

DEFAULT_MIN_BUCKET = 64

# every dense codec leaf carries positions at axis 3: K/V (L, B, H, S, D)
# and the int8 scales (L, B, H, S) alike (runtime/kvcache.py)
_POS_AXIS = 3


def bucket_ladder(max_len: int, min_bucket: int = DEFAULT_MIN_BUCKET):
    """Powers of two from `min_bucket` up, terminated at `max_len`
    (always the top rung, whatever its divisibility): e.g.
    bucket_ladder(1536) -> (64, 128, 256, 512, 1024, 1536)."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    if min_bucket < 1:
        raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
    b = 1
    while b < min_bucket:
        b *= 2
    out = []
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


def normalize_ladder(buckets: Sequence[int], max_len: int):
    """Validate an explicit ladder: ascending positive ints, entries
    beyond `max_len` dropped, `max_len` appended as the top rung when
    missing (the full allocation must be reachable)."""
    out = []
    for b in buckets:
        b = int(b)
        if b < 1:
            raise ValueError(f"bucket lengths must be >= 1, got {b}")
        if out and b <= out[-1]:
            raise ValueError(f"bucket ladder must ascend, got {buckets}")
        if b < max_len:
            out.append(b)
    out.append(max_len)
    return tuple(out)


def bucket_for(ladder: Sequence[int], need: int) -> int:
    """Smallest ladder bucket holding `need` live positions."""
    for b in ladder:
        if b >= need:
            return b
    raise ValueError(
        f"{need} positions exceed the ladder's top bucket {ladder[-1]}")


def pad_cache_to(cache, n: int):
    """Grow every cache leaf's position axis to `n` columns (zeros).
    The new columns sit beyond every live position limit, so the band
    mask excludes them until a write claims them — padding is
    attention-invisible. Callers jit this with `n` static (one compiled
    grow program per (from, to) bucket pair)."""
    def pad(a):
        grow = n - a.shape[_POS_AXIS]
        if grow < 0:
            raise ValueError(
                f"cannot shrink a cache leaf from {a.shape[_POS_AXIS]} "
                f"to {n} positions (buckets grow only)")
        if grow == 0:
            return a
        widths = [(0, 0)] * a.ndim
        widths[_POS_AXIS] = (0, grow)
        return jnp.pad(a, widths)

    return {k: pad(v) for k, v in cache.items()}


def make_bucketed_generate(cfg, *, max_len: int, max_new_tokens: int,
                           buckets=None, temperature: float = 0.0,
                           top_k: Optional[int] = None,
                           top_p: Optional[float] = None,
                           min_p: Optional[float] = None,
                           compute_dtype=None, kv_dtype=None, ffn=None,
                           attn_kernel="auto", family: str = "gpt"):
    """Host-dispatched bucketed decoder: generate(prepared, ids, rng) ->
    (B, max_new_tokens), token-identical to the family's scan-based
    decoder (generate.make_generate / llama.make_generate) but with the
    cache allocated at `max_len`-serving semantics AND per-step bytes
    tracking the live position via the bucket ladder.

    `max_len` is the serving allocation the ladder tops out at (the
    batcher's preallocation; prompt + max_new_tokens must fit inside
    it). `buckets=None` takes the power-of-two ladder; an explicit
    ascending tuple overrides it; `(max_len,)` degenerates to the
    UNBUCKETED program — the A/B baseline the `decode_bucketing`
    benchmark and the parity tests compare against. `family` picks the
    cached forward: "gpt" (runtime/generate.forward_with_cache) or
    "llama" (models/llama.forward_with_cache — dense caches only; a
    sliding-window config already decodes O(window) on the rolling ring
    and is rejected here).

    rng discipline matches the scan decoders split-for-split, so sampled
    streams agree draw-for-draw, not just greedy ones."""
    from dnn_tpu.runtime.generate import _sample

    if attn_kernel == "auto":
        # bucketing IS the length-aware dispatch: the allocation already
        # tracks the live position, and letting "auto" flip einsum ->
        # Pallas kernel as a stream grows past AUTO_KERNEL_MIN_S would
        # change attention implementations MID-STREAM — breaking the
        # bit-identity-to-the-unbucketed-program guarantee this module
        # documents. Explicit True/"interpret" remain available for
        # callers who accept that trade.
        attn_kernel = False

    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if max_len < 2:
        raise ValueError(f"max_len must be >= 2, got {max_len}")
    if max_len > cfg.block_size:
        raise ValueError(
            f"max_len {max_len} exceeds block_size {cfg.block_size}")
    ladder = (bucket_ladder(max_len) if buckets is None
              else normalize_ladder(buckets, max_len))

    if family == "gpt":
        from dnn_tpu.runtime import generate as _gen

        init_cache = _gen.init_cache

        def _forward(prepared, ids, cache, start):
            return _gen.forward_with_cache(
                prepared, ids, cache, start, cfg=cfg,
                compute_dtype=compute_dtype, ffn=ffn,
                attn_kernel=attn_kernel)
    elif family == "llama":
        from dnn_tpu.models import llama as _llama

        if cfg.sliding_window is not None and not cfg.alt_window:
            raise ValueError(
                "sliding-window configs decode O(window) on the rolling "
                "ring (llama.make_generate) — bucketing targets the "
                "dense full-length cache")
        init_cache = _llama.init_cache

        def _forward(prepared, ids, cache, start):
            return _llama.forward_with_cache(
                prepared, ids, cache, start, cfg=cfg,
                compute_dtype=compute_dtype, ffn=ffn,
                attn_kernel=attn_kernel)
    else:
        raise ValueError(f"unknown family {family!r} (gpt|llama)")

    cache_dtype = (kv_dtype if kv_dtype is not None
                   else (compute_dtype or jnp.float32))

    # donate the prefill cache too: the freshly-initialized allocation is
    # written once and returned — without aliasing the write is a full
    # extra copy of the first bucket (same contract as _step's donation)
    @functools.partial(jax.jit, donate_argnums=(2,))
    def _prefill(prepared, ids, cache):
        logits, cache = _forward(prepared, ids, cache, 0)
        return logits[:, -1], cache

    @functools.partial(jax.jit, donate_argnums=(1,))
    def _step(prepared, cache, tok, pos, rng):
        # one compiled program PER BUCKET (cache shape); `pos` is a
        # traced scalar, so every step of a bucket shares its program.
        # The named_scope is trace-time only: device profiles name each
        # bucket's step program (obs/profile.py)
        bucket = jax.tree.leaves(cache)[0].shape[_POS_AXIS]
        with jax.named_scope(f"decode_buckets.step_b{bucket}"):
            logits, cache = _forward(prepared, tok[:, None], cache, pos)
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature=temperature,
                          top_k=top_k, top_p=top_p, min_p=min_p)
            return cache, nxt, rng

    # no donation: a pad's output never fits the input buffer, and the
    # unusable-donation warning would fire on every bucket crossing
    _grow = jax.jit(pad_cache_to, static_argnums=(1,))

    def generate(prepared, ids, rng):
        ids = jnp.asarray(ids)
        b, t = ids.shape
        if t + max_new_tokens > max_len:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {max_len}")
        n = bucket_for(ladder, t)
        cache = init_cache(cfg, b, n, cache_dtype)
        logits_last, cache = _prefill(prepared, ids, cache)
        rng, sub = jax.random.split(rng)
        tok = _sample(logits_last, sub, temperature=temperature,
                      top_k=top_k, top_p=top_p, min_p=min_p)
        toks = [tok]
        # bucket dispatch/grow tallied locally and flushed to the obs
        # registry AFTER the loop — the decode loop itself stays free of
        # per-step lock traffic (dnn_tpu/obs overhead budget)
        dispatch: dict = {}
        grows = 0
        for i in range(max_new_tokens - 1):
            pos = t + i  # this step's cache-write position
            nb = bucket_for(ladder, pos + 1)
            if nb != n:
                cache = _grow(cache, nb)
                n = nb
                grows += 1
            cache, tok, rng = _step(prepared, cache, tok,
                                    jnp.int32(pos), rng)
            dispatch[n] = dispatch.get(n, 0) + 1
            toks.append(tok)
        from dnn_tpu import obs

        m = obs.metrics()
        if m is not None:
            from dnn_tpu.utils.metrics import labeled

            # same metric family as ContinuousBatcher (the README's
            # documented names): bucket-ladder activity is one concept
            # whether the pool or the solo decoder drives it
            for bk, cnt in dispatch.items():
                m.inc(labeled("serving.decode_bucket_dispatch_total",
                              bucket=bk), cnt)
            if grows:
                m.inc("serving.decode_bucket_grow_total", grows)
        return jnp.stack(toks, axis=1)

    generate.buckets = ladder
    return generate
