"""Constrained (structured) decoding: regex/JSON-grammar output masks.

The modern serving feature the reference cannot express (its one forward
returns a single tensor, node.py:137-200): force a model's COMPLETION to
match a formal language — "JSON mode", tool-call schemas, enum picks —
by masking disallowed tokens at every step.

Design (the outlines/guided-decoding construction, TPU-shaped):

  1. A practical REGEX SUBSET compiles to a byte-level DFA at request
     -construction time (Thompson NFA -> subset construction). Supported:
     literals (UTF-8, multi-byte ok), escapes (\\d \\w \\s \\D \\W \\S,
     \\n \\t \\r and escaped metachars), '.', char classes [a-z0-9_],
     [^...], groups (...), alternation |, and repetition * + ? {m} {m,}
     {m,n}. Matches are FULL-string (anchors are implicit).
  2. The DFA is lifted from bytes to TOKENS once per (pattern, vocab):
     walk every vocab token's byte string through the DFA from every
     state via one trie pass — `table[s, t]` = end state or -1
     (disallowed). This is the only vocab-sized work, and it is
     per-pattern, host-side, cacheable.
  3. Per decode step the serving layer reads `mask_row(state)` — a (V,)
     f32 row of 0 / -1e30 — and ADDS it to the slot's logit-bias row,
     which is already a dynamic input of the compiled decode program
     (runtime/serving.py `_bias`). Masking therefore changes NO compiled
     program: the DFA advances on the host (one int per committed
     token), the device sees only a fresh bias row. EOS is allowed
     exactly in accepting states, so a sampled stop always yields a
     complete match.

  Cost note: the serving layer keeps each grammar's (S, V) allowed
  table DEVICE-RESIDENT (uploaded once per grammar into a bool row
  pool, `mask_table` below) and indexes it with a per-slot DFA-state
  vector inside the compiled decode program — per-step host->device
  traffic is one int32 per slot (the state vector), not a (V,) f32 row
  per constrained slot (~200 KB at GPT-2 vocab, the round-4 design
  this replaced). The host still walks the DFA (one int per committed
  token) for finish detection; the device never waits on it.

Bounded-depth JSON ("JSON mode") ships as `json_regex(max_depth)`:
regular languages cannot nest unboundedly, so the value grammar is
expanded to a fixed depth — the standard guided-decoding trade, stated
rather than hidden.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

NEG_BIG = -1e30

# ----------------------------------------------------------------------
# regex parser -> NFA (Thompson construction)
# ----------------------------------------------------------------------

_ANY = frozenset(range(256)) - {ord("\n")}  # '.' (newline excluded)
_DIGIT = frozenset(range(ord("0"), ord("9") + 1))
_WORD = (frozenset(range(ord("a"), ord("z") + 1))
         | frozenset(range(ord("A"), ord("Z") + 1)) | _DIGIT | {ord("_")})
_SPACE = frozenset(b" \t\n\r\f\v")
_ESC_CLASS = {
    "d": _DIGIT, "D": frozenset(range(256)) - _DIGIT,
    "w": _WORD, "W": frozenset(range(256)) - _WORD,
    "s": _SPACE, "S": frozenset(range(256)) - _SPACE,
}
_ESC_CHAR = {"n": ord("\n"), "t": ord("\t"), "r": ord("\r"),
             "f": ord("\f"), "v": ord("\v"), "0": 0}


class _Nfa:
    """States hold edges [(byteset | None, target)]; None = epsilon."""

    def __init__(self):
        self.edges: List[List[Tuple[Optional[frozenset], int]]] = []

    def state(self) -> int:
        self.edges.append([])
        return len(self.edges) - 1

    def edge(self, a: int, sym: Optional[frozenset], b: int):
        self.edges[a].append((sym, b))


class _Parser:
    """Recursive descent over the pattern; every production returns an
    NFA fragment (start, end) with a single entry and exit state."""

    def __init__(self, pattern: str, nfa: _Nfa):
        self.p = pattern
        self.i = 0
        self.nfa = nfa

    def _peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def _take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def _error(self, msg: str):
        raise ValueError(f"regex error at offset {self.i} "
                         f"in {self.p!r}: {msg}")

    # alternation := concat ('|' concat)*
    def alternation(self) -> Tuple[int, int]:
        frags = [self.concat()]
        while self._peek() == "|":
            self._take()
            frags.append(self.concat())
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.state(), self.nfa.state()
        for fs, fe in frags:
            self.nfa.edge(s, None, fs)
            self.nfa.edge(fe, None, e)
        return s, e

    def concat(self) -> Tuple[int, int]:
        frags = []
        while self._peek() is not None and self._peek() not in "|)":
            frags.append(self.repeat())
        if not frags:  # empty branch (e.g. "a|" or "()")
            s = self.nfa.state()
            return s, s
        s, e = frags[0]
        for fs, fe in frags[1:]:
            self.nfa.edge(e, None, fs)
            e = fe
        return s, e

    def repeat(self) -> Tuple[int, int]:
        frag = self.atom()
        while self._peek() in ("*", "+", "?", "{"):
            op = self._peek()
            if op == "{":
                save = self.i
                bounds = self._try_bounds()
                if bounds is None:
                    self.i = save
                    break  # literal '{' already consumed by atom? no —
                    # atom treats '{' as literal only via escape; a bare
                    # '{' that isn't a bound is an error below
                lo, hi = bounds
                frag = self._repeat_bounded(frag, lo, hi)
            else:
                self._take()
                s, e = self.nfa.state(), self.nfa.state()
                fs, fe = frag
                self.nfa.edge(s, None, e) if op in "*?" else None
                self.nfa.edge(s, None, fs)
                self.nfa.edge(fe, None, e)
                if op in "*+":
                    self.nfa.edge(fe, None, fs)
                frag = (s, e)
        return frag

    def _try_bounds(self) -> Optional[Tuple[int, Optional[int]]]:
        """Parse '{m}', '{m,}', '{m,n}' after the opening brace; None if
        the text is not a bound (caller treats '{' literally)."""
        assert self._take() == "{"
        j = self.i
        digits = ""
        while j < len(self.p) and self.p[j].isdigit():
            digits += self.p[j]
            j += 1
        if not digits:
            return None
        lo = int(digits)
        hi: Optional[int] = lo
        if j < len(self.p) and self.p[j] == ",":
            j += 1
            d2 = ""
            while j < len(self.p) and self.p[j].isdigit():
                d2 += self.p[j]
                j += 1
            hi = int(d2) if d2 else None
        if j >= len(self.p) or self.p[j] != "}":
            return None
        self.i = j + 1
        if hi is not None and hi < lo:
            self._error(f"bad repetition bound {{{lo},{hi}}}")
        return lo, hi

    def _clone(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        """Deep-copy a fragment's subgraph (bounded repetition expands by
        copying — fragments are small; patterns with huge bounds should
        restructure)."""
        fs, fe = frag
        # collect reachable states (fe seeded explicitly: every Thompson
        # fragment reaches its exit, but the invariant is free to assert)
        seen = {fs, fe}
        stack = [fs, fe]
        while stack:
            s = stack.pop()
            for _, t in self.nfa.edges[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        mapping = {s: self.nfa.state() for s in seen}
        for s in seen:
            for sym, t in self.nfa.edges[s]:
                self.nfa.edge(mapping[s], sym, mapping[t])
        return mapping[fs], mapping[fe]

    def _repeat_bounded(self, frag, lo: int, hi: Optional[int]):
        s = self.nfa.state()
        e = s
        for _ in range(lo):
            fs, fe = self._clone(frag)
            self.nfa.edge(e, None, fs)
            e = fe
        if hi is None:  # {m,} — a trailing star
            fs, fe = self._clone(frag)
            star_s, star_e = self.nfa.state(), self.nfa.state()
            self.nfa.edge(star_s, None, star_e)
            self.nfa.edge(star_s, None, fs)
            self.nfa.edge(fe, None, star_e)
            self.nfa.edge(fe, None, fs)
            self.nfa.edge(e, None, star_s)
            e = star_e
        else:
            for _ in range(hi - lo):
                fs, fe = self._clone(frag)
                opt_e = self.nfa.state()
                self.nfa.edge(e, None, fs)
                self.nfa.edge(e, None, opt_e)  # skip
                self.nfa.edge(fe, None, opt_e)
                e = opt_e
        return s, e

    def atom(self) -> Tuple[int, int]:
        ch = self._peek()
        if ch is None:
            self._error("unexpected end of pattern")
        if ch == "(":
            self._take()
            frag = self.alternation()
            if self._peek() != ")":
                self._error("unbalanced '('")
            self._take()
            return frag
        if ch == "[":
            return self._frag(self._char_class())
        if ch == ".":
            self._take()
            return self._frag(_ANY)
        if ch == "\\":
            return self._frag(self._escape())
        if ch in "*+?)|":
            self._error(f"unexpected {ch!r}")
        if ch == "{":
            self._error("bare '{' (escape it as \\{ or use {m,n} after "
                        "an atom)")
        # literal char — non-ASCII expands to its UTF-8 byte sequence
        self._take()
        bs = ch.encode("utf-8")
        s = self.nfa.state()
        e = s
        for b in bs:
            nxt = self.nfa.state()
            self.nfa.edge(e, frozenset({b}), nxt)
            e = nxt
        return s, e

    def _frag(self, byteset: frozenset) -> Tuple[int, int]:
        s, e = self.nfa.state(), self.nfa.state()
        self.nfa.edge(s, byteset, e)
        return s, e

    def _escape(self) -> frozenset:
        assert self._take() == "\\"
        ch = self._peek()
        if ch is None:
            self._error("dangling backslash")
        self._take()
        if ch in _ESC_CLASS:
            return _ESC_CLASS[ch]
        if ch in _ESC_CHAR:
            return frozenset({_ESC_CHAR[ch]})
        if ch == "x":
            hx = self.p[self.i:self.i + 2]
            if len(hx) != 2:
                self._error("\\x needs two hex digits")
            try:
                v = int(hx, 16)
            except ValueError:
                self._error(f"bad hex escape \\x{hx}")
            self.i += 2
            return frozenset({v})
        if ord(ch) < 128:  # escaped metachar / punctuation
            return frozenset({ord(ch)})
        self._error(f"unsupported escape \\{ch}")

    def _char_class(self) -> frozenset:
        assert self._take() == "["
        negate = False
        if self._peek() == "^":
            negate = True
            self._take()
        members: set = set()
        first = True
        while True:
            ch = self._peek()
            if ch is None:
                self._error("unbalanced '['")
            if ch == "]" and not first:
                self._take()
                break
            first = False
            if ch == "\\":
                sub = self._escape()
                if len(sub) > 1:  # class escape like \d inside [...]
                    members |= sub
                    continue
                lo = next(iter(sub))
            else:
                self._take()
                bs = ch.encode("utf-8")
                if len(bs) > 1:
                    self._error("non-ASCII in char class (use "
                                "alternation of literals instead)")
                lo = bs[0]
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._take()  # '-'
                hi_ch = self._take()
                if hi_ch == "\\":
                    sub = self._escape()
                    if len(sub) != 1:
                        self._error("class escape cannot end a range")
                    hi = next(iter(sub))
                else:
                    hb = hi_ch.encode("utf-8")
                    if len(hb) > 1:
                        self._error("non-ASCII range bound")
                    hi = hb[0]
                if hi < lo:
                    self._error(f"reversed range {chr(lo)}-{chr(hi)}")
                members |= set(range(lo, hi + 1))
            else:
                members.add(lo)
        if negate:
            return frozenset(range(256)) - members
        return frozenset(members)


# ----------------------------------------------------------------------
# NFA -> DFA (subset construction)
# ----------------------------------------------------------------------

@dataclasses.dataclass
class Dfa:
    """trans (S, 256) int32 (-1 = dead), accepting (S,) bool, start 0."""

    trans: np.ndarray
    accepting: np.ndarray

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]


def _eps_closure(nfa: _Nfa, states: frozenset) -> frozenset:
    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for sym, t in nfa.edges[s]:
            if sym is None and t not in out:
                out.add(t)
                stack.append(t)
    return frozenset(out)


def compile_regex(pattern: str) -> Dfa:
    """Compile the supported regex subset to a byte-level DFA (full-match
    semantics — the whole emitted string must match)."""
    nfa = _Nfa()
    parser = _Parser(pattern, nfa)
    start, accept = parser.alternation()
    if parser.i != len(pattern):
        parser._error("trailing characters (unbalanced ')'?)")

    d0 = _eps_closure(nfa, frozenset({start}))
    index: Dict[frozenset, int] = {d0: 0}
    order = [d0]
    rows: List[np.ndarray] = []
    i = 0
    while i < len(order):
        cur = order[i]
        row = np.full((256,), -1, np.int32)
        # group outgoing byte edges
        targets_by_byte: Dict[int, set] = {}
        for s in cur:
            for sym, t in nfa.edges[s]:
                if sym is None:
                    continue
                for b in sym:
                    targets_by_byte.setdefault(b, set()).add(t)
        # canonicalize target sets -> dfa states
        memo: Dict[frozenset, int] = {}
        for b, ts in targets_by_byte.items():
            key = frozenset(ts)
            j = memo.get(key)
            if j is None:
                closure = _eps_closure(nfa, key)
                j = index.get(closure)
                if j is None:
                    j = len(order)
                    index[closure] = j
                    order.append(closure)
                memo[key] = j
            row[b] = j
        rows.append(row)
        i += 1
    trans = np.stack(rows)
    accepting = np.asarray([accept in st for st in order], bool)
    return Dfa(trans=trans, accepting=accepting)


def match(dfa: Dfa, data: bytes) -> bool:
    """Full-match test (used by the tests to cross-check constrained
    output against the compiled automaton)."""
    s = 0
    for b in data:
        s = int(dfa.trans[s, b])
        if s < 0:
            return False
    return bool(dfa.accepting[s])


# ----------------------------------------------------------------------
# DFA over bytes -> transition table over TOKENS
# ----------------------------------------------------------------------

def _token_table(dfa: Dfa, vocab: Sequence[bytes]) -> np.ndarray:
    """(S, V) int32: end state of walking token t's bytes from state s,
    -1 anywhere the walk dies. One trie pass per DFA state — O(S x trie)
    instead of O(S x V x len)."""
    trie: dict = {}
    for tid, bs in enumerate(vocab):
        node = trie
        for b in bs:
            node = node.setdefault(b, {})
        node.setdefault(None, []).append(tid)

    S, V = dfa.n_states, len(vocab)
    table = np.full((S, V), -1, np.int32)
    for s0 in range(S):
        stack = [(trie, s0)]
        while stack:
            node, s = stack.pop()
            for key, sub in node.items():
                if key is None:
                    for tid in sub:
                        table[s0, tid] = s
                    continue
                t = int(dfa.trans[s, key])
                if t >= 0:
                    stack.append((sub, t))
    # empty-byte tokens (specials) would be state-preserving no-ops the
    # model could emit forever — ban them outright (EOS is handled
    # separately by mask_row)
    for tid, bs in enumerate(vocab):
        if len(bs) == 0:
            table[:, tid] = -1
    return table


class TokenConstraint:
    """A compiled (pattern, vocab) constraint — immutable and shareable
    across requests; per-request progress is just an int DFA state the
    serving layer tracks.

    `vocab` maps token id -> the token's BYTES as emitted (for a
    byte-level tokenizer, its byte; for BPE, the decoded bytes of that
    token). `advance(state, token)` -> next state or -1; `mask_row`
    -> (V,) f32 additive row (0 allowed / -1e30 banned) with EOS allowed
    exactly in accepting states. The eos override assumes eos_id is a
    SPECIAL token the grammar can never consume — the serving layer
    rejects submissions where `allowed[:, eos_id]` is true in any
    REACHABLE state (ContinuousBatcher.submit; `reachable` below —
    states only enterable mid-token can never host a decode step, so
    eos aliasing there is harmless)."""

    def __init__(self, dfa: Dfa, vocab: Sequence[bytes]):
        self.dfa = dfa
        self.vocab_size = len(vocab)
        self.table = _token_table(dfa, vocab)
        self.allowed = self.table >= 0  # (S, V) bool
        self.accepting = dfa.accepting
        self.start = 0
        self._reachable: Optional[np.ndarray] = None

    @property
    def reachable(self) -> np.ndarray:
        """(S,) bool: states reachable from start via TOKEN transitions.
        The subset construction can mint byte-DFA states no whole token
        ever lands on; guards that quantify over states (e.g. the serving
        layer's eos check) must ignore those or they reject grammars on
        behavior that can never occur."""
        if self._reachable is None:
            seen = np.zeros(self.table.shape[0], bool)
            stack = [self.start]
            seen[self.start] = True
            while stack:
                s = stack.pop()
                row = self.table[s]
                for t in np.unique(row[row >= 0]):
                    if not seen[t]:
                        seen[t] = True
                        stack.append(int(t))
            self._reachable = seen
        return self._reachable

    @classmethod
    def from_regex(cls, pattern: str, vocab: Sequence[bytes]
                   ) -> "TokenConstraint":
        return cls(compile_regex(pattern), vocab)

    def advance(self, state: int, token: int) -> int:
        return int(self.table[state, token])

    def has_continuation(self, state: int) -> bool:
        return bool(self.allowed[state].any())

    def is_accepting(self, state: int) -> bool:
        return bool(self.accepting[state])

    def mask_row(self, state: int, eos_id: Optional[int]) -> np.ndarray:
        row = np.where(self.allowed[state], 0.0, NEG_BIG).astype(np.float32)
        if eos_id is not None:
            row[eos_id] = 0.0 if self.accepting[state] else NEG_BIG
        return row

    def mask_table(self, eos_id: Optional[int]) -> np.ndarray:
        """(S, V) bool: mask_row's allowed-set for EVERY state at once —
        the device-resident form (True = allowed; the decode program
        turns it into 0/-1e30 after a per-slot row gather). EOS column
        overridden exactly as mask_row does."""
        tab = self.allowed.copy()
        if eos_id is not None:
            tab[:, eos_id] = self.accepting.astype(bool)
        return tab

    def trans_table(self, eos_id: Optional[int]) -> np.ndarray:
        """(S, V) int32 LOCAL next-state table with SELF-LOOP closure —
        the device-resident walk form: next[s, t] = advance(s, t) where
        the grammar allows t, s otherwise. Dead transitions never index
        out of range (masking already bans those tokens; the self-loop
        makes the walk total), and the EOS column holds the state — a
        sampled EOS retires on host, and under the overlap pipeline the
        one garbage step dispatched past it must be idempotent. Both
        closures make replaying any masked-off token a no-op, which is
        exactly what the one-step dispatch pipeline needs: a stale step
        can never corrupt a slot's DFA state, only re-derive it."""
        S = self.table.shape[0]
        hold = np.arange(S, dtype=np.int32)[:, None]
        tab = np.where(self.allowed, self.table, hold).astype(np.int32)
        if eos_id is not None:
            tab[:, eos_id] = hold[:, 0]
        return tab


# ----------------------------------------------------------------------
# JSON mode
# ----------------------------------------------------------------------

_JSON_WS = r"[ \t\n\r]*"
_JSON_ESC = r"\\([\"\\/bfnrt]|u[0-9a-fA-F]{4})"
_JSON_STR = f'"([^"\\\\]|{_JSON_ESC})*"'
_JSON_NUM = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?"


def json_regex(max_depth: int = 2) -> str:
    """A regex matching JSON values nested up to `max_depth` levels of
    arrays/objects (depth 0 = scalars only). Regular languages cannot
    nest unboundedly — bounded expansion is the standard
    structured-output trade, made explicit here."""
    ws = _JSON_WS
    value = f"({_JSON_STR}|{_JSON_NUM}|true|false|null)"
    for _ in range(max_depth):
        arr = f"\\[{ws}({value}({ws},{ws}{value})*)?{ws}\\]"
        obj = (f"\\{{{ws}({_JSON_STR}{ws}:{ws}{value}"
               f"({ws},{ws}{_JSON_STR}{ws}:{ws}{value})*)?{ws}\\}}")
        value = f"({_JSON_STR}|{_JSON_NUM}|true|false|null|{arr}|{obj})"
    return value


_META = set("\\.[](){}*+?|")


def regex_escape(text: str) -> str:
    """Escape `text` so it matches literally under this module's regex
    subset (the analog of re.escape for compile_regex)."""
    return "".join("\\" + ch if ch in _META else ch for ch in text)


def choice_regex(options: Sequence[str]) -> str:
    """A regex matching exactly one of `options` verbatim — the
    enum/classifier constraint ("answer with one of these labels"):

        c = TokenConstraint.from_regex(
            choice_regex(["positive", "negative", "neutral"]), vocab)

    Greedy decode then picks the highest-likelihood label prefix-by
    -prefix; sampling stays proportional within the allowed set."""
    opts = [o for o in options]
    if not opts:
        raise ValueError("choice_regex needs at least one option")
    return "(" + "|".join(regex_escape(o) for o in opts) + ")"


def byte_vocab(vocab_size: int) -> List[bytes]:
    """The trivial byte-level vocab (token i == byte i for i < 256,
    empty for the rest) — what the tests and byte-tokenizer models use."""
    return [bytes([i]) if i < 256 else b"" for i in range(vocab_size)]
