"""KV-cache storage codecs: float and int8.

Decode at long context is bounded by CACHE reads, not weights: every step
streams the whole (L, B, H, S, D) K/V history from HBM for one token of
compute. Weight-only quantization (dnn_tpu/quant.py) halves/quarters the
weight bytes; this module does the same for the cache — the other half of
the decode-bandwidth story (VERDICT r2, weak #6).

Scheme, mirroring quant.py's weight recipe:

  * **Symmetric per-(position, head) int8.** Each cached K/V row (the D
    head-dim vector written at one position) gets one f32 scale:
    ``scale = max|row| / 127``. Rows are the natural grain: each is
    written once at its own decode step (so quantization is a cheap local
    epilogue on the new row, never a re-pass over the cache) and scales
    broadcast along D.
  * **Scales commute with both attention einsums.** Scores:
    ``q @ (k_q * ks)^T == (q @ k_q^T) * ks`` — dequant lands on the
    (T, S) score matrix, not a materialized float cache copy. Values:
    ``p @ (v_q * vs) == (p * vs) @ v_q`` — fold the scale into the
    (small) probability matrix before the contraction. The int8 cache is
    read at 1 byte/element; nothing float-sized is ever rebuilt.
  * Numerics: probabilities and accumulation stay f32 (same as the float
    path); the only new error is the per-row int8 rounding of K/V, which
    the parity test bounds (cosine > 0.999, token-parity on real decodes).

A codec is three functions over a PER-LAYER cache pytree (every leaf
carries a leading L axis at rest; `lax.scan` peels it): `init`, `write`,
`attend`. `generate.forward_with_cache` threads whichever codec matches
its cache, so the same decode loop serves f32, bf16, and int8 caches.

**Sliding windows** (Mistral-class models) come in two forms:

  * `window=` on the standard codecs adds a LOWER-bound mask — key
    positions <= limit - window are dropped — over an ordinary
    full-length cache. Storage is unchanged; every runtime (batcher,
    pipeline stages, chunked prefill) gets window semantics for free.
  * `RollingFloatKV` / `RollingInt8KV` store only `window` positions as
    a ring buffer (write at ``pos % window``): the solo decode loop's
    memory win — cache bytes are O(window) however long the stream runs.
    Ring slot j holds absolute position ``a_j = p - ((p - j) % W)`` at
    step p; masking ``a_j >= 0`` is exactly "written and in-window", so
    the two forms are attention-equivalent (pinned in
    tests/test_sliding_window.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30

__all__ = ["FloatKV", "Int8KV", "Int4KV", "RollingFloatKV", "RollingInt8KV",
           "band_keep", "codec_for_cache", "AUTO_KERNEL_MIN_S"]

# `use_kernel="auto"` threshold: below this many cache positions the XLA
# einsum path is at least as fast as the Pallas streaming kernel on every
# measured shape (benchmarks/attn_kernel_probe.py: einsum is
# near-bandwidth-optimal at short/moderate context, and the bucketed
# decode path — runtime/decode_buckets.py — keeps the allocation tracking
# the live length anyway). At or above it, a decode step against a LONG
# preallocated cache routes through the position-clamped kernel
# (ops/pallas/cached_attention.decode_attention), whose index-map clamp
# makes bytes/step proportional to the live position instead of the
# allocation — the regime behind the 13%-MBU long-context row
# (BASELINE.md). Heuristic, to be refined when the chip can re-measure.
AUTO_KERNEL_MIN_S = 1024


def band_keep(cols, limit, window):
    """THE sliding-window band predicate: causal upper bound
    (cols <= limit) plus the optional lower bound
    (cols > limit - window). Every codec that band-masks — the dense
    codecs via _KernelDispatch._band_keep AND the paged pool
    (runtime/paged_kvcache.PagedKV) — goes through here, so the
    boundary semantics can never diverge between them. Broadcasts over
    whatever shapes the caller aligned; `window` may be traced."""
    keep = cols <= limit
    if window is not None:
        keep &= cols > limit - window
    return keep


class _KernelDispatch:
    """Shared use_kernel plumbing: True engages the Pallas path with its
    own TPU/tiling dispatch; the string "auto" engages it ONLY on a TPU
    backend AND only against caches of at least AUTO_KERNEL_MIN_S
    positions (the length-aware policy: long-context decode streams
    through the position-clamped kernel, everything else stays on the
    einsum / bucketed-XLA path); the string "interpret" forces the kernel
    in Pallas interpreter mode (CPU CI runs the REAL kernel logic inside
    the full decode loop instead of silently falling back to the einsum).

    Also hosts THE window predicate: every attend variant of every codec
    masks through `_band_keep` / `_rows_keep`, so the sliding-window
    band-edge semantics live in exactly one place. Each attend variant
    additionally accepts a per-call `window=` override — a TRACED scalar
    is allowed, which is how per-LAYER windows (Gemma-2's alternating
    local/global attention) ride one scanned block body: the caller
    threads a (L,) window array through its layer scan and passes each
    layer's value here (a "no window" layer passes cfg.block_size, which
    makes the lower bound vacuous). A traced override disables the Pallas
    kernel path for that call (the kernel masks causally only).

    `softcap` (Gemma-2 attn_logit_softcapping) bounds scores to
    (-cap, cap) via cap*tanh(s/cap) BEFORE masking — also einsum-only."""

    use_kernel = False
    window: Optional[int] = None
    softcap: Optional[float] = None

    def _interp(self):
        return True if self.use_kernel == "interpret" else None

    def _kernel_on(self, c) -> bool:
        """Resolve the use_kernel mode against a concrete cache: True/
        "interpret" are unconditional, "auto" is the length-aware policy
        (TPU backend AND cache length >= AUTO_KERNEL_MIN_S — see the
        class docstring). Tiling/window/softcap guards stay with each
        attend variant's call site."""
        if self.use_kernel == "auto":
            return (jax.default_backend() == "tpu"
                    and c["k"].shape[2] >= AUTO_KERNEL_MIN_S)
        return bool(self.use_kernel)

    def _cap(self, s):
        """Apply attention-logit softcapping (identity when unset)."""
        if self.softcap is not None:
            s = self.softcap * jnp.tanh(s / self.softcap)
        return s

    def _band_keep(self, cols, limit, window=None):
        """The shared band predicate (module-level band_keep) with the
        codec's static window as the default; `window` overrides it
        (may be traced — see class docstring)."""
        return band_keep(cols, limit,
                         window if window is not None else self.window)

    def _rows_keep(self, c, pos, window=None):
        """(B, 1, 1, S) keep-mask for shared-limit decode rows at per-slot
        positions pos (B,). _RingStorage overrides this with the ring
        occupancy predicate — that override is the ONLY masking
        difference between a rolling codec and its base."""
        cols = jnp.arange(c["k"].shape[2])
        return self._band_keep(cols[None, None, None, :],
                               pos[:, None, None, None], window)


def _rows_update(cache, new, pos):
    """cache (B,H,S,...) <- new (B,H,1,...) at per-row positions pos (B,)."""
    return jax.vmap(
        lambda c, n, p: lax.dynamic_update_slice_in_dim(c, n, p, axis=1)
    )(cache, new, pos)


def _rows_write(cache, new, pos, write_gate):
    """cache (B,H,S,...) <- new (B,H,T,...) at per-row positions pos (B,)
    (T=1 decode steps, T=k+1 speculative verify blocks); rows with
    write_gate False re-write their EXISTING content at pos (a bitwise
    no-op — gather and scatter share the same clamped start). The gate
    folds into the (B,H,T,...) written ROWS — one gather + one
    dynamic-update-slice per leaf — instead of the older
    full-update-then-cache-sized-select form, whose select materialized
    a second allocation-sized buffer per leaf per layer even under
    donation (the CPU-optimized decode step carried 3 cache-sized copies
    per step from exactly this; the gate-folded form lowers to a true
    in-place update — asserted by the analysis gate's decode audit,
    dnn_tpu/analysis/program.audit_serving_decode)."""
    t = new.shape[2]
    cur = jax.vmap(
        lambda c, p: lax.dynamic_slice_in_dim(c, p, t, axis=1)
    )(cache, pos)
    gate = write_gate.reshape((-1,) + (1,) * (cache.ndim - 1))
    rows = jnp.where(gate, new.astype(cache.dtype), cur)
    return _rows_update(cache, rows, pos)


class FloatKV(_KernelDispatch):
    """The plain cache: K/V stored in `dtype` (f32 default, bf16 for
    halved bandwidth).

    `use_kernel=True` routes attend/attend_rows through the Pallas
    cached-attention kernel (dnn_tpu/ops/pallas/cached_attention.py):
    online-softmax streaming of the cache with runtime position limits —
    one compiled program for every chunk start and slot position. Falls
    back to the einsum path off-TPU or when shapes don't tile.

    `window=W` adds the sliding-window lower bound: key positions
    <= limit - W are masked in every attend variant (the kernel has no
    window support, so a window forces the einsum path)."""

    def __init__(self, dtype=jnp.float32, use_kernel=False,
                 window: Optional[int] = None,
                 softcap: Optional[float] = None):
        self.dtype = dtype
        self.use_kernel = use_kernel
        self.window = window
        self.softcap = softcap

    def init(self, cfg, batch: int, max_len: int):
        shape = (cfg.n_layer, batch, cfg.n_head, max_len,
                 cfg.n_embd // cfg.n_head)
        return {"k": jnp.zeros(shape, self.dtype),
                "v": jnp.zeros(shape, self.dtype)}

    def write(self, c, k, v, start_pos):
        """c: per-layer {"k","v"} (B,H,S,D); k/v (B,H,T,D) at start_pos."""
        return {
            "k": lax.dynamic_update_slice_in_dim(
                c["k"], k.astype(c["k"].dtype), start_pos, axis=2),
            "v": lax.dynamic_update_slice_in_dim(
                c["v"], v.astype(c["v"].dtype), start_pos, axis=2),
        }

    def attend(self, q, c, pos_limit, base=None, window=None):
        """q (B,H,T,D) against the full cache, masking key positions >
        their row's limit (pos_limit (T,)).

        `base` is the kernel contract marker: the caller asserts
        pos_limit == base + arange(T) by passing the start position
        (generate.py's _block_with_cache does). The kernel path engages
        ONLY with it — call sites with folded/tiled row limits (the LLaMA
        GQA group trick, llama.py) never pass base, so use_kernel can't
        silently mis-mask them; they fall through to the einsum (or, for
        T==1 folded rows, route via attend_rows' decode kernel)."""
        if (self._kernel_on(c) and base is not None and self.window is None
                and window is None and self.softcap is None):
            from dnn_tpu.ops.pallas.cached_attention import (
                cached_attention, decode_attention,
            )

            pos_b = jnp.broadcast_to(base, (q.shape[0],))
            if q.shape[2] == 1:
                # decode step: the heads-folded streaming kernel (few
                # programs, big DMAs) — the general kernel's block_q=1
                # grid measured 23x slower (ops/pallas/cached_attention)
                return decode_attention(
                    q, c["k"], c["v"], pos_b,
                    interpret=self._interp()).astype(c["v"].dtype)
            return cached_attention(
                q, c["k"], c["v"], pos_b,
                interpret=self._interp()).astype(c["v"].dtype)
        d = q.shape[-1]
        s = jnp.einsum("bhtd,bhsd->bhts", q, c["k"]).astype(jnp.float32) / jnp.sqrt(d)
        s = self._cap(s)
        cols = jnp.arange(c["k"].shape[2])
        keep = self._band_keep(cols[None, None, None, :],
                               pos_limit[None, None, :, None], window)
        s = jnp.where(keep, s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bhsd->bhtd", p.astype(c["v"].dtype), c["v"])

    # --- per-row variants (continuous batching: each slot at its own
    # position; `write_gate` (B,) bool keeps inactive slots untouched) ---

    def write_rows(self, c, k, v, pos, write_gate):
        return {"k": _rows_write(c["k"], k, pos, write_gate),
                "v": _rows_write(c["v"], v, pos, write_gate)}

    def attend_rows_causal(self, q, c, pos, window=None):
        """q (B, H, T, D) VERIFY blocks: row t of slot b attends cache
        columns <= pos[b] + t (per-row positions AND within-block
        causality — the speculative verify chunk's masking, which neither
        attend (shared batch limits) nor attend_rows (shared row limit)
        expresses). Op-and-dtype recipe mirrors attend_rows exactly —
        score einsum in the operand dtype, f32 softmax, probs cast to the
        cache dtype — so a greedy verify reproduces the step-by-step
        decode's argmax even under bf16 compute (the spec batcher's
        token-identity contract)."""
        d = q.shape[-1]
        s = jnp.einsum("bhtd,bhsd->bhts", q, c["k"]).astype(jnp.float32) \
            / jnp.sqrt(d)
        s = self._cap(s)
        cols = jnp.arange(c["k"].shape[2])
        rows = jnp.arange(q.shape[2])
        limit = pos[:, None, None, None] + rows[None, None, :, None]
        keep = self._band_keep(cols[None, None, None, :], limit, window)
        s = jnp.where(keep, s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bhsd->bhtd", p.astype(c["v"].dtype),
                          c["v"])

    def attend_rows(self, q, c, pos, window=None):
        """q (B, H, R, D); every row of slot b masked to keys at positions
        <= pos[b]. R=1 is plain per-slot decode; R=G is the LLaMA GQA fold
        (all group rows share their slot's limit — llama.LlamaFamilyRows)."""
        if (self._kernel_on(c) and self.window is None and window is None
                and self.softcap is None):
            from dnn_tpu.ops.pallas.cached_attention import decode_attention

            return decode_attention(q, c["k"], c["v"], pos,
                                    interpret=self._interp()) \
                .astype(c["v"].dtype)
        d = q.shape[-1]
        s = jnp.einsum("bhtd,bhsd->bhts", q, c["k"]).astype(jnp.float32) / jnp.sqrt(d)
        s = self._cap(s)
        s = jnp.where(self._rows_keep(c, pos, window), s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bhsd->bhtd", p.astype(c["v"].dtype), c["v"])


def _quantize_rows(x):
    """x (..., D) -> (int8 (..., D), f32 scales (...,)) — symmetric
    per-row, the cache analog of quant.quantize_tensor."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _quantize_rows_int4(x):
    """x (..., D) -> (int4 (..., D), f32 scales (...,)) — symmetric
    per-row at 7 levels. The scale grain is the same per-(position, head)
    row as the int8 codec's (each row is quantized once, at its own
    write, against its own max — the "per-bucket" scales of the cache
    recipe: one scale per D-wide bucket), which is what keeps 4-bit
    rounding bounded: a whole-tensor scale at 7 levels would be
    useless, a per-row one is the cache analog of quant.py's int4
    GROUP scheme (quantize_tensor_int4)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / 7.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]), -7, 7)
    return q.astype(jnp.int4), scale


class Int8KV(_KernelDispatch):
    """int8 K/V with per-(position, head) f32 scales — 4x less cache
    bandwidth per decode step than f32, 2x less than bf16.

    `use_kernel=True`: the Pallas cached-attention kernel streams the
    int8 bytes straight from HBM and folds the scales inside VMEM — the
    1-byte read becomes a guarantee instead of an XLA fusion hope (see
    dnn_tpu/ops/pallas/cached_attention.py).

    `window=W`: sliding-window lower bound, exactly as FloatKV's."""

    # the quantization recipe, overridden by Int4KV (same layout, 4-bit
    # payload); every write funnels through _quant so the two codecs
    # cannot drift
    _qdtype = jnp.int8
    _quant = staticmethod(_quantize_rows)

    def __init__(self, use_kernel=False,
                 window: Optional[int] = None,
                 softcap: Optional[float] = None):
        self.use_kernel = use_kernel
        self.window = window
        self.softcap = softcap

    def init(self, cfg, batch: int, max_len: int):
        shape = (cfg.n_layer, batch, cfg.n_head, max_len,
                 cfg.n_embd // cfg.n_head)
        return {
            "k": jnp.zeros(shape, self._qdtype),
            "v": jnp.zeros(shape, self._qdtype),
            "ks": jnp.ones(shape[:-1], jnp.float32),
            "vs": jnp.ones(shape[:-1], jnp.float32),
        }

    def write(self, c, k, v, start_pos):
        kq, ks = self._quant(k)
        vq, vs = self._quant(v)
        return {
            "k": lax.dynamic_update_slice_in_dim(c["k"], kq, start_pos, axis=2),
            "v": lax.dynamic_update_slice_in_dim(c["v"], vq, start_pos, axis=2),
            "ks": lax.dynamic_update_slice_in_dim(c["ks"], ks, start_pos, axis=2),
            "vs": lax.dynamic_update_slice_in_dim(c["vs"], vs, start_pos, axis=2),
        }

    def attend(self, q, c, pos_limit, base=None, window=None):
        # `base` marks the pos_limit == base + arange(T) contract (see
        # FloatKV.attend) — kernel path only with it
        if (self._kernel_on(c) and base is not None and self.window is None
                and window is None and self.softcap is None):
            from dnn_tpu.ops.pallas.cached_attention import (
                cached_attention, decode_attention,
            )

            pos_b = jnp.broadcast_to(base, (q.shape[0],))
            if q.shape[2] == 1:  # decode step: streaming kernel
                return decode_attention(
                    q, c["k"], c["v"], pos_b, ks=c["ks"], vs=c["vs"],
                    interpret=self._interp())
            return cached_attention(
                q, c["k"], c["v"], pos_b,
                ks=c["ks"], vs=c["vs"], interpret=self._interp())
        d = q.shape[-1]
        # scores in f32; the per-position K scale lands on the score matrix
        # (commutes with the D contraction)
        s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                       c["k"].astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        s = s * c["ks"][:, :, None, :] / jnp.sqrt(d)
        s = self._cap(s)
        cols = jnp.arange(c["k"].shape[2])
        keep = self._band_keep(cols[None, None, None, :],
                               pos_limit[None, None, :, None], window)
        s = jnp.where(keep, s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        # fold the V scale into the (small) probability matrix, then
        # contract against the raw int8 values
        p = p * c["vs"][:, :, None, :]
        return jnp.einsum("bhts,bhsd->bhtd", p, c["v"].astype(jnp.float32),
                          preferred_element_type=jnp.float32)

    # --- per-row variants (continuous batching) ---

    def write_rows(self, c, k, v, pos, write_gate):
        kq, ks = self._quant(k)   # (B,H,1,D), (B,H,1)
        vq, vs = self._quant(v)
        return {
            "k": _rows_write(c["k"], kq, pos, write_gate),
            "v": _rows_write(c["v"], vq, pos, write_gate),
            "ks": _rows_write(c["ks"], ks, pos, write_gate),
            "vs": _rows_write(c["vs"], vs, pos, write_gate),
        }

    def attend_rows_causal(self, q, c, pos, window=None):
        # per-row causal verify blocks (see FloatKV.attend_rows_causal);
        # scales fold exactly as in attend_rows' recipe
        d = q.shape[-1]
        s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                       c["k"].astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        s = s * c["ks"][:, :, None, :] / jnp.sqrt(d)
        s = self._cap(s)
        cols = jnp.arange(c["k"].shape[2])
        rows = jnp.arange(q.shape[2])
        limit = pos[:, None, None, None] + rows[None, None, :, None]
        keep = self._band_keep(cols[None, None, None, :], limit, window)
        s = jnp.where(keep, s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        p = p * c["vs"][:, :, None, :]
        return jnp.einsum("bhts,bhsd->bhtd", p,
                          c["v"].astype(jnp.float32),
                          preferred_element_type=jnp.float32)

    def attend_rows(self, q, c, pos, window=None):
        # shared-limit decode rows, any R (see FloatKV.attend_rows)
        if (self._kernel_on(c) and self.window is None and window is None
                and self.softcap is None):
            from dnn_tpu.ops.pallas.cached_attention import decode_attention

            return decode_attention(q, c["k"], c["v"], pos,
                                    ks=c["ks"], vs=c["vs"],
                                    interpret=self._interp())
        d = q.shape[-1]
        s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                       c["k"].astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        s = s * c["ks"][:, :, None, :] / jnp.sqrt(d)
        s = self._cap(s)
        s = jnp.where(self._rows_keep(c, pos, window), s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        p = p * c["vs"][:, :, None, :]
        return jnp.einsum("bhts,bhsd->bhtd", p, c["v"].astype(jnp.float32),
                          preferred_element_type=jnp.float32)


class Int4KV(Int8KV):
    """int4 K/V with per-(position, head) f32 scales — 8x less cache
    payload bandwidth per decode step than f32, 2x less than int8.
    Storage is NATIVE jnp.int4 (XLA S4: two values per byte in the HBM
    layout, the same packing quant.py's int4 weights ride).

    Same layout and attend math as Int8KV — only the quantizer (7
    levels, per-row scales) differs, so every attend variant (scores
    scaled on the (T, S) matrix, V scales folded into the probability
    matrix) is inherited verbatim. Einsum-only: the Pallas cached-
    attention kernel streams 1-byte elements; sub-byte VMEM loads are
    not wired, so the kernel path stays off whatever `use_kernel` says
    (the s4->f32 upcast fuses into the XLA dot instead). Accuracy: the
    parity tests bound per-row int4 rounding (cosine > 0.99 on real
    decode shapes); prefer int8 when the quality budget is tight —
    int4 is the bandwidth-endpoint rung of the serving-spec ladder
    (kv_dtype="int4", composable with the bucket ladder and the paged
    pool like every other cache dtype)."""

    _qdtype = jnp.int4
    _quant = staticmethod(_quantize_rows_int4)

    def __init__(self, window: Optional[int] = None,
                 softcap: Optional[float] = None):
        super().__init__(use_kernel=False, window=window, softcap=softcap)

    def _kernel_on(self, c) -> bool:
        return False  # no sub-byte kernel path (see class docstring)


def ring_positions(pos, w: int):
    """Absolute position held by ring slot j at stream position `pos`:
    ``a_j = pos - ((pos - j) % w)`` — the latest position congruent to j
    that is <= pos. Negative means "slot not yet written". Broadcasts
    over pos's shape, appending a (w,) axis. The SINGLE source of truth
    for ring occupancy: both Rolling codecs' masks and the prompt->ring
    gather (llama._ring_from_prompt) derive from it."""
    pos = jnp.asarray(pos)
    j = jnp.arange(w)
    return pos[..., None] - jnp.mod(pos[..., None] - j, w)


class _RingStorage:
    """Shared rolling-ring discipline, mixed over a base codec: only
    `window` positions are stored, a write lands at ``pos % window``, and
    attends mask ring slot j by ``ring_positions(pos, W) >= 0`` —
    "written and inside the live band" in one predicate (keys are stored
    already rotated at their absolute positions, so relative RoPE
    geometry is untouched by the wrap).

    Decode-oriented: multi-row attends (prefill chunks, speculative
    verify blocks) belong on a full-length cache with `window=` masking —
    prefill there, then gather the live band into the ring
    (llama.make_generate's rolling path does exactly this). A multi-row
    ring attend would let early query rows see slots their own future
    already overwrote, so it is rejected rather than mis-masked."""

    def init(self, cfg, batch: int, max_len: int):
        # `max_len` is the stream bound; storage is the window
        del max_len
        return super().init(cfg, batch, self.window)

    def attend(self, q, c, pos_limit, base=None, window=None):
        if q.shape[2] != 1:
            raise ValueError(
                "rolling cache attends single decode rows only — prefill "
                "on a full-length cache with window= masking, then gather "
                "the live band (llama.make_generate's rolling path)")
        del base, window
        return self.attend_rows(
            q, c, jnp.broadcast_to(pos_limit[0], (q.shape[0],)))

    def write_rows(self, c, k, v, pos, write_gate):
        w = c["k"].shape[2]
        return super().write_rows(c, k, v, jnp.mod(pos, w), write_gate)

    def attend_rows_causal(self, q, c, pos, window=None):
        raise ValueError(
            "speculative verify blocks need a full-length cache — rolling "
            "storage cannot express per-row history beyond the ring")

    def _rows_keep(self, c, pos, window=None):
        """Ring occupancy replaces the band mask — the one masking
        difference vs the base codec (see _KernelDispatch._rows_keep).
        A per-call window override makes no sense on a ring (storage IS
        the window) and is ignored."""
        del window
        return (ring_positions(pos, c["k"].shape[2]) >= 0)[:, None, None, :]

    @staticmethod
    def _ring_scatter(c, new, start_pos, w: int):
        """Write rows at absolute positions [start_pos, start_pos+t) into
        their ring slots; only the last min(t, w) rows survive the wrap,
        and their slots are distinct — a plain scatter."""
        t = next(iter(new.values())).shape[2]
        if t == 1:
            slot = jnp.mod(start_pos, w)
            return {kk: lax.dynamic_update_slice_in_dim(
                c[kk], new[kk], slot, axis=2) for kk in new}
        m = min(t, w)
        slots = jnp.mod(start_pos + jnp.arange(t - m, t), w)
        return {kk: c[kk].at[:, :, slots].set(new[kk][:, :, t - m:])
                for kk in new}


class RollingFloatKV(_RingStorage, FloatKV):
    """Ring-buffer float cache for sliding-window decode (see
    _RingStorage for the storage discipline and contract)."""

    def __init__(self, dtype=jnp.float32, window: Optional[int] = None):
        if window is None or window < 1:
            raise ValueError(
                f"rolling cache needs a positive window, got {window}")
        super().__init__(dtype, use_kernel=False, window=window)

    def write(self, c, k, v, start_pos):
        w = c["k"].shape[2]
        return self._ring_scatter(
            c, {"k": k.astype(c["k"].dtype), "v": v.astype(c["v"].dtype)},
            start_pos, w)
    # attend_rows: FloatKV's einsum with _RingStorage._rows_keep


class RollingInt8KV(_RingStorage, Int8KV):
    """Ring-buffer int8 cache: _RingStorage's discipline with Int8KV's
    per-row scales."""

    def __init__(self, window: Optional[int] = None):
        if window is None or window < 1:
            raise ValueError(
                f"rolling cache needs a positive window, got {window}")
        super().__init__(use_kernel=False, window=window)

    def write(self, c, k, v, start_pos):
        w = c["k"].shape[2]
        kq, ks = self._quant(k)
        vq, vs = self._quant(v)
        return self._ring_scatter(
            c, {"k": kq, "v": vq, "ks": ks, "vs": vs}, start_pos, w)
    # attend_rows: Int8KV's scaled einsum with _RingStorage._rows_keep


def codec_for_cache(cache, use_kernel=False,
                    window: Optional[int] = None, rolling: bool = False,
                    softcap: Optional[float] = None):
    """Infer the codec from a cache pytree's structure (int8 caches carry
    scale leaves). `use_kernel` opts attend/attend_rows into the Pallas
    cached-attention kernel (TPU; einsum fallback elsewhere): False/True
    as before, "auto" = the length-aware policy (kernel only on TPU
    against caches >= AUTO_KERNEL_MIN_S positions — long-context decode
    streams through the position-clamped kernel, short caches stay on
    the einsum), "interpret" = kernel in Pallas interpreter mode. `window`
    adds the sliding-window lower bound; `rolling=True` additionally
    treats the cache as a `window`-length ring buffer (rolling cannot be
    inferred from structure — a ring leaf looks like a short cache).
    `softcap` is Gemma-2's attention-logit softcapping (einsum paths
    only; no rolling support — Gemma-2 alternates local/global layers,
    so its decode never rolls)."""
    if rolling:
        if softcap is not None:
            raise ValueError("softcap is not supported on rolling caches")
        if "ks" in cache:
            if cache["k"].dtype == jnp.int4:
                raise ValueError(
                    "rolling int4 caches are not built — roll at int8 "
                    "(RollingInt8KV) or keep int4 on a full-length cache")
            return RollingInt8KV(window=window)
        return RollingFloatKV(cache["k"].dtype, window=window)
    if "ks" in cache:
        if cache["k"].dtype == jnp.int4:
            return Int4KV(window=window, softcap=softcap)
        return Int8KV(use_kernel=use_kernel, window=window, softcap=softcap)
    return FloatKV(cache["k"].dtype, use_kernel=use_kernel, window=window,
                   softcap=softcap)
