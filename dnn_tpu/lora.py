"""LoRA — low-rank adaptation for parameter-efficient fine-tuning.

The reference is inference-only (readme.md:112) and its only notion of
weights is a monolithic `.pth` loaded per node (node.py:294-317); it has
no fine-tuning story at all. This module adds the modern one on top of
this framework's pure-pytree models, TPU-first:

  * Adapters are a SEPARATE small pytree (a flat {path: {"a", "b"}}
    dict), not a model rewrite — any family (GPT, LLaMA, MoE) and any
    layout (per-layer `h_i` or stacked `prepare_stacked` / pipeline
    stage-stacked) is adaptable, because merging is a tree operation:
    W + (alpha/r) * a @ b, batched over any leading stack axes by
    jnp.matmul broadcasting.
  * Training closes over the FROZEN base params and differentiates only
    the adapter tree — `jax.grad` over a pytree of a few M parameters
    while the base stays donated/placed wherever it already lives
    (replicated, tp-sharded, fsdp-sharded: the merge is elementwise in
    the base, so GSPMD keeps the base's sharding and replicates the tiny
    adapter math).
  * Serving merges once (`merge_lora`) and runs the standard decode
    paths — zero inference-time overhead, the way LoRA is deployed.

b is zero-initialized, so at init the adapted model IS the base model
(merge == identity); a uses a 1/sqrt(rank)-scaled normal.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

# Kernel-bearing key names eligible for adaptation, per family:
#   GPT  (models/gpt.py):   qkv, proj (attn + mlp), fc
#   LLaMA (models/llama.py): q, k, v, o, gate, up, down
# Embeddings / lm_head / norms are excluded by default (standard LoRA
# practice: adapt the linear maps, freeze everything else).
DEFAULT_TARGETS = ("qkv", "proj", "fc", "q", "k", "v", "o", "gate", "up",
                   "down")


def _path_keys(path) -> Tuple[str, ...]:
    return tuple(
        str(getattr(p, "key", getattr(p, "name", p))) for p in path
    )


def _path_str(path) -> str:
    return "/".join(_path_keys(path))


def _is_target(path, leaf, targets) -> bool:
    keys = _path_keys(path)
    # a weight: last-two-dims matmul/gather operand, never a bias/scale
    # vector; "kernel"/"embedding" leaves under a targeted name count, as
    # does a bare 2D+ array whose own key is the target (so explicitly
    # requesting e.g. "wte" adapts the embedding table)
    if leaf.ndim < 2:
        return False
    if not (set(keys) & set(targets)):
        return False
    return keys[-1] in ("kernel", "embedding") or keys[-1] in targets


def init_lora(rng, params, *, rank: int, targets: Iterable[str] = DEFAULT_TARGETS,
              dtype=jnp.float32) -> Dict[str, Dict[str, jax.Array]]:
    """Build the adapter tree for `params`: {path: {"a": (..., in, r),
    "b": (..., r, out)}} for every targeted kernel leaf. Leading stack
    axes (layer stacks from `prepare_stacked`, stage stacks from the
    pipeline layout) are preserved, so one adapter tree fits whichever
    layout the base params are in. b = 0 -> merge is the identity at
    init."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    adapters: Dict[str, Dict[str, jax.Array]] = {}
    keys = jax.random.split(rng, max(len(flat), 1))
    for (path, leaf), key in zip(flat, keys):
        if not _is_target(path, leaf, tuple(targets)):
            continue
        *lead, d_in, d_out = leaf.shape
        a = jax.random.normal(key, (*lead, d_in, rank), dtype) / jnp.sqrt(
            jnp.asarray(rank, dtype))
        b = jnp.zeros((*lead, rank, d_out), dtype)
        adapters[_path_str(path)] = {"a": a, "b": b}
    if not adapters:
        raise ValueError(
            f"no param leaf matched targets {tuple(targets)}; "
            "check the param tree's key names")
    return adapters


def lora_scaling(adapters, *, alpha: Optional[float] = None) -> float:
    """alpha/rank, the standard LoRA scale (alpha defaults to rank, i.e.
    scale 1.0 — rank is read off the adapter shapes)."""
    if not adapters:
        raise ValueError("empty adapter dict (nothing was loaded/built)")
    any_ad = next(iter(adapters.values()))
    rank = any_ad["a"].shape[-1]
    return float(alpha if alpha is not None else rank) / float(rank)


def merge_lora(params, adapters, *, alpha: Optional[float] = None):
    """W + (alpha/r) a @ b on every adapted leaf; all other leaves pass
    through untouched. Pure tree op — jit-safe, grads flow into
    `adapters` (and not into `params` when the caller differentiates only
    the adapter argument), and leading stack axes batch via matmul
    broadcasting."""
    scale = lora_scaling(adapters, alpha=alpha)
    consumed = set()

    def merge_leaf(path, w):
        ad = adapters.get(_path_str(path))
        if ad is None:
            return w
        consumed.add(_path_str(path))
        delta = jnp.matmul(ad["a"], ad["b"]) * scale
        return w + delta.astype(w.dtype)

    merged = jax.tree_util.tree_map_with_path(merge_leaf, params)
    unused = set(adapters) - consumed
    if unused:
        # a layout/key mismatch (per-layer adapters onto stacked params,
        # or a foreign model's artifact) must not become a silent
        # identity merge that serves the un-tuned model
        raise ValueError(
            f"{len(unused)} adapter entries matched no param leaf "
            f"(layout mismatch?): {sorted(unused)[:3]}...")
    return merged


def make_lora_loss(loss_fn: Callable, base_params, *,
                   alpha: Optional[float] = None) -> Callable:
    """(adapters, batch) -> scalar, with `base_params` frozen in the
    closure. Feed to train.make_train_step / make_sharded_train_step —
    the optimizer then sees ONLY the adapter tree (its state is
    adapter-sized, the parameter-efficiency half of LoRA's pitch)."""

    def lora_loss(adapters, batch):
        return loss_fn(merge_lora(base_params, adapters, alpha=alpha), batch)

    return lora_loss


def adapters_to_stacked(adapters, n_layer: int):
    """Per-layer adapter paths (``h_i/...``, the training layout) -> the
    `prepare_stacked` serving layout (``blocks/...`` with a leading L
    axis). Lets artifacts trained against per-layer params serve through
    `lora_view` without retraining. Non-block paths (wte etc.) pass
    through unchanged — their layout is identical in both forms."""
    out: Dict[str, Dict[str, jax.Array]] = {}
    groups: Dict[str, Dict[int, dict]] = {}
    for path, ab in adapters.items():
        keys = path.split("/")
        if keys[0].startswith("h_") and keys[0][2:].isdigit():
            groups.setdefault("/".join(keys[1:]), {})[int(keys[0][2:])] = ab
        else:
            out[path] = ab
    for rest, by_layer in groups.items():
        if set(by_layer) != set(range(n_layer)):
            raise ValueError(
                f"adapter covers layers {sorted(by_layer)} of {rest} but "
                f"the model has {n_layer} — a partial stack would "
                "silently zero the missing layers")
        out["blocks/" + rest] = {
            "a": jnp.stack([by_layer[i]["a"] for i in range(n_layer)]),
            "b": jnp.stack([by_layer[i]["b"] for i in range(n_layer)]),
        }
    return out


def stack_loras(adapter_list, *, alphas=None):
    """N separate adapter trees (several fine-tunes of ONE base, same
    structure and rank) -> one multi-adapter tree {path: {"a": (N+1, ...,
    in, r), "b": (N+1, ..., r, out)}} with each adapter's merge scale
    (alpha_i / r) folded into its b slab and an ALL-ZERO adapter at
    index 0 — the base model, selected by requests that name no adapter.
    Feed to `lora_view` for per-request adapter serving
    (ContinuousBatcher(lora_adapters=...))."""
    if not adapter_list:
        raise ValueError("adapter_list must name at least one adapter")
    if alphas is not None and len(alphas) != len(adapter_list):
        raise ValueError(
            f"{len(alphas)} alphas for {len(adapter_list)} adapters")
    paths = set(adapter_list[0])
    for i, ad in enumerate(adapter_list[1:], 1):
        if set(ad) != paths:
            raise ValueError(
                f"adapter {i} targets different leaves than adapter 0: "
                f"{sorted(set(ad) ^ paths)[:3]}...")
    out: Dict[str, Dict[str, jax.Array]] = {}
    for p in sorted(paths):
        a0, b0 = adapter_list[0][p]["a"], adapter_list[0][p]["b"]
        a_stack, b_stack = [jnp.zeros_like(a0)], [jnp.zeros_like(b0)]
        for i, ad in enumerate(adapter_list):
            if ad[p]["a"].shape != a0.shape or ad[p]["b"].shape != b0.shape:
                raise ValueError(
                    f"adapter {i} shape mismatch at {p}: "
                    f"{ad[p]['a'].shape}/{ad[p]['b'].shape} vs "
                    f"{a0.shape}/{b0.shape}")
            scale = lora_scaling(
                ad, alpha=None if alphas is None else alphas[i])
            a_stack.append(ad[p]["a"])
            b_stack.append(ad[p]["b"] * scale)
        out[p] = {"a": jnp.stack(a_stack), "b": jnp.stack(b_stack)}
    return out


def lora_view(params, stacked, sel, *, transposed: bool = False):
    """Attach per-slot adapter selection to a param tree: for every path
    in `stacked` (a `stack_loras` result), the dict HOLDING that kernel
    leaf gains a {"lora": {a, b, sel}} entry that ops.nn.linear applies
    as a low-rank delta on top of its base matmul (float or quantized —
    the base leaf is untouched, so one set of base weights serves every
    adapter).

    `sel` is the (B, N+1) one-hot adapter choice per batch row (row 0 of
    the stack is the all-zero base adapter). Leaves under a leading
    layer-stack axis (the `prepare_stacked` serving layout) get the
    adapter axis transposed behind the layer axis and sel broadcast to
    (L, B, N+1), so `lax.scan` over the blocks peels both together.

    Pure tree surgery on the host — no weight copies; rebuilt whenever
    the slot->adapter assignment changes (shape-stable, so the jitted
    decode program never recompiles). `transposed=True` marks a stack
    already passed through `transpose_lora_stack` (serving callers do
    the moveaxis once instead of per view).

    Only LINEAR leaves can be served this way — the delta applies inside
    ops.nn.linear. An embedding-targeted adapter (path ending in
    "embedding", which jnp.take-based lookups would silently ignore) is
    rejected, mirroring merge_lora's no-silent-identity guard."""
    sel = jnp.asarray(sel)

    def _attach(node, keys, ab):
        # keys[-1] is the kernel leaf's own name ("kernel" — or "q" after
        # weight quantization); the lora entry rides its PARENT dict
        if len(keys) < 2:
            raise ValueError(
                f"adapter path {'/'.join(keys)!r} names no containing dict")
        k = keys[0]
        if not isinstance(node, dict) or k not in node:
            raise ValueError(
                f"adapter path segment {k!r} not found in params (layout "
                f"mismatch? keys: "
                f"{sorted(node)[:6] if isinstance(node, dict) else type(node)})")
        out = dict(node)
        if len(keys) == 2:
            child = dict(node[k])
            a, b = ab["a"], ab["b"]
            if a.ndim == 4:  # layer-stacked leaf
                if not transposed:
                    a = jnp.moveaxis(a, 0, 1)  # (N, L, ..) -> (L, N, ..)
                    b = jnp.moveaxis(b, 0, 1)
                s = jnp.broadcast_to(sel, (a.shape[0],) + sel.shape)
            else:
                s = sel
            child["lora"] = {"a": a, "b": b, "sel": s}
            out[k] = child
        else:
            out[k] = _attach(node[k], keys[1:], ab)
        return out

    view = params
    for path, ab in stacked.items():
        if path.split("/")[-1] == "embedding":
            raise ValueError(
                f"adapter targets the embedding table ({path}); per-request "
                "serving applies deltas inside linear layers only — an "
                "embedding adapter would be silently ignored. Merge it "
                "(merge_lora) or retrain with linear targets.")
        view = _attach(view, path.split("/"), ab)
    return view


def transpose_lora_stack(stacked):
    """One-time serving prep of a `stack_loras` result: layer-stacked
    slabs moved to scan order ((N, L, ...) -> (L, N, ...)) ONCE, so every
    subsequent `lora_view(..., transposed=True)` is pure host-side dict
    surgery with no device transposes (the per-submit fast path)."""
    out = {}
    for path, ab in stacked.items():
        a, b = ab["a"], ab["b"]
        if a.ndim == 4:
            a, b = jnp.moveaxis(a, 0, 1), jnp.moveaxis(b, 0, 1)
        out[path] = {"a": a, "b": b}
    return out


def save_lora(path: str, adapters, *, alpha: Optional[float] = None) -> None:
    """Adapters -> one npz (keys '<leaf path>:a' / ':b'; '__alpha__' when
    a non-default alpha was trained with — the merge scale is part of the
    artifact, or a loader would silently apply the adapters at the wrong
    strength). The artifact is the only thing a fine-tune ships — base
    weights stay wherever the base checkpoint lives."""
    import numpy as np

    from dnn_tpu.io.checkpoint import save_npz

    flat = {}
    for k, ab in adapters.items():
        flat[f"{k}:a"] = np.asarray(ab["a"])
        flat[f"{k}:b"] = np.asarray(ab["b"])
    if alpha is not None:
        flat["__alpha__"] = np.asarray(float(alpha), np.float32)
    save_npz(path, flat)


def load_lora(path: str) -> Tuple[Dict[str, Dict[str, Any]], Optional[float]]:
    """npz -> (adapters, alpha). `alpha` is None when the artifact was
    saved without one (trained at the default alpha=rank); pass it
    through: `merge_lora(params, adapters, alpha=alpha)`."""
    from dnn_tpu.io.checkpoint import load_npz

    flat = load_npz(path)
    alpha = None
    if "__alpha__" in flat:
        alpha = float(flat.pop("__alpha__"))
    out: Dict[str, Dict[str, Any]] = {}
    for k, v in flat.items():
        leaf_path, _, which = k.rpartition(":")
        if which not in ("a", "b"):
            raise ValueError(f"malformed LoRA npz key: {k}")
        out.setdefault(leaf_path, {})[which] = jnp.asarray(v)
    for k, ab in out.items():
        if set(ab) != {"a", "b"}:
            raise ValueError(f"LoRA npz missing half of {k}: has {set(ab)}")
    return out, alpha
