"""LoRA — low-rank adaptation for parameter-efficient fine-tuning.

The reference is inference-only (readme.md:112) and its only notion of
weights is a monolithic `.pth` loaded per node (node.py:294-317); it has
no fine-tuning story at all. This module adds the modern one on top of
this framework's pure-pytree models, TPU-first:

  * Adapters are a SEPARATE small pytree (a flat {path: {"a", "b"}}
    dict), not a model rewrite — any family (GPT, LLaMA, MoE) and any
    layout (per-layer `h_i` or stacked `prepare_stacked` / pipeline
    stage-stacked) is adaptable, because merging is a tree operation:
    W + (alpha/r) * a @ b, batched over any leading stack axes by
    jnp.matmul broadcasting.
  * Training closes over the FROZEN base params and differentiates only
    the adapter tree — `jax.grad` over a pytree of a few M parameters
    while the base stays donated/placed wherever it already lives
    (replicated, tp-sharded, fsdp-sharded: the merge is elementwise in
    the base, so GSPMD keeps the base's sharding and replicates the tiny
    adapter math).
  * Serving merges once (`merge_lora`) and runs the standard decode
    paths — zero inference-time overhead, the way LoRA is deployed.

b is zero-initialized, so at init the adapted model IS the base model
(merge == identity); a uses a 1/sqrt(rank)-scaled normal.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

# Kernel-bearing key names eligible for adaptation, per family:
#   GPT  (models/gpt.py):   qkv, proj (attn + mlp), fc
#   LLaMA (models/llama.py): q, k, v, o, gate, up, down
# Embeddings / lm_head / norms are excluded by default (standard LoRA
# practice: adapt the linear maps, freeze everything else).
DEFAULT_TARGETS = ("qkv", "proj", "fc", "q", "k", "v", "o", "gate", "up",
                   "down")


def _path_keys(path) -> Tuple[str, ...]:
    return tuple(
        str(getattr(p, "key", getattr(p, "name", p))) for p in path
    )


def _path_str(path) -> str:
    return "/".join(_path_keys(path))


def _is_target(path, leaf, targets) -> bool:
    keys = _path_keys(path)
    # a weight: last-two-dims matmul/gather operand, never a bias/scale
    # vector; "kernel"/"embedding" leaves under a targeted name count, as
    # does a bare 2D+ array whose own key is the target (so explicitly
    # requesting e.g. "wte" adapts the embedding table)
    if leaf.ndim < 2:
        return False
    if not (set(keys) & set(targets)):
        return False
    return keys[-1] in ("kernel", "embedding") or keys[-1] in targets


def init_lora(rng, params, *, rank: int, targets: Iterable[str] = DEFAULT_TARGETS,
              dtype=jnp.float32) -> Dict[str, Dict[str, jax.Array]]:
    """Build the adapter tree for `params`: {path: {"a": (..., in, r),
    "b": (..., r, out)}} for every targeted kernel leaf. Leading stack
    axes (layer stacks from `prepare_stacked`, stage stacks from the
    pipeline layout) are preserved, so one adapter tree fits whichever
    layout the base params are in. b = 0 -> merge is the identity at
    init."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    adapters: Dict[str, Dict[str, jax.Array]] = {}
    keys = jax.random.split(rng, max(len(flat), 1))
    for (path, leaf), key in zip(flat, keys):
        if not _is_target(path, leaf, tuple(targets)):
            continue
        *lead, d_in, d_out = leaf.shape
        a = jax.random.normal(key, (*lead, d_in, rank), dtype) / jnp.sqrt(
            jnp.asarray(rank, dtype))
        b = jnp.zeros((*lead, rank, d_out), dtype)
        adapters[_path_str(path)] = {"a": a, "b": b}
    if not adapters:
        raise ValueError(
            f"no param leaf matched targets {tuple(targets)}; "
            "check the param tree's key names")
    return adapters


def lora_scaling(adapters, *, alpha: Optional[float] = None) -> float:
    """alpha/rank, the standard LoRA scale (alpha defaults to rank, i.e.
    scale 1.0 — rank is read off the adapter shapes)."""
    if not adapters:
        raise ValueError("empty adapter dict (nothing was loaded/built)")
    any_ad = next(iter(adapters.values()))
    rank = any_ad["a"].shape[-1]
    return float(alpha if alpha is not None else rank) / float(rank)


def merge_lora(params, adapters, *, alpha: Optional[float] = None):
    """W + (alpha/r) a @ b on every adapted leaf; all other leaves pass
    through untouched. Pure tree op — jit-safe, grads flow into
    `adapters` (and not into `params` when the caller differentiates only
    the adapter argument), and leading stack axes batch via matmul
    broadcasting."""
    scale = lora_scaling(adapters, alpha=alpha)
    consumed = set()

    def merge_leaf(path, w):
        ad = adapters.get(_path_str(path))
        if ad is None:
            return w
        consumed.add(_path_str(path))
        delta = jnp.matmul(ad["a"], ad["b"]) * scale
        return w + delta.astype(w.dtype)

    merged = jax.tree_util.tree_map_with_path(merge_leaf, params)
    unused = set(adapters) - consumed
    if unused:
        # a layout/key mismatch (per-layer adapters onto stacked params,
        # or a foreign model's artifact) must not become a silent
        # identity merge that serves the un-tuned model
        raise ValueError(
            f"{len(unused)} adapter entries matched no param leaf "
            f"(layout mismatch?): {sorted(unused)[:3]}...")
    return merged


def make_lora_loss(loss_fn: Callable, base_params, *,
                   alpha: Optional[float] = None) -> Callable:
    """(adapters, batch) -> scalar, with `base_params` frozen in the
    closure. Feed to train.make_train_step / make_sharded_train_step —
    the optimizer then sees ONLY the adapter tree (its state is
    adapter-sized, the parameter-efficiency half of LoRA's pitch)."""

    def lora_loss(adapters, batch):
        return loss_fn(merge_lora(base_params, adapters, alpha=alpha), batch)

    return lora_loss


def save_lora(path: str, adapters, *, alpha: Optional[float] = None) -> None:
    """Adapters -> one npz (keys '<leaf path>:a' / ':b'; '__alpha__' when
    a non-default alpha was trained with — the merge scale is part of the
    artifact, or a loader would silently apply the adapters at the wrong
    strength). The artifact is the only thing a fine-tune ships — base
    weights stay wherever the base checkpoint lives."""
    import numpy as np

    from dnn_tpu.io.checkpoint import save_npz

    flat = {}
    for k, ab in adapters.items():
        flat[f"{k}:a"] = np.asarray(ab["a"])
        flat[f"{k}:b"] = np.asarray(ab["b"])
    if alpha is not None:
        flat["__alpha__"] = np.asarray(float(alpha), np.float32)
    save_npz(path, flat)


def load_lora(path: str) -> Tuple[Dict[str, Dict[str, Any]], Optional[float]]:
    """npz -> (adapters, alpha). `alpha` is None when the artifact was
    saved without one (trained at the default alpha=rank); pass it
    through: `merge_lora(params, adapters, alpha=alpha)`."""
    from dnn_tpu.io.checkpoint import load_npz

    flat = load_npz(path)
    alpha = None
    if "__alpha__" in flat:
        alpha = float(flat.pop("__alpha__"))
    out: Dict[str, Dict[str, Any]] = {}
    for k, v in flat.items():
        leaf_path, _, which = k.rpartition(":")
        if which not in ("a", "b"):
            raise ValueError(f"malformed LoRA npz key: {k}")
        out.setdefault(leaf_path, {})[which] = jnp.asarray(v)
    for k, ab in out.items():
        if set(ab) != {"a", "b"}:
            raise ValueError(f"LoRA npz missing half of {k}: has {set(ab)}")
    return out, alpha
