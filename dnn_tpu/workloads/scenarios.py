"""Declarative workload scenarios: client populations + their SLOs.

A `Scenario` is pure data plus two pure functions: `script(seed)`
produces the complete request list (arrival offset, prompt tokens,
sampling/constraint/adapter options) as a deterministic function of the
seed, and `build_server()` constructs the in-process LMServer the
runner drives when no external target is given. The SLO rides the
scenario — each workload declares what "served well" means for ITS
traffic shape, and the verdict engine (obs/slo.py) judges the recorded
outcomes against exactly that declaration.

The registry (`SCENARIOS`) covers ROADMAP item 5b's diversity list:

  chat         multi-turn chat over SHARED system prompts — tenants
               reuse whole prompt_pad-aligned prefixes, so the server's
               prefix cache (serving.py) gets real hit traffic and the
               dnn_tpu_prefix_hit_ratio gauge has a workload to read
               (feeds ROADMAP item 2's fleet-wide tier);
  longcontext  prompts near max_len at a low Poisson rate — the
               prefill-chunk-loop regime, where TTFT is the objective
               under pressure;
  json_mode    every request grammar-constrained ([0-9]+ over the byte
               vocab) under a BURSTY envelope — constrained decoding at
               load, the per-step DFA walk paying rent while arrivals
               spike;
  json_mode_fast
               the SAME constrained population on the interleaved+
               overlap hot path (on-device DFA walk, ISSUE 16) —
               json_mode is its convoy-admission control row;
  spec_mix     a speculative server (int8 self-draft, the repo's
               standard pair) under a mixed client population — draft
               acceptance meets heterogeneous budgets. (Beam search
               has NO pooled serving path — runtime/beam.py is a solo
               decoder — so the "speculative + beam" mix serves its
               speculative half; a beam workload needs beam-in-the-
               pool first, stated here rather than faked.);
  lora         multi-tenant adapter traffic: base + two LoRA tenants
               interleaved in one pool (feeds ROADMAP item 3's
               closed-loop story);
  breach_chaos chat traffic with an injected device-step fault storm
               (dnn_tpu/chaos step_fault) that exhausts the worker's
               restart budget — the scenario that MUST breach, so the
               incident-bundle path is exercised and asserted on every
               round, not only on bad days.

Model shape: a tiny GPT (2L/64d, vocab 256) — the workload rows
measure the SERVING FABRIC (admission, scheduling, constraints,
adapters, SLO accounting) at real concurrency on whatever substrate
runs them; model-compute rows live elsewhere in run_all. Durations are
seconds, not minutes, so all six scenarios fit a bench round.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from dnn_tpu.obs.slo import SLOSpec
from dnn_tpu.workloads.arrivals import (
    bursty_arrivals,
    poisson_arrivals,
    uniform,
)

__all__ = ["Request", "Scenario", "SCENARIOS", "get_scenario"]


@dataclasses.dataclass(frozen=True)
class Request:
    """One scheduled request: fire at `at` seconds after scenario
    start, submit `prompt` with `max_new` and `opts` (forwarded to
    ContinuousBatcher.submit — temperature / top_k / constraint /
    adapter...). `client` names the logical client for per-tenant
    reporting; `seed` pins the request's sampling stream."""

    at: float
    prompt: np.ndarray
    max_new: int
    client: str
    seed: int
    opts: Optional[dict] = None


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One workload: `script(seed)` -> [Request] (pure), `slo` the
    declaration the verdict judges, `build_server()` -> a constructed
    runtime/lm_server.LMServer (the runner closes it), `chaos_plan` an
    optional dnn_tpu/chaos FaultPlan dict installed for the run, and
    `expect_breach` flips the run_all assertion: the scenario is GREEN
    when it breaches AND its incident bundle reconstructs."""

    name: str
    description: str
    slo: SLOSpec
    duration_s: float
    script: Callable[[int], List[Request]]
    build_server: Callable[[], object]
    chaos_plan: Optional[dict] = None
    expect_breach: bool = False
    settle_s: float = 0.0  # wall the runner waits beyond the last
    # request's deadline for stragglers


# ----------------------------------------------------------------------
# shared model shape + prompt helpers
# ----------------------------------------------------------------------

VOCAB = 256
PROMPT_PAD = 8


def _cfg():
    from dnn_tpu.models import gpt

    return gpt.GPTConfig(block_size=160, vocab_size=VOCAB, n_layer=2,
                         n_head=4, n_embd=64)


_prepared_cache: dict = {}


def _prepared(cfg):
    """One init per config shape per process — six scenarios must not
    pay six identical inits (keyed on the dataclass, which is
    hashable)."""
    if cfg not in _prepared_cache:
        import jax

        from dnn_tpu.models import gpt

        _prepared_cache[cfg] = gpt.prepare_stacked(
            gpt.init(jax.random.PRNGKey(0), cfg), cfg)
    return _prepared_cache[cfg]


def _tokens(seed: int, name: str, n: int, *, lo: int = 1,
            hi: int = VOCAB) -> np.ndarray:
    """n deterministic token ids in [lo, hi) — the seeded stand-in for
    tokenized user text."""
    return np.asarray(
        [lo + int(uniform(seed, name, i) * (hi - lo)) for i in range(n)],
        np.int32)


def _lm_server(cfg, prepared, *, slo_spec: Optional[SLOSpec] = None,
               **kwargs):
    """Scenario server: an in-process LMServer with the scenario's own
    SLO wired into the goodput tracker, so the LIVE burn-rate gauges
    (obs/goodput.py) watch the same objectives the post-hoc verdict
    judges — the report carries both views."""
    from dnn_tpu.runtime.lm_server import LMServer

    kwargs.setdefault("slots", 4)
    kwargs.setdefault("max_len", 64)
    kwargs.setdefault("prompt_pad", PROMPT_PAD)
    kwargs.setdefault("default_max_new", 8)
    kwargs.setdefault("request_timeout", 30.0)
    if slo_spec is not None and "slo" not in kwargs:
        from dnn_tpu.obs.goodput import SLOConfig

        kwargs["slo"] = SLOConfig(
            ttft_s=slo_spec.ttft_s, inter_token_s=slo_spec.itl_s,
            availability=slo_spec.availability,
            target=min(slo_spec.ttft_p, slo_spec.itl_p) / 100.0,
            window_s=60.0)
    return LMServer(cfg, prepared, **kwargs)


# ----------------------------------------------------------------------
# scenario builders (module-level functions, not lambdas: the analysis
# gate's lint walks real defs, and tracebacks should name the scenario)
# ----------------------------------------------------------------------

_CHAT_TENANTS = 2       # distinct shared system prompts
_CHAT_CLIENTS = 6
_CHAT_TURNS = 3
_SYSTEM_CHUNKS = 2      # system prompt = 2 full prompt_pad chunks -> a
# follow-up turn's longest cached prefix covers both


def _chat_script(seed: int, *, rate_hz: float, duration_s: float,
                 name: str = "chat") -> List[Request]:
    """Each arrival is one TURN of one client's conversation. A
    client's system prompt is its TENANT's (shared across clients of
    the tenant, chunk-aligned so the prefix cache can reuse it); the
    turn suffix is unique per (client, turn). Turns of one client are
    spread across the schedule in order."""
    arrivals = poisson_arrivals(rate_hz, duration_s, seed=seed,
                                name=f"{name}:arr")
    systems = [_tokens(seed, f"{name}:sys:{t}",
                       _SYSTEM_CHUNKS * PROMPT_PAD)
               for t in range(_CHAT_TENANTS)]
    out: List[Request] = []
    for i, at in enumerate(arrivals):
        client = i % _CHAT_CLIENTS
        turn = (i // _CHAT_CLIENTS) % _CHAT_TURNS
        tenant = client % _CHAT_TENANTS
        tail_n = 3 + int(uniform(seed, f"{name}:tail:{client}:{turn}", 0)
                         * 5)
        tail = _tokens(seed, f"{name}:msg:{client}:{turn}", tail_n)
        out.append(Request(
            at=at, prompt=np.concatenate([systems[tenant], tail]),
            max_new=6, client=f"c{client}", seed=1000 + i))
    return out


def _make_chat(light: bool) -> Scenario:
    dur = 4.0 if light else 10.0
    rate = 3.0 if light else 4.0
    cfg = _cfg()
    slo = SLOSpec(ttft_s=2.0, itl_s=1.0, availability=0.98,
                  goodput_floor_tps=2.0)

    def build():
        return _lm_server(cfg, _prepared(cfg), prefix_cache=8,
                          temperature=0.0, slo_spec=slo)

    def script(seed: int):
        return _chat_script(seed, rate_hz=rate, duration_s=dur)

    return Scenario(
        name="chat",
        description="multi-turn chat, shared system prompts (prefix "
                    "reuse), Poisson open-loop",
        slo=slo, duration_s=dur, script=script, build_server=build,
        settle_s=8.0)


def _make_longcontext(light: bool) -> Scenario:
    dur = 4.0 if light else 10.0
    rate = 1.0 if light else 1.5
    cfg = _cfg()
    max_len, pad, max_new = 144, 16, 8
    slo = SLOSpec(ttft_s=5.0, itl_s=1.5, availability=0.98,
                  goodput_floor_tps=1.0)

    def build():
        return _lm_server(cfg, _prepared(cfg), slots=2, max_len=max_len,
                          prompt_pad=pad, temperature=0.0, slo_spec=slo)

    def script(seed: int):
        arrivals = poisson_arrivals(rate, dur, seed=seed, name="lc:arr")
        out = []
        for i, at in enumerate(arrivals):
            n = 96 + int(uniform(seed, f"lc:len:{i}", 0)
                         * (max_len - max_new - 96))
            out.append(Request(
                at=at, prompt=_tokens(seed, f"lc:prompt:{i}", n),
                max_new=max_new, client=f"c{i % 3}", seed=2000 + i))
        return out

    return Scenario(
        name="longcontext",
        description="prompts near max_len (chunked-prefill regime), "
                    "low-rate Poisson",
        slo=slo, duration_s=dur, script=script, build_server=build,
        settle_s=10.0)


def _make_json_mode(light: bool) -> Scenario:
    dur = 4.0 if light else 10.0
    base = 1.5 if light else 2.0
    cfg = _cfg()
    slo = SLOSpec(ttft_s=3.0, itl_s=1.5, availability=0.98,
                  goodput_floor_tps=1.0)

    def build():
        return _lm_server(cfg, _prepared(cfg), allow_constraints=True,
                          temperature=1.0, slo_spec=slo)

    def script(seed: int):
        from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab

        cons = TokenConstraint.from_regex(r"[0-9]+", byte_vocab(VOCAB))
        arrivals = bursty_arrivals(base, dur, seed=seed,
                                   burst_factor=3.0, period_s=dur,
                                   name="json:arr")
        out = []
        for i, at in enumerate(arrivals):
            out.append(Request(
                at=at, prompt=_tokens(seed, f"json:prompt:{i}", 6),
                max_new=6, client=f"c{i % 4}", seed=3000 + i,
                opts={"constraint": cons, "temperature": 1.0}))
        return out

    return Scenario(
        name="json_mode",
        description="grammar-constrained decoding ([0-9]+) under a "
                    "bursty/diurnal envelope",
        slo=slo, duration_s=dur, script=script, build_server=build,
        settle_s=8.0)


def _make_json_mode_fast(light: bool) -> Scenario:
    """json_mode's script on the HOT path (ISSUE 16): the same bursty
    grammar-constrained population, served by an interleaved-admission +
    overlap server — the composition the on-device DFA walk unlocked
    (constrained x chunked prefill x one-step pipelining; prefix cache
    stays off: ilv x prefix reuse is still rejected loud). The paired
    `json_mode` row is the convoy-admission control; the ledger ratchets
    the throughput ratio and the host fraction via
    benchmarks/constrained_hotpath_probe.py."""
    dur = 4.0 if light else 10.0
    base = 1.5 if light else 2.0
    cfg = _cfg()
    slo = SLOSpec(ttft_s=3.0, itl_s=1.5, availability=0.98,
                  goodput_floor_tps=1.0)

    def build():
        return _lm_server(cfg, _prepared(cfg), allow_constraints=True,
                          constraint_rows=8, temperature=1.0,
                          prefill_chunk_tokens=PROMPT_PAD, overlap=True,
                          slo_spec=slo)

    def script(seed: int):
        from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab

        cons = TokenConstraint.from_regex(r"[0-9]+", byte_vocab(VOCAB))
        arrivals = bursty_arrivals(base, dur, seed=seed,
                                   burst_factor=3.0, period_s=dur,
                                   name="jsonf:arr")
        out = []
        for i, at in enumerate(arrivals):
            out.append(Request(
                at=at, prompt=_tokens(seed, f"jsonf:prompt:{i}", 6),
                max_new=6, client=f"c{i % 4}", seed=3000 + i,
                opts={"constraint": cons, "temperature": 1.0}))
        return out

    return Scenario(
        name="json_mode_fast",
        description="grammar-constrained decoding on the interleaved+"
                    "overlap hot path (device-side DFA walk), bursty "
                    "envelope",
        slo=slo, duration_s=dur, script=script, build_server=build,
        settle_s=8.0)


def _make_spec_mix(light: bool) -> Scenario:
    dur = 4.0 if light else 10.0
    rate = 2.0 if light else 3.0
    cfg = _cfg()
    slo = SLOSpec(ttft_s=3.0, itl_s=1.5, availability=0.98,
                  goodput_floor_tps=1.0)

    def build():
        from dnn_tpu.quant import quantize_gpt

        prepared = _prepared(cfg)
        # the speculative batcher samples at the SERVER-level
        # configuration (per-request temperature is the dense pool's
        # feature — serving_spec.submit rejects it loud), so the mix
        # here is across budgets, prompts and seeds, sampled pool-wide
        return _lm_server(cfg, prepared, draft_cfg=cfg,
                          draft_prepared=quantize_gpt(prepared),
                          spec_k=2, temperature=1.0, top_k=20,
                          slo_spec=slo)

    def script(seed: int):
        arrivals = poisson_arrivals(rate, dur, seed=seed,
                                    name="spec:arr")
        out = []
        for i, at in enumerate(arrivals):
            long_req = uniform(seed, f"spec:mode:{i}", 0) < 0.5
            out.append(Request(
                at=at, prompt=_tokens(seed, f"spec:prompt:{i}", 5),
                max_new=10 if long_req else 4, client=f"c{i % 4}",
                seed=4000 + i))
        return out

    return Scenario(
        name="spec_mix",
        description="speculative serving (int8 self-draft, k=2), "
                    "sampled pool under a mixed short/long-budget "
                    "population",
        slo=slo, duration_s=dur, script=script, build_server=build,
        settle_s=8.0)


def _make_lora(light: bool) -> Scenario:
    dur = 4.0 if light else 10.0
    rate = 2.0 if light else 3.0
    cfg = _cfg()

    slo = SLOSpec(ttft_s=3.0, itl_s=1.5, availability=0.98,
                  goodput_floor_tps=1.0)

    def build():
        import jax

        from dnn_tpu import lora

        prepared = _prepared(cfg)
        adapters = [lora.init_lora(jax.random.PRNGKey(s), prepared,
                                   rank=2) for s in (7, 8)]
        return _lm_server(cfg, prepared, lora_adapters=adapters,
                          temperature=0.0, slo_spec=slo)

    def script(seed: int):
        arrivals = poisson_arrivals(rate, dur, seed=seed,
                                    name="lora:arr")
        out = []
        for i, at in enumerate(arrivals):
            # three tenants: base model + two adapters, round-robin
            tenant = i % 3
            out.append(Request(
                at=at, prompt=_tokens(seed, f"lora:prompt:{i}", 5),
                max_new=6, client=f"tenant{tenant}", seed=5000 + i,
                opts=None if tenant == 0
                else {"adapter": tenant - 1}))
        return out

    return Scenario(
        name="lora",
        description="multi-tenant LoRA traffic: base + 2 adapters "
                    "interleaved in one pool",
        slo=slo, duration_s=dur, script=script, build_server=build,
        settle_s=8.0)


def _make_breach_chaos(light: bool) -> Scenario:
    dur = 3.0 if light else 6.0
    cfg = _cfg()
    slo = SLOSpec(ttft_s=2.0, availability=0.99)

    def build():
        # worker_restarts=1: the injected step-fault storm exhausts the
        # restart budget almost immediately and the server degrades to
        # fail-fast — a deterministic, reproducible availability breach
        return _lm_server(cfg, _prepared(cfg), temperature=0.0,
                          worker_restarts=1, request_timeout=10.0,
                          slo_spec=slo)

    def script(seed: int):
        return _chat_script(seed, rate_hz=3.0, duration_s=dur,
                            name="breach")

    return Scenario(
        name="breach_chaos",
        description="chat traffic under an injected device-step fault "
                    "storm — MUST breach; green means the incident "
                    "bundle reconstructs",
        slo=slo, duration_s=dur, script=script, build_server=build,
        # every step from n=2 on faults: the first worker dies, its
        # successor dies on its first step, the restart budget (1)
        # exhausts, and every queued + subsequent request fails fast
        chaos_plan={"seed": 0, "faults": [
            {"kind": "step_fault", "at_n": 2, "count": 100000}]},
        expect_breach=True, settle_s=12.0)


SCENARIOS: Dict[str, Callable[[bool], Scenario]] = {
    "chat": _make_chat,
    "longcontext": _make_longcontext,
    "json_mode": _make_json_mode,
    "json_mode_fast": _make_json_mode_fast,
    "spec_mix": _make_spec_mix,
    "lora": _make_lora,
    "breach_chaos": _make_breach_chaos,
}


def get_scenario(name: str, *, light: bool = False) -> Scenario:
    try:
        make = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; known: "
            f"{', '.join(sorted(SCENARIOS))}") from None
    return make(light)
