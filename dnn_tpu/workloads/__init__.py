"""dnn_tpu.workloads — the open-loop multi-scenario workload suite.

ROADMAP item 5's harness: items 1-4 are all judged by it, so it lands
as its own package instead of staying a per-probe one-off. Three
coordinated pieces:

  * arrival processes (workloads/arrivals.py): seeded, DETERMINISTIC
    open-loop arrival envelopes — Poisson and bursty/diurnal — built on
    the chaos planner's blake2s `decide()` idiom, so the same seed
    replays the identical schedule on every host and Python build
    (golden-pinned in tests);
  * scenarios (workloads/scenarios.py): declarative client populations
    with their own SLOs — multi-turn chat over shared system prompts
    (prefix reuse, feeds ROADMAP item 2), long-context, constrained/
    JSON-mode decoding at load, speculative greedy/sampled mixes, and
    multi-tenant LoRA traffic — each a `Scenario` whose `script(seed)`
    is a pure function of the seed;
  * the runner (workloads/runner.py): fires each scenario's schedule
    open-loop (arrivals never wait for completions) against an
    in-process `LMServer` or a gRPC address (a PR-12 router fleet
    included), records per-request TTFT / inter-token samples /
    outcomes, hands them to the SLO verdict engine (obs/slo.py), and
    on any breach snapshots the flight ring + /stepz + /fleetz into an
    on-disk incident bundle (`python -m dnn_tpu.obs incident PATH`
    renders the timeline).

Each scenario lands as a `workload_<name>` row in benchmarks/run_all.py
with its SLO asserted in-run (benchmarks/workload_probe.py), and the
whole trajectory is read back by benchmarks/ledger.py.
"""

from dnn_tpu.workloads.arrivals import (  # noqa: F401
    bursty_arrivals,
    diurnal_envelope,
    poisson_arrivals,
    uniform,
)
from dnn_tpu.workloads.scenarios import (  # noqa: F401
    Request,
    Scenario,
    SCENARIOS,
    get_scenario,
)
from dnn_tpu.workloads.runner import run_scenario  # noqa: F401

__all__ = [
    "poisson_arrivals", "bursty_arrivals", "diurnal_envelope", "uniform",
    "Request", "Scenario", "SCENARIOS", "get_scenario", "run_scenario",
]
