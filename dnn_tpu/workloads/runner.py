"""Open-loop scenario runner: fire the schedule, record, judge, dump.

The runner is the glue between a Scenario's pure script and the SLO
verdict: it fires each request at its SCHEDULED offset (open-loop —
arrivals never wait for completions; a saturated server faces the same
demand a healthy one does), records per-request TTFT / inter-token
gaps / outcome from the serving stack's own callbacks, hands the
records to obs/slo.evaluate, and on a breach writes the incident
bundle (flight ring filtered to the breach window + /stepz + /fleetz)
so the post-mortem exists the moment the verdict does.

Targets:

  * in-process (default): the scenario's own `build_server()` LMServer;
    requests ride `server.worker.submit(..., on_token=...)` — the same
    queue/admission/batcher path a gRPC request takes, minus the wire.
    First-token and inter-token timestamps come from the worker's
    per-token commit callback, so TTFT here is the queue+prefill time
    the server's own `serving.ttft_seconds` metric measures;
  * `target="host:port"`: a live LM daemon or a PR-12 router front
    door; requests ride NodeClient.generate_stream (one client per
    request, the chaos-probe pattern — a shared channel against an
    in-process router produces CANCELLED storms), and the incident
    bundle snapshots the target's obs endpoint over HTTP when
    `target_obs_url` is given.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

import numpy as np

from dnn_tpu.obs import slo as _slo
from dnn_tpu.workloads.scenarios import Scenario

__all__ = ["run_scenario"]


class _Rec:
    """Mutable per-request record; `as_dict` emits the obs/slo schema.
    Token timestamps append from the worker thread (single producer per
    request — list.append is atomic under the GIL)."""

    def __init__(self, i: int, client: str, t_sched: float):
        self.i = i
        self.client = client
        self.t_sched = t_sched   # scheduled offset (script time)
        self.t_sub: Optional[float] = None   # actual submit offset
        self.token_ts: List[float] = []      # commit offsets
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None
        self.tokens = 0
        self.t_done: Optional[float] = None

    def as_dict(self) -> dict:
        ttft = None
        itl: List[float] = []
        if self.token_ts:
            base = self.t_sub if self.t_sub is not None else self.t_sched
            ttft = self.token_ts[0] - base
            itl = [b - a for a, b in zip(self.token_ts,
                                         self.token_ts[1:])]
        d = {"i": self.i, "client": self.client,
             "t": round(self.t_sched, 4),
             "lag_s": (None if self.t_sub is None
                       else round(self.t_sub - self.t_sched, 4)),
             "outcome": self.outcome, "tokens": self.tokens,
             "ttft_s": None if ttft is None else round(ttft, 5),
             "itl_s": [round(x, 5) for x in itl],
             "t_done": (None if self.t_done is None
                        else round(self.t_done, 4))}
        if self.error:
            d["error"] = self.error[:160]
        return d


class _LocalTarget:
    """Drive an in-process LMServer through its batcher worker."""

    def __init__(self, server):
        self.server = server

    def submit(self, req, rec: _Rec, now):
        def on_token(_tok, _rec=rec, _now=now):
            _rec.token_ts.append(_now())

        fut = self.server.worker.submit(
            np.asarray(req.prompt, np.int32), req.max_new, req.seed,
            opts=req.opts, on_token=on_token)

        def done(f, _rec=rec, _now=now):
            try:
                out = f.result()
                _rec.outcome = "ok"
                _rec.tokens = int(np.asarray(out).size)
            except Exception as e:  # noqa: BLE001 — explicit rejection
                _rec.outcome = "rejected"
                _rec.error = f"{type(e).__name__}: {e}"
            # t_done LAST: the drain loop polls it, and a record seen
            # resolved before its outcome landed would judge as lost
            _rec.t_done = _now()
        fut.add_done_callback(done)
        return fut

    def warm(self, req, deadline_s: float = 240.0):
        """One request through the full admit/prefill/decode path before
        the clock starts — the timed window must measure serving, not
        XLA (every probe in this repo warms to steady state; STUDIES
        §16's warmup-artifact post-mortem is why). Uses the script's own
        first request so the compiled shapes match the traffic."""
        fut = self.server.worker.submit(
            np.asarray(req.prompt, np.int32), max(2, req.max_new // 2),
            0, opts=req.opts)
        fut.result(timeout=deadline_s)
        b = getattr(self.server, "batcher", None)
        if b is not None and getattr(b, "_prefix_cache", None) is not None:
            # the warm request primes the prefix cache (fine — real
            # fleets run warm) but must not inflate the REPORTED ratio
            b.prefix_hits = b.prefix_misses = 0

    def grace_s(self) -> float:
        return float(getattr(self.server, "request_timeout", 30.0)) + 5.0

    def forensics(self) -> dict:
        srv = self.server
        return {"stepclock": getattr(srv, "step_clock", None),
                "goodput": getattr(srv, "goodput", None),
                "batcher": getattr(srv, "batcher", None)}

    def close(self):
        self.server.close()


class _GrpcTarget:
    """Drive a live daemon / router at `address` over the wire. One
    NodeClient + thread per request; token timestamps come from the
    GenerateStream commits, so TTFT/ITL are wire-true."""

    def __init__(self, address: str, *, timeout_s: float = 30.0):
        self.address = address
        self.timeout_s = timeout_s
        self._threads: List[threading.Thread] = []

    def submit(self, req, rec: _Rec, now):
        def run():
            # EVERYTHING inside the try — a client-construction (or
            # import) failure must record an explicit rejection, never
            # leave the record outcome-less to be judged silently lost
            cl = None
            try:
                from dnn_tpu.comm.client import NodeClient

                cl = NodeClient(self.address, transport="grpc",
                                breaker=False)
                opts = dict(req.opts or {})
                if "constraint" in opts:
                    # constraints have no wire spelling (gen options
                    # carry scalars); a remote constrained scenario
                    # must fail loud, not silently unconstrained
                    raise ValueError(
                        "constraint= requests cannot ride the gRPC "
                        "target; run json_mode in-process")
                n = 0
                for _tok in cl.generate_stream(
                        req.prompt, max_new_tokens=req.max_new,
                        seed=req.seed, timeout=self.timeout_s, **opts):
                    rec.token_ts.append(now())
                    n += 1
                rec.outcome = "ok"
                rec.tokens = n
            except Exception as e:  # noqa: BLE001 — explicit rejection
                rec.outcome = "rejected"
                rec.error = f"{type(e).__name__}: {e}"
            finally:
                rec.t_done = now()
                if cl is not None:
                    cl.close()

        th = threading.Thread(target=run, daemon=True)
        th.start()
        self._threads.append(th)
        return th

    def grace_s(self) -> float:
        return self.timeout_s + 5.0

    def warm(self, req, deadline_s: float = 240.0):
        """Polled first request (the fleet probe's pattern): a mid-boot
        UNAVAILABLE is 'not yet', not 'failed'."""
        from dnn_tpu.comm.client import NodeClient

        t_end = time.monotonic() + deadline_s
        last = "no attempt"
        while time.monotonic() < t_end:
            cl = NodeClient(self.address, transport="grpc",
                            breaker=False)
            try:
                cl.generate(req.prompt, max_new_tokens=2, seed=0,
                            timeout=min(120.0, deadline_s))
                return
            except Exception as e:  # noqa: BLE001 — still booting
                last = f"{type(e).__name__}: {e}"
            finally:
                cl.close()
            time.sleep(1.0)
        raise RuntimeError(f"warm request never completed: {last[:200]}")

    def forensics(self) -> dict:
        return {}

    def close(self):
        for th in self._threads:
            th.join(timeout=1.0)


def run_scenario(scenario: Scenario, *, seed: int = 0,
                 target: Optional[str] = None,
                 target_obs_url: Optional[str] = None,
                 incident_dir: Optional[str] = None) -> dict:
    """Run one scenario end to end; returns

        {"report": SLOReport, "records": [dict], "wall_s": float,
         "bundle": path|None, "extras": {...}}

    `target` (a "host:port" string) redirects the load onto a live
    daemon or router instead of the scenario's own in-process server;
    `target_obs_url` then lets breach forensics snapshot that process's
    obs endpoint. The incident bundle is written whenever the verdict
    is a breach — under `incident_dir` (default: a fresh directory in
    $DNN_TPU_OBS_DIR / tmp) — and its path rides the result."""
    from dnn_tpu import obs
    from dnn_tpu.chaos import inject as chaos_inject

    script = sorted(scenario.script(seed), key=lambda r: r.at)
    if not script:
        raise ValueError(f"scenario {scenario.name!r} produced an "
                         "empty script")
    own_server = target is None
    tgt = (_LocalTarget(scenario.build_server()) if own_server
           else _GrpcTarget(target))
    injector = None
    try:
        # warm BEFORE the chaos plan installs: the injected fault
        # schedule counts from the measured window's first step, and
        # the warm request must pay the compiles, not the timed traffic
        tgt.warm(script[0])
        if scenario.chaos_plan is not None:
            injector = chaos_inject.install(dict(scenario.chaos_plan))
        t0 = time.monotonic()
        t0_epoch = time.time()

        def now() -> float:
            return time.monotonic() - t0

        obs.flight.record("workload_begin", scenario=scenario.name,
                          seed=seed, requests=len(script),
                          duration_s=scenario.duration_s,
                          target=target or "in-process")
        records = [_Rec(i, r.client, r.at)
                   for i, r in enumerate(script)]
        try:
            for req, rec in zip(script, records):
                while (dt := req.at - now()) > 0:
                    time.sleep(min(dt, 0.02))
                rec.t_sub = now()
                try:
                    tgt.submit(req, rec, now)
                except Exception as e:  # noqa: BLE001 — a submit-time
                    # crash is an explicit rejection, never a lost record
                    rec.outcome = "rejected"
                    rec.error = f"{type(e).__name__}: {e}"
                    rec.t_done = now()

            # drain: open-loop stops ARRIVING at duration_s; completions
            # get the settle window beyond that. Stragglers still in
            # flight then get the target's request-timeout grace on top
            # — the serving stack promises EXPLICIT resolution within
            # that bound, so only a record that outlasts it has truly
            # violated the no-silent-loss contract (slow != lost)
            deadline = scenario.duration_s + scenario.settle_s
            hard = deadline + tgt.grace_s()
            while now() < hard and any(r.t_done is None
                                       for r in records):
                time.sleep(0.05)
        finally:
            if injector is not None:
                chaos_inject.uninstall()
                injector = None

        wall = now()
        fx = tgt.forensics()
        burn = None
        if fx.get("goodput") is not None:
            try:
                burn = {k: round(v, 4) for k, v in
                        fx["goodput"].burn_rates().items()}
            except Exception:  # noqa: BLE001 — a dead tracker loses
                burn = None    # only the rider field, never the verdict
        rec_dicts = [r.as_dict() for r in records]
        report = _slo.evaluate(scenario.name, rec_dicts, scenario.slo,
                               wall_s=wall, t0_epoch=t0_epoch,
                               burn_rates=burn)
        obs.flight.record("workload_verdict", scenario=scenario.name,
                          ok=report.ok, completed=report.completed,
                          rejected=report.rejected, lost=report.lost,
                          goodput_tps=report.goodput_tps)

        extras: dict = {}
        b = fx.get("batcher")
        if b is not None \
                and getattr(b, "_prefix_cache", None) is not None:
            looked = b.prefix_hits + b.prefix_misses
            extras["prefix_hits"] = b.prefix_hits
            extras["prefix_misses"] = b.prefix_misses
            extras["prefix_hit_ratio"] = round(
                b.prefix_hits / looked, 4) if looked else 0.0

        bundle = None
        if not report.ok:
            if incident_dir is None:
                from dnn_tpu.obs.flight import default_dump_dir

                incident_dir = os.path.join(
                    default_dump_dir(),
                    f"incident-{scenario.name}-{os.getpid()}-"
                    f"{int(t0_epoch)}")
            if target_obs_url is not None:
                bundle = _slo.write_incident_bundle(
                    incident_dir, report, url=target_obs_url,
                    records=rec_dicts)
            else:
                bundle = _slo.write_incident_bundle(
                    incident_dir, report,
                    stepclock=fx.get("stepclock"), records=rec_dicts)
    finally:
        # a failed warm / mid-run crash must not leak the in-process
        # server (its worker thread and obs endpoint outlive the call)
        if injector is not None:
            chaos_inject.uninstall()
        tgt.close()
    return {"report": report, "records": rec_dicts,
            "wall_s": round(wall, 3), "bundle": bundle,
            "extras": extras}
