"""Seeded, deterministic open-loop arrival processes.

An open-loop generator decides WHEN requests arrive independently of
how the server is doing — the arrival schedule is fixed before the
first request fires, so a saturated server faces the same demand a
healthy one does (closed-loop load self-throttles and hides collapse;
STUDIES §17's admit-then-deadline-cancel pathology is only visible
open-loop).

Determinism contract (same as dnn_tpu/chaos/plan.py): every draw comes
from `uniform(seed, name, i)` — a blake2s hash of the triple — so the
same seed yields the identical arrival times and client scripts on any
host, any Python build, any thread timing. No `random`, no numpy RNG
(whose bit streams are version-pinned promises we don't control), no
wall clock. Tests pin golden schedules.

Two envelopes:

  * `poisson_arrivals(rate_hz, duration_s, seed=...)` — homogeneous
    Poisson: exponential inter-arrival gaps via inverse transform,
    the memoryless baseline every queueing result assumes;
  * `bursty_arrivals(...)` — inhomogeneous Poisson by THINNING
    (Lewis-Shedler): candidates are drawn at the peak rate and each is
    kept with probability rate(t)/peak, where rate(t) follows
    `diurnal_envelope` — a smooth raised-cosine day/night cycle with a
    configurable burst factor. Thinning keeps the determinism trivial
    (two draws per candidate, both counter-indexed) and is exact, not
    an approximation.
"""

from __future__ import annotations

import hashlib
import math
from typing import List

__all__ = ["uniform", "poisson_arrivals", "bursty_arrivals",
           "diurnal_envelope"]


def uniform(seed: int, name: str, i: int) -> float:
    """Pure seeded draw in [0, 1) for the i-th use of `name` — the one
    source of randomness in this package (chaos/plan.decide's idiom,
    kept separate so workload schedules and fault schedules can never
    collide on a seam name)."""
    h = hashlib.blake2s(
        f"wl:{seed}:{name}:{i}".encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


def poisson_arrivals(rate_hz: float, duration_s: float, *, seed: int,
                     name: str = "poisson") -> List[float]:
    """Arrival offsets (seconds, ascending) of a homogeneous Poisson
    process at `rate_hz` over [0, duration_s)."""
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    out: List[float] = []
    t, i = 0.0, 0
    while True:
        u = uniform(seed, name, i)
        i += 1
        # inverse-transform exponential; 1-u keeps u=0 finite
        t += -math.log(1.0 - u) / rate_hz
        if t >= duration_s:
            return out
        out.append(t)


def diurnal_envelope(t: float, period_s: float, *,
                     burst_factor: float = 4.0) -> float:
    """Rate multiplier in [1, burst_factor] at offset `t` of a
    raised-cosine day/night cycle: trough 1.0 at t=0, peak
    `burst_factor` at t=period/2. A compressed 'diurnal' shape — real
    traffic's 24 h cycle scaled down to a bench-runnable period."""
    if period_s <= 0:
        raise ValueError(f"period_s must be > 0, got {period_s}")
    if burst_factor < 1.0:
        raise ValueError(
            f"burst_factor must be >= 1, got {burst_factor}")
    phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period_s))
    return 1.0 + (burst_factor - 1.0) * phase


def bursty_arrivals(base_rate_hz: float, duration_s: float, *,
                    seed: int, burst_factor: float = 4.0,
                    period_s: float = 20.0,
                    name: str = "bursty") -> List[float]:
    """Arrival offsets of an inhomogeneous Poisson process whose rate
    follows `base_rate_hz * diurnal_envelope(t)` — bursts up to
    `burst_factor` x base at each period's peak. Exact Lewis-Shedler
    thinning: candidates at the peak rate, each kept with probability
    rate(t)/peak; both draws are counter-indexed so the schedule is a
    pure function of the seed."""
    if base_rate_hz <= 0:
        raise ValueError(f"base_rate_hz must be > 0, got {base_rate_hz}")
    peak = base_rate_hz * burst_factor
    out: List[float] = []
    t, i = 0.0, 0
    while True:
        u = uniform(seed, f"{name}:gap", i)
        keep = uniform(seed, f"{name}:keep", i)
        i += 1
        t += -math.log(1.0 - u) / peak
        if t >= duration_s:
            return out
        rate_t = base_rate_hz * diurnal_envelope(
            t, period_s, burst_factor=burst_factor)
        if keep < rate_t / peak:
            out.append(t)
