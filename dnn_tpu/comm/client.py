"""gRPC edge client — the initiator role.

Rebuilds the reference's node-0 client path (initiate_inference,
node.py:137-200): run the local stage, send the activation downstream, wait
for the result to ride back up the response chain, return the final tensor.
Adds what the reference lacked (SURVEY §5 "Failure detection ... No retry"):
a real HealthCheck probe before submitting (its HealthCheck had no caller —
SURVEY §3.4), channel reuse, and bounded retries with exponential backoff
on transient transport failures.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import grpc
import numpy as np

from dnn_tpu import obs
from dnn_tpu.chaos import inject as _chaos_inject
from dnn_tpu.comm import transport as _tx
from dnn_tpu.comm import wire_pb2 as pb
from dnn_tpu.comm import wirecodec as wc
from dnn_tpu.comm.service import (
    PER_STAGE_BUDGET_S,
    RETRYABLE_CODES,
    SERVICE_NAME,
    _tensor_arr,
    _tensor_msg,
    full_jitter_delay as _backoff_delay,
)
from dnn_tpu.io.serialization import PayloadCorruptError
from dnn_tpu.utils.metrics import labeled

log = logging.getLogger("dnn_tpu.comm")


def pipeline_budget(num_parts: int, *, margin: float = 30.0,
                    transport: str = "grpc", warm: bool = False) -> float:
    """Overall edge-client budget for one pipeline traversal: one per-stage
    slice per part plus a margin. Strictly larger than the first hop's
    server-side budget (transport.hop_budget_s over num_parts - 1 stages,
    see StageServer._forward), so a downstream timeout surfaces to the
    client as an error status from the first stage, never as the client's
    own DEADLINE_EXCEEDED racing the relay. `transport` is the edge hop's
    NEGOTIATED transport — a device/shm pipeline sheds the gRPC
    serialization margin per stage (the satellite fix), and `warm=True`
    additionally drops to the post-compile slice ONLY when the caller
    knows every downstream hop is warm too: the domination invariant
    above assumes uniform rungs, so a cold or mixed pipeline must keep
    the default cold slice (and a pipeline whose downstream rungs fall
    back to grpc should keep transport="grpc", whose arithmetic is
    reference-compatible bit-exact)."""
    if transport in ("device", "shm"):
        return _tx.hop_budget_s(transport, num_parts, warm=warm) + margin
    return PER_STAGE_BUDGET_S * num_parts + margin



class CircuitOpenError(RuntimeError):
    """Raised by a fast-failing client whose breaker is OPEN: the target
    has failed `threshold` consecutive calls and the cooldown has not
    elapsed. Callers treat it like UNAVAILABLE without paying the
    connect timeout + retry ladder per request."""


class CircuitBreaker:
    """Per-target circuit breaker: closed -> (threshold consecutive
    failures) -> open -> (cooldown) -> half-open (ONE probe call) ->
    closed on success / open with doubled cooldown on failure. A
    flapping stage then sheds load in O(1) per request instead of
    burning a full retry ladder each, and the half-open probe bounds
    detection of recovery to one cooldown. Thread-safe; state
    transitions land in the flight ring and the
    `comm.circuit_state{target=}` gauge (0 closed / 1 half-open / 2
    open)."""

    _STATE_VAL = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

    def __init__(self, target: str = "", *, threshold: int = 5,
                 cooldown_s: float = 1.0, max_cooldown_s: float = 30.0):
        self.target = target
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.max_cooldown_s = float(max_cooldown_s)
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._cooldown = self.cooldown_s
        m = obs.metrics()
        if m is not None:
            m.set_fn(labeled("comm.circuit_state", target=target),
                     lambda: self._STATE_VAL[self._state])

    @property
    def state(self) -> str:
        return self._state

    def release(self):
        """Give back a HALF-OPEN probe slot without judging it — used
        when the caller that consumed the slot DELEGATES the actual
        call elsewhere (send_tensors falling back to per-item
        send_tensor, which runs its own allow/record cycle). Re-opens
        with the cooldown already elapsed, so the next allow() hands
        the probe slot to the delegate immediately; without this the
        breaker would sit in half_open with no probe in flight and
        shed 100% of traffic forever."""
        with self._lock:
            if self._state == "half_open":
                self._state = "open"
                self._opened_at = time.monotonic() - self._cooldown

    def allow(self) -> bool:
        """True when a call may proceed. In OPEN, flips to HALF-OPEN
        (allowing exactly one probe) once the cooldown elapses."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if time.monotonic() - self._opened_at < self._cooldown:
                    return False
                self._state = "half_open"
                obs.flight.record("circuit_half_open", target=self.target)
                return True
            # half_open: one probe is already in flight
            return False

    def record(self, ok: bool):
        with self._lock:
            if ok:
                if self._state != "closed":
                    obs.flight.record("circuit_close", target=self.target)
                self._state = "closed"
                self._failures = 0
                self._cooldown = self.cooldown_s
                return
            self._failures += 1
            if self._state == "half_open":
                # failed probe: reopen with a longer cooldown
                self._state = "open"
                self._opened_at = time.monotonic()
                self._cooldown = min(self._cooldown * 2,
                                     self.max_cooldown_s)
                obs.flight.record("circuit_reopen", target=self.target,
                                  cooldown_s=round(self._cooldown, 3))
            elif self._state == "closed" \
                    and self._failures >= self.threshold:
                self._state = "open"
                self._opened_at = time.monotonic()
                obs.flight.record("circuit_open", target=self.target,
                                  failures=self._failures,
                                  cooldown_s=round(self._cooldown, 3))


def _gen_rid(max_new_tokens, seed, temperature, top_k, top_p,
             adapter=None, min_p=None, repetition_penalty=None,
             logit_bias=None, dedup=None):
    """Encode generation options into the request_id the LM daemon parses
    (lm_server.parse_gen_options): positional max_new/seed, then named
    t=/k=/p=/m=/r= sampling overrides and a= (the per-request LoRA
    adapter index of a multi-adapter server)."""
    rid = f"gen:{max_new_tokens}" + (f":{seed}" if seed is not None else "")
    if temperature is not None:
        rid += f":t={temperature}"
    if top_k is not None:
        rid += f":k={top_k}"
    if top_p is not None:
        rid += f":p={top_p}"
    if min_p is not None:
        rid += f":m={min_p}"
    if repetition_penalty is not None:
        rid += f":r={repetition_penalty}"
    if logit_bias:
        pairs = ",".join(f"{int(t)}~{float(v)}"
                         for t, v in logit_bias.items())
        rid += f":b={pairs}"
    if adapter is not None:
        rid += f":a={adapter}"
    if dedup is not None:
        # exactly-once guard: the LM daemon's admission dedups on this
        # key, so a client-side retry after a drain/requeue can never
        # run the same generation twice (lm_server parse_gen_options d=)
        rid += f":d={dedup}"
    return rid


class NodeClient:
    """Sync client for a NodeService endpoint (ours or a reference node's —
    the wire protocol is identical).

    `transport` sets the hop preference for tensor submissions
    (comm/transport.py): "auto" (default) negotiates device -> shm ->
    grpc on first send via a wire-compatible SendMessage handshake —
    reference peers (and the LM daemon, which declines) land on grpc
    transparently; explicit "device"/"shm" fail loud when unsatisfiable;
    "grpc" skips the handshake entirely (byte-identical reference
    behavior).

    Resilience (ISSUE 8): `breaker=True` (default) runs a per-client
    CircuitBreaker — after `threshold` consecutive terminal send
    failures the client fails fast (CircuitOpenError) for a cooldown
    instead of burning the full retry ladder per request, with one
    half-open probe per cooldown to detect recovery; pass False to
    disable or a prebuilt CircuitBreaker to share/tune one. A gRPC
    channel that entered connect backoff is REBUILT (fresh channel)
    after `rebuild_after` consecutive UNAVAILABLE outcomes: a sync
    channel whose first connects failed can sit out gRPC's internal
    reconnect backoff and miss a server that has since come up — the
    PR 7 lesson the transport test used to work around with a fresh
    client per poll. Health probes count toward (and benefit from) the
    rebuild streak but bypass the breaker — they ARE the recovery
    probe."""

    REBUILD_AFTER = 2  # consecutive UNAVAILABLEs before a fresh channel

    def __init__(self, address: str, *, transport: str = "auto",
                 breaker=True, rebuild_after: Optional[int] = None):
        from dnn_tpu.native import native_available

        native_available()  # warm the one-time native codec build up front
        if transport not in _tx.TRANSPORTS:
            raise ValueError(
                f"transport must be one of {_tx.TRANSPORTS}, got "
                f"{transport!r}")
        self.address = address
        self.transport = transport
        self._channel = grpc.insecure_channel(
            address, options=_tx.GRPC_MSG_OPTIONS)
        self._chan_lock = threading.Lock()
        self._conn_fail_streak = 0
        self._last_rebuild = 0.0
        self.rebuild_after = self.REBUILD_AFTER if rebuild_after is None \
            else int(rebuild_after)
        self.channel_rebuilds = 0
        if breaker is True:
            self.breaker: Optional[CircuitBreaker] = CircuitBreaker(address)
        elif breaker:
            self.breaker = breaker
        else:
            self.breaker = None
        self._negotiated: Optional[_tx.Negotiated] = None
        self._neg_lock = threading.Lock()

    # -- channel health (the wedged-backoff rebuild, ISSUE 8 satellite) --

    def _note_conn_result(self, code) -> None:
        """Track consecutive connect-level failures; at `rebuild_after`
        the channel is replaced wholesale. Only UNAVAILABLE counts —
        it is the one code gRPC returns both for a refused connect and
        for a channel sitting in reconnect backoff; application errors
        (INVALID_ARGUMENT, DEADLINE on a live server) prove the
        connection works and reset the streak."""
        if code != grpc.StatusCode.UNAVAILABLE:
            self._conn_fail_streak = 0
            return
        self._conn_fail_streak += 1
        if self._conn_fail_streak >= self.rebuild_after:
            self._rebuild_channel()

    def _rebuild_channel(self):
        with self._chan_lock:
            now = time.monotonic()
            if now - self._last_rebuild < 1.0:
                # concurrent failing calls all cross the streak at once
                # during an outage; one fresh channel per second is the
                # fix — a rebuild storm is not
                self._conn_fail_streak = 0
                return
            self._last_rebuild = now
            old, self._channel = self._channel, grpc.insecure_channel(
                self.address, options=_tx.GRPC_MSG_OPTIONS)
            self._conn_fail_streak = 0
            self.channel_rebuilds += 1
        try:
            old.close()  # cancels any straggler calls still parked on
            # the backoff channel — they were failing anyway
        except Exception:  # noqa: BLE001 — already-closed channel
            pass
        m = obs.metrics()
        if m is not None:
            m.inc(labeled("comm.channel_rebuilds_total",
                          target=self.address))
        obs.flight.record("channel_rebuild", target=self.address,
                          rebuilds=self.channel_rebuilds)
        log.info("rebuilt gRPC channel to %s after %d consecutive "
                 "connect failures", self.address, self.rebuild_after)

    # -- transport negotiation (comm/transport.py) ----------------------

    def _raw_send_message(self, sender_id: str, text: str,
                          timeout: float = 10.0) -> str:
        """Bare SendMessage (no spans/tagging) — the negotiation
        side-channel."""
        call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/SendMessage",
            request_serializer=pb.MessageRequest.SerializeToString,
            response_deserializer=pb.MessageReply.FromString,
        )
        return call(pb.MessageRequest(sender_id=sender_id,
                                      message_text=text),
                    timeout=timeout).confirmation_text

    def _ensure_negotiated(self) -> _tx.Negotiated:
        """Negotiate once per client. A transport-level RPC failure
        (endpoint not up yet) returns an UNCACHED grpc verdict — the
        unary send's own retry loop handles the outage, and the
        handshake reruns on the next call. TransportMisconfigError
        (explicit request refused) propagates — fail-loud."""
        with self._neg_lock:
            if self._negotiated is not None:
                return self._negotiated
            if self.transport == "grpc":
                self._negotiated = _tx.Negotiated(
                    "grpc", _tx.GrpcSender(), reason="explicit")
                return self._negotiated
            try:
                neg = _tx.negotiate_over(
                    self._raw_send_message, transport=self.transport,
                    target=self.address)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.UNIMPLEMENTED:
                    # peer has no SendMessage at all: a permanent verdict
                    self._negotiated = _tx.Negotiated(
                        "grpc", _tx.GrpcSender(), reason="no SendMessage")
                    return self._negotiated
                return _tx.Negotiated("grpc", _tx.GrpcSender(),
                                      reason=f"hello failed: {code}")
            self._negotiated = neg
            return neg

    def health_check(self, timeout: float = 5.0) -> bool:
        call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/HealthCheck",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.HealthCheckResponse.FromString,
        )
        try:
            healthy = bool(call(pb.Empty(), timeout=timeout).is_healthy)
            self._note_conn_result(None)
            return healthy
        except grpc.RpcError as e:
            # a probe that can't CONNECT advances the rebuild streak, so
            # polling health against a late-starting server self-heals
            # out of gRPC's internal backoff (wait_healthy needs no
            # fresh-client workaround anymore)
            self._note_conn_result(e.code() if hasattr(e, "code")
                                   else None)
            return False

    def send_message(self, sender_id: str, text: str, timeout: float = 5.0) -> str:
        call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/SendMessage",
            request_serializer=pb.MessageRequest.SerializeToString,
            response_deserializer=pb.MessageReply.FromString,
        )
        # trace tag rides sender_id (the text front's request_id analog)
        with obs.start_span("rpc.SendMessage", parent=obs.current_span(),
                            target=self.address) as sp:
            return call(
                pb.MessageRequest(
                    sender_id=obs.tag_request_id(sender_id, sp),
                    message_text=text),
                timeout=timeout,
            ).confirmation_text

    def wait_healthy(self, deadline: float = 30.0, interval: float = 0.5) -> bool:
        """Poll HealthCheck until it answers healthy or `deadline` seconds
        elapse. The startup-ordering fix for the reference's blind 2-second
        sleep before initiating (start_inference_after_delay, node.py:203-207)."""
        t_end = time.monotonic() + deadline
        while True:
            if self.health_check(timeout=min(5.0, interval * 4)):
                return True
            if time.monotonic() >= t_end:
                return False
            time.sleep(interval)

    def send_tensor(
        self,
        arr: np.ndarray,
        *,
        request_id: str = "req",
        timeout: float = 60.0,
        retries: int = 2,
        backoff: float = 0.2,
    ) -> tuple[str, Optional[np.ndarray]]:
        """Submit an activation; returns (status, final_tensor_or_None) —
        the response-chain semantics of node.py:180-194. Transient transport
        failures (RETRYABLE_CODES) are retried up to `retries` times with
        exponential backoff; the pipeline is stateless per request, so a
        resend is safe. `timeout` is the OVERALL budget across all attempts
        and backoff sleeps, not a per-attempt deadline.

        The payload rides the NEGOTIATED transport: a device hop hands
        the array through the in-process mailbox (zero serialization), a
        shm hop writes it once into a shared ring slot, and the grpc
        fallback carries the inline zero-copy tensor — byte-identical to
        the reference wire. Ticket payloads persist until the response
        lands, so transport-level retries stay safe on every rung.

        Observability: the call runs under an `rpc.SendTensor` span
        (parented to the ambient obs span when one is active) carrying a
        `transport` attr, and the span's trace rides to the server as a
        `tr=` request_id segment — wire-compatible (every peer treats
        request_id as opaque; our servers parse and continue the trace).
        Per-attempt latency and payload bytes land in the shared
        registry (histograms labeled by transport, plus the
        exact-quantile `comm.hop_seconds` series); each retry bumps
        `comm.retries_total{target=...,outcome=<code>}` (full-jitter
        backoff — see _backoff_delay) and logs the trace id so a
        backoff storm is attributable to the requests living through
        it. The remaining budget rides the wire as a `dl=` request_id
        segment (comm/transport.tag_deadline) for downstream hops to
        honor, and the client-side circuit breaker (see the class
        docstring) fails fast when the target is flapping."""
        if self.breaker is not None and not self.breaker.allow():
            raise CircuitOpenError(
                f"circuit open for {self.address}: shedding fast "
                f"(cooldown {self.breaker._cooldown:.1f}s)")
        neg = self._ensure_negotiated()
        sp = obs.start_span("rpc.SendTensor", parent=obs.current_span(),
                            target=self.address, transport=neg.name)
        # the propagated deadline (dl=) rides the request_id: downstream
        # hops cap their own forward/retry budgets to what THIS caller
        # still has, so a chain can never over-spend a dying deadline
        rid = obs.tag_request_id(request_id, sp) if sp else request_id
        request = neg.sender.make_request(
            arr, _tx.tag_deadline(rid, timeout))
        m = obs.metrics()
        deadline = time.monotonic() + timeout
        attempt = 0
        completed = False
        try:
            while True:
                remaining = deadline - time.monotonic()
                # refresh the propagated deadline EVERY attempt: after
                # retries + backoff the wire must advertise what is
                # actually left, not the original budget — or every
                # downstream hop over-spends a nearly-dead request
                request.request_id = _tx.tag_deadline(rid, remaining)
                t_try = time.perf_counter()
                if m is not None:
                    # per ATTEMPT: retries resend the payload, and the
                    # counter must match the bytes actually on the wire
                    # (and the server's direction="in" count)
                    m.inc(labeled("comm.payload_bytes_total",
                                  direction="out"), request.ByteSize())
                # inside the loop: a channel rebuild between attempts
                # must take effect on the NEXT attempt, not the next
                # send_tensor call
                call = self._channel.unary_unary(
                    f"/{SERVICE_NAME}/SendTensor",
                    request_serializer=wc.serialize_request,
                    response_deserializer=wc.parse_response,
                )
                try:
                    _chaos_inject.perturb_rpc("client", self.address)
                    t_send_wall = time.time() if sp else 0.0
                    resp = call(request, timeout=max(remaining, 0.001))
                    dt = time.perf_counter() - t_try
                    if sp:
                        # clock-offset sampling for cross-host trace
                        # stitching (obs/fleet.py): the SUCCESSFUL
                        # attempt's wall-clock send/receive window — the
                        # span's own ts/dur covers retries and backoff
                        # sleeps, which would bias the NTP-style
                        # midpoint estimate by seconds
                        sp.set(cs=t_send_wall, cr=time.time())
                    if m is not None:
                        m.observe_hist(
                            labeled("comm.rpc_latency_seconds",
                                    method="SendTensor", role="client",
                                    transport=neg.name),
                            dt)
                        m.observe(labeled("comm.hop_seconds",
                                          target=self.address,
                                          transport=neg.name,
                                          mode="nested"), dt)
                        m.inc(labeled("comm.payload_bytes_total",
                                      direction="in"), resp.ByteSize())
                    # decode INSIDE the loop: a crc32c mismatch on the
                    # response is transient corruption, and resending is as
                    # safe as for a transport failure.
                    result = (
                        _tensor_arr(resp.result_tensor)
                        if resp.HasField("result_tensor") else None
                    )
                    sp.set(attempts=attempt + 1)
                    completed = True
                    self._note_conn_result(None)
                    return resp.status, result
                except (grpc.RpcError, PayloadCorruptError) as e:
                    code = e.code() if isinstance(e, grpc.RpcError) else None
                    self._note_conn_result(code)
                    if m is not None and \
                            code == grpc.StatusCode.DEADLINE_EXCEEDED:
                        m.inc(labeled("comm.deadline_exceeded_total",
                                          target=self.address))
                    retryable = isinstance(e, PayloadCorruptError) \
                        or code in RETRYABLE_CODES
                    # full jitter: decorrelates the retry herd so a
                    # partial outage is not amplified by synchronized
                    # resends; the out-of-budget check uses the WORST
                    # CASE delay, so the ladder still respects the
                    # propagated deadline exactly
                    worst = backoff * (2 ** attempt)
                    out_of_budget = deadline - time.monotonic() <= worst
                    if not retryable or attempt >= retries or out_of_budget:
                        sp.set(error=str(code or e), attempts=attempt + 1)
                        raise
                    delay = _backoff_delay(backoff, attempt)
                    if m is not None:
                        m.inc(labeled(
                            "comm.retries_total", target=self.address,
                            outcome=(code.name.lower() if code
                                     else "payload_corrupt")))
                    obs.flight.record(
                        "rpc_retry", target=self.address,
                        code=str(code or type(e).__name__),
                        attempt=attempt + 1, trace_id=sp.trace_id)
                    log.warning(
                        "send_tensor to %s failed (%s), retry %d/%d in "
                        "%.2fs [trace=%s]",
                        self.address, code or e, attempt + 1, retries,
                        delay, sp.trace_id or "-",
                    )
                    time.sleep(delay)
                    attempt += 1
        finally:
            # ticket payloads (device mailbox entry / shm ring slot)
            # live until the hop resolves, so retries can resend them
            if completed:
                neg.sender.sent_ok(request)
            else:
                neg.sender.cleanup(request)
            if self.breaker is not None:
                self.breaker.record(completed)
            sp.end()

    def send_tensors(
        self,
        arrs,
        *,
        request_id: str = "req",
        timeout: float = 120.0,
    ):
        """Submit a SEQUENCE of activations (microbatches) over the
        streamed Relay path: every item is acked by the first stage as
        soon as it is accepted, so stage 0 computes microbatch m+1 while
        the downstream stages work on m — the cross-process MPMD overlap
        the nested unary chain cannot express. Oversized payloads ride
        chunked (comm/transport.py CHUNK_BYTES), lifting the unary
        path's 4 MB gRPC message ceiling.

        Returns [(status, result_or_None), ...] in submission order.
        NOT retried: the stream is stateful (acks already released
        payload slots) — callers needing at-least-once fall back to
        per-item `send_tensor`. Peers without the Relay RPC (reference
        nodes) degrade to exactly that sequential unary fallback."""
        arrs = list(arrs)
        if not arrs:
            return []
        if self.breaker is not None and not self.breaker.allow():
            raise CircuitOpenError(
                f"circuit open for {self.address}: shedding fast")
        # EVERY exit path below must settle the breaker exactly once:
        # record an outcome, or RELEASE the (possibly half-open) probe
        # slot when the call is delegated to send_tensor, which runs
        # its own allow/record cycle — an un-settled half_open slot
        # would shed all traffic forever
        recorded = False

        def _breaker_done(ok: bool):
            nonlocal recorded
            if self.breaker is not None and not recorded:
                self.breaker.record(ok)
            recorded = True

        def _breaker_release():
            nonlocal recorded
            if self.breaker is not None and not recorded:
                self.breaker.release()
            recorded = True

        request_id = _tx.tag_deadline(request_id, timeout)
        neg = self._ensure_negotiated()
        if neg.relay_known and not neg.relay_ok:
            # the handshake already said the peer has no Relay RPC
            # (reference protocol): go straight to the unary chain
            # instead of paying a doomed probe per call
            _breaker_release()
            return [self.send_tensor(a, request_id=request_id,
                                     timeout=timeout) for a in arrs]
        sp = obs.start_span("rpc.Relay", parent=obs.current_span(),
                            target=self.address, transport=neg.name,
                            items=len(arrs))
        m = obs.metrics()
        pending = {}
        send_ts = {}
        results: dict = {}
        statuses: dict = {}

        def frames():
            for seq, arr in enumerate(arrs):
                req = neg.sender.make_request(
                    arr, obs.tag_request_id(request_id, sp)
                    if sp else request_id)
                pending[seq] = req
                send_ts[seq] = time.perf_counter()
                yield from _tx.split_requests(req, seq)

        call = self._channel.stream_stream(
            f"/{SERVICE_NAME}/Relay",
            request_serializer=wc.serialize_request,
            response_deserializer=wc.parse_response,
        )
        try:
            for resp in call(frames(), timeout=timeout):
                seq = _tx.parse_ack(resp.status)
                if seq is not None:
                    req = pending.pop(seq, None)
                    if req is not None:
                        neg.sender.sent_ok(req)
                    if m is not None and seq in send_ts:
                        # hop latency under the streamed schedule:
                        # submit -> first-stage accept
                        dt = time.perf_counter() - send_ts[seq]
                        m.observe(labeled("comm.hop_ack_seconds",
                                          target=self.address,
                                          transport=neg.name), dt)
                        m.observe_hist(
                            labeled("comm.rpc_latency_seconds",
                                    method="Relay", role="client",
                                    transport=neg.name), dt)
                    continue
                seq, human = _tx.parse_result(resp.status)
                if seq is None or seq < 0:
                    # stream-level error status: surfaces on every
                    # not-yet-answered item
                    raise RuntimeError(
                        f"relay stream error: {human or resp.status}")
                statuses[seq] = human
                results[seq] = (_tensor_arr(resp.result_tensor)
                                if resp.HasField("result_tensor") else None)
                if len(results) == len(arrs):
                    break
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                # reference peer: sequential unary fallback (idempotent
                # per item, so the ordinary retry machinery applies)
                sp.end(fallback="unary")
                _breaker_release()
                return [self.send_tensor(a, request_id=request_id,
                                         timeout=timeout) for a in arrs]
            sp.set(error=str(e.code()))
            self._note_conn_result(e.code())
            _breaker_done(False)
            raise
        except Exception:  # noqa: BLE001 — stream-level errors (relay
            # error status, response corruption) settle the breaker too
            _breaker_done(False)
            raise
        finally:
            for req in pending.values():
                neg.sender.cleanup(req)
            pending.clear()
            sp.end()
        missing = [i for i in range(len(arrs)) if i not in statuses]
        _breaker_done(not missing)
        if missing:
            raise RuntimeError(
                f"relay stream ended without results for items {missing}")
        return [(statuses[i], results[i]) for i in range(len(arrs))]

    def generate(
        self,
        prompt_ids,
        *,
        max_new_tokens: int = 32,
        seed: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: Optional[float] = None,
        logit_bias: Optional[dict] = None,
        adapter: Optional[int] = None,
        dedup: Optional[str] = None,
        timeout: float = 120.0,
    ) -> np.ndarray:
        """Client path for the LM daemon (dnn_tpu/runtime/lm_server.py):
        prompt token ids -> generated tokens. Options ride the request_id
        as "gen:max_new[:seed][:t=..][:k=..][:p=..][:m=..][:r=..][:b=..][:a=..]" — the same wire
        message a reference-built client would send, just with an integer
        payload. Sampling overrides are per request (None = server
        defaults). A request is self-contained (prompt + options), so the
        transport-level retries in send_tensor stay safe here; `dedup`
        (an opaque key, rides as d=) makes that at-least-once
        EXACTLY-once — the daemon's admission joins a retried key to
        the original request instead of generating twice."""
        rid = _gen_rid(max_new_tokens, seed, temperature, top_k, top_p,
                       adapter, min_p, repetition_penalty, logit_bias,
                       dedup)
        status, result = self.send_tensor(
            np.asarray(prompt_ids, np.int32).reshape(-1),
            request_id=rid, timeout=timeout,
        )
        if result is None:
            raise RuntimeError(f"LM server returned no tokens: {status}")
        return np.asarray(result, np.int32)

    def embed(self, prompt_ids, *, pooling: str = "mean",
              timeout: float = 60.0) -> np.ndarray:
        """Embedding endpoint of the LM daemon: prompt token ids -> the
        pooled final hidden state (f32 (C,)). `pooling` is "mean" (masked
        average over real tokens) or "last" (final token's state). Same
        wire message as everything else — the request_id "embed[:pool]"
        selects the endpoint (runtime/lm_server.SendTensor)."""
        status, result = self.send_tensor(
            np.asarray(prompt_ids, np.int32).reshape(-1),
            request_id=f"embed:{pooling}", timeout=timeout,
        )
        if result is None:
            raise RuntimeError(f"LM server returned no embedding: {status}")
        return np.asarray(result, np.float32)

    # -- disaggregated prefill/decode (dnn_tpu/control) -----------------

    def prefill_kv(self, prompt_ids, *, timeout: float = 60.0) -> np.ndarray:
        """Prefill-export endpoint: ask a PREFILL replica to run the
        prompt's chunk loop and return the packed KV handoff payload
        (one uint8 tensor — dnn_tpu/control/handoff.py). Hand it to a
        decode replica with `put_kv` and generate with the matching
        h=<key> option; the router does all three per request on a
        role-split fleet."""
        status, result = self.send_tensor(
            np.asarray(prompt_ids, np.int32).reshape(-1),
            request_id="prefill", timeout=timeout,
        )
        if result is None:
            raise RuntimeError(f"LM server returned no KV payload: {status}")
        return np.asarray(result, np.uint8)

    def put_kv(self, key: str, payload, *, timeout: float = 60.0) -> str:
        """Stage a prefill replica's KV payload on THIS server under
        `key` (single-use; consumed by a generate carrying h=<key>).
        Returns the server's status line; a geometry mismatch raises
        as INVALID_ARGUMENT."""
        status, _ = self.send_tensor(
            np.asarray(payload, np.uint8).reshape(-1),
            request_id=f"kvput:{key}", timeout=timeout,
        )
        return status

    # -- fleet KV tier (dnn_tpu/kvtier): block-granular migration -------

    def kv_stage(self, prompt_ids, *, timeout: float = 60.0) -> str:
        """Ask a replica to prefill these tokens' full blocks straight
        into its radix prefix store (no decode slot held) — the
        prefill half of disaggregated BLOCK handoff. Returns the
        status line (stage stats as JSON suffix)."""
        status, _ = self.send_tensor(
            np.asarray(prompt_ids, np.int32).reshape(-1),
            request_id="kvstage", timeout=timeout)
        return status

    def kv_lease(self, prompt_ids, *, timeout: float = 30.0) -> dict:
        """Donor side of a block pull: lease the longest resident
        block run for these tokens. Returns the offer meta — {lease,
        bytes, blocks, n_tokens, shm?, nonce?} (kvtier/migrate.py)."""
        import json as _json

        status, result = self.send_tensor(
            np.asarray(prompt_ids, np.int32).reshape(-1),
            request_id="kvlease", timeout=timeout)
        if result is None:
            raise RuntimeError(f"kvlease returned no meta: {status}")
        return _json.loads(np.asarray(result, np.uint8).tobytes())

    def kv_fetch(self, lease_id: str, *, timeout: float = 30.0
                 ) -> np.ndarray:
        """grpc rung of a block pull: the staged payload bytes for a
        lease. NOT_FOUND (raised as RpcError) = expired; the caller
        re-prefills."""
        status, result = self.send_tensor(
            np.zeros((1,), np.int32),
            request_id=f"kvfetch:{lease_id}", timeout=timeout)
        if result is None:
            raise RuntimeError(f"kvfetch returned no payload: {status}")
        return np.asarray(result, np.uint8)

    def kv_ack(self, lease_id: str, *, timeout: float = 10.0) -> str:
        """Confirm ingest of a pulled lease so the donor releases its
        staging NOW instead of waiting out the TTL."""
        status, _ = self.send_tensor(
            np.zeros((1,), np.int32),
            request_id=f"kvack:{lease_id}", timeout=timeout)
        return status

    def kv_pull_from(self, donor_address: str, prompt_ids, *,
                     timeout: float = 60.0) -> str:
        """Instruct THIS replica to pull the prefix's blocks from
        `donor_address` and adopt them (the router's migration
        instruction). Advisory: a failed pull answers a
        kvtier_fallback status, never an error — the follow-up
        generate re-prefills."""
        import json as _json

        spec = _json.dumps({
            "donor": donor_address,
            "tokens": [int(x) for x in
                       np.asarray(prompt_ids, np.int32).reshape(-1)],
        }).encode()
        status, _ = self.send_tensor(
            np.frombuffer(spec, np.uint8),
            request_id="kvpull", timeout=timeout)
        return status

    def send_tensor_stream(self, arr, *, request_id: str,
                           timeout: float = 120.0):
        """RAW streaming passthrough: submit `arr` on GenerateStream
        with `request_id` VERBATIM and yield each TensorResponse as it
        arrives — the router's relay primitive (generate_stream
        re-encodes options; a front door must forward the original
        id, dl=/tr=/d= segments and all). Abandoning the iterator
        cancels the RPC, which frees the upstream decode slot."""
        call = self._channel.unary_stream(
            f"/{SERVICE_NAME}/GenerateStream",
            request_serializer=wc.serialize_request,
            response_deserializer=wc.parse_response,
        )
        stream = call(
            wc.TensorRequest(
                request_id=request_id,
                tensor=_tensor_msg(np.asarray(arr, np.int32).reshape(-1))),
            timeout=timeout,
        )
        try:
            yield from stream
        finally:
            stream.cancel()  # no-op on a finished stream

    def generate_stream(
        self,
        prompt_ids,
        *,
        max_new_tokens: int = 32,
        seed: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: Optional[float] = None,
        logit_bias: Optional[dict] = None,
        adapter: Optional[int] = None,
        dedup: Optional[str] = None,
        timeout: float = 120.0,
    ):
        """Streaming client for the LM daemon's GenerateStream RPC: yields
        each token (int) as the server commits it. Abandoning the iterator
        (break / close / GC) cancels the RPC, which frees the server-side
        decode slot at its next step boundary — a disconnected client never
        decodes on to its budget. NOT retried: a stream is stateful (tokens
        already delivered), unlike the self-contained unary generate() —
        for the same reason a `dedup` key cannot JOIN a stream; the
        server accepts and ignores it."""
        rid = _gen_rid(max_new_tokens, seed, temperature, top_k, top_p,
                       adapter, min_p, repetition_penalty, logit_bias,
                       dedup)
        call = self._channel.unary_stream(
            f"/{SERVICE_NAME}/GenerateStream",
            request_serializer=wc.serialize_request,
            response_deserializer=wc.parse_response,
        )
        sp = obs.start_span("rpc.GenerateStream",
                            parent=obs.current_span(),
                            target=self.address)
        stream = call(
            wc.TensorRequest(
                request_id=obs.tag_request_id(rid, sp),
                tensor=_tensor_msg(
                    np.asarray(prompt_ids, np.int32).reshape(-1))),
            timeout=timeout,
        )
        n = 0
        try:
            for resp in stream:
                if resp.HasField("result_tensor"):
                    n += 1
                    yield int(_tensor_arr(resp.result_tensor)[0])
        finally:
            stream.cancel()  # no-op on a finished stream
            sp.end(tokens=n)

    def generate_text(
        self,
        prompt: str,
        *,
        max_new_tokens: int = 32,
        seed: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: Optional[float] = None,
        logit_bias: Optional[dict] = None,
        adapter: Optional[int] = None,
        timeout: float = 120.0,
    ) -> str:
        """Text client for a tokenizer-equipped LM daemon: the prompt rides
        SendMessage's message_text, generation options ride sender_id as
        "gen:max_new[:seed][:t=..][:k=..][:p=..][:m=..][:r=..][:b=..][:a=..]", and the reply is the
        generated continuation (lm_server.LMServer.SendMessage)."""
        rid = _gen_rid(max_new_tokens, seed, temperature, top_k, top_p,
                       adapter, min_p, repetition_penalty, logit_bias)
        return self.send_message(rid, prompt, timeout=timeout)

    def generate_text_stream(
        self,
        prompt: str,
        tokenizer,
        *,
        max_new_tokens: int = 32,
        seed: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: Optional[float] = None,
        logit_bias: Optional[dict] = None,
        adapter: Optional[int] = None,
        timeout: float = 120.0,
    ):
        """Streaming TEXT client: encode the prompt with `tokenizer`
        (which must match the daemon's — the ids ride GenerateStream),
        yield UTF-8-safe text chunks as tokens commit. A multi-byte
        character split across BPE pieces is held until complete
        (io/tokenizer.stream_detokenizer), so the concatenation of the
        yielded chunks equals the one-shot decode of the full stream
        byte-for-byte for prefix-monotone tokenizers (ByteTokenizer and
        this package's HF adapter; see StreamingDetokenizer's docstring
        for the cleanup-rewriting caveat) — the text form of the serving
        edge the reference's unary SendTensor could never express
        (node_service.proto:7). Abandoning the iterator cancels the RPC
        (frees the server's decode slot), same as generate_stream."""
        from dnn_tpu.io.tokenizer import stream_detokenizer

        det = stream_detokenizer(tokenizer)
        for tok in self.generate_stream(
                tokenizer.encode(prompt), max_new_tokens=max_new_tokens,
                seed=seed, temperature=temperature, top_k=top_k,
                top_p=top_p, min_p=min_p,
                repetition_penalty=repetition_penalty,
                logit_bias=logit_bias, adapter=adapter, timeout=timeout):
            chunk = det.push(tok)
            if chunk:
                yield chunk
        tail = det.flush()
        if tail:
            yield tail

    def close(self):
        neg, self._negotiated = self._negotiated, None
        if neg is not None:
            neg.sender.close()
        self._channel.close()
