"""gRPC edge client — the initiator role.

Rebuilds the reference's node-0 client path (initiate_inference,
node.py:137-200): run the local stage, send the activation downstream, wait
for the result to ride back up the response chain, return the final tensor.
Adds what the reference lacked: a real HealthCheck probe before submitting
(its HealthCheck had no caller — SURVEY §3.4) and channel reuse.
"""

from __future__ import annotations

import logging
from typing import Optional

import grpc
import numpy as np

from dnn_tpu.comm import wire_pb2 as pb
from dnn_tpu.comm.service import SERVICE_NAME, _tensor_arr, _tensor_msg

log = logging.getLogger("dnn_tpu.comm")


class NodeClient:
    """Sync client for a NodeService endpoint (ours or a reference node's —
    the wire protocol is identical)."""

    def __init__(self, address: str):
        self.address = address
        self._channel = grpc.insecure_channel(address)

    def health_check(self, timeout: float = 5.0) -> bool:
        call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/HealthCheck",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.HealthCheckResponse.FromString,
        )
        try:
            return bool(call(pb.Empty(), timeout=timeout).is_healthy)
        except grpc.RpcError:
            return False

    def send_message(self, sender_id: str, text: str, timeout: float = 5.0) -> str:
        call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/SendMessage",
            request_serializer=pb.MessageRequest.SerializeToString,
            response_deserializer=pb.MessageReply.FromString,
        )
        return call(
            pb.MessageRequest(sender_id=sender_id, message_text=text), timeout=timeout
        ).confirmation_text

    def send_tensor(
        self, arr: np.ndarray, *, request_id: str = "req", timeout: float = 60.0
    ) -> tuple[str, Optional[np.ndarray]]:
        """Submit an activation; returns (status, final_tensor_or_None) —
        the response-chain semantics of node.py:180-194."""
        call = self._channel.unary_unary(
            f"/{SERVICE_NAME}/SendTensor",
            request_serializer=pb.TensorRequest.SerializeToString,
            response_deserializer=pb.TensorResponse.FromString,
        )
        resp = call(
            pb.TensorRequest(request_id=request_id, tensor=_tensor_msg(arr)),
            timeout=timeout,
        )
        result = _tensor_arr(resp.result_tensor) if resp.HasField("result_tensor") else None
        return resp.status, result

    def close(self):
        self._channel.close()
