"""Zero-copy proto3 wire codec for the comm hot path.

The reference transport round-trips every activation through THREE host
copies per direction: `arr.tobytes()` (copy 1), protobuf's internal
bytes-field store + `SerializeToString` (copy 2), and
`np.frombuffer(...).copy()` on the receiver (copy 3) — measured as the
dominant term of the 75.9% warm bubble fraction at cifar scale
(STUDIES.md §10). Python protobuf cannot take a memoryview for a bytes
field, so the fix is one layer down: this module hand-assembles and
hand-parses the proto3 *wire format* of the three Tensor-carrying
messages (`Tensor`, `TensorRequest`, `TensorResponse` —
dnn_tpu/comm/wire.proto), which the repo can do because every gRPC
method is registered with EXPLICIT serializer callables
(comm/service._handlers, comm/client) rather than generated stubs.

Wire compatibility is byte-level: the serializer emits valid proto3
(length-delimited fields, packed repeated int32 shape — exactly what
protobuf itself emits for these messages), and the parser is a tolerant
field scanner that skips unknown fields and accepts both packed and
non-packed shape encodings, so reference peers running real protobuf
interoperate unchanged (pinned by tests/test_transport.py golden
round-trips against wire_pb2).

Copy accounting: the ONLY payload copy on the send side is the final
`b"".join` into the gRPC message buffer (unavoidable — the transport
owns its buffer), and the receive side is a `np.frombuffer` VIEW over
the gRPC message bytes (zero copies; the array keeps the buffer alive
via .base). Payload bytes that had to be materialized anyway —
non-contiguous arrays, foreign endianness — are counted into
`comm.payload_bytes_copied_total`, so a zero counter next to a nonzero
`comm.payload_bytes_total` is the proof the hot path stayed zero-copy.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

BytesLike = Union[bytes, memoryview]

# wire types
_VARINT = 0
_I64 = 1
_LEN = 2
_I32 = 5


def _encode_varint(n: int) -> bytes:
    if n < 0:
        # int32/int64 negative values ride as 64-bit two's complement
        n &= 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _decode_varint(buf, pos: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint in wire payload")
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint overflows 64 bits")


def _scan(buf: memoryview):
    """Yield (field_no, wire_type, value) over one message's wire bytes.
    LEN fields yield a zero-copy memoryview slice; varint/fixed yield
    ints. Unknown wire types fail loud (corrupt frame, not a field to
    skip)."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _decode_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == _VARINT:
            val, pos = _decode_varint(buf, pos)
        elif wt == _LEN:
            ln, pos = _decode_varint(buf, pos)
            if pos + ln > n:
                raise ValueError("truncated length-delimited field")
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == _I64:
            val = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wt == _I32:
            val = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt} (field {field})")
        yield field, wt, val


def _int32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


# ----------------------------------------------------------------------
# message shims (duck-typed stand-ins for the wire_pb2 classes on the
# paths the servers/clients actually touch)
# ----------------------------------------------------------------------

class Tensor:
    """wire.proto `Tensor`. `tensor_data` may be a memoryview (zero-copy
    slice of the inbound gRPC buffer, or the outbound array's own
    buffer); consumers treat it as read-only bytes."""

    __slots__ = ("tensor_data", "shape", "dtype", "crc32c")

    def __init__(self, tensor_data: BytesLike = b"",
                 shape: Sequence[int] = (), dtype: str = "",
                 crc32c: Optional[int] = None):
        self.tensor_data = tensor_data
        self.shape = list(shape)
        self.dtype = dtype
        self.crc32c = crc32c

    def HasField(self, name: str) -> bool:  # noqa: N802 — pb API
        if name != "crc32c":
            raise ValueError(f"Tensor has no presence field {name!r}")
        return self.crc32c is not None

    def _parts(self) -> List[BytesLike]:
        parts: List[BytesLike] = []
        ln = len(self.tensor_data)
        if ln:  # proto3 canonical form omits empty scalar fields
            parts.append(b"\x0a" + _encode_varint(ln))
            parts.append(self.tensor_data)
        if self.shape:
            packed = b"".join(_encode_varint(int(s)) for s in self.shape)
            parts.append(b"\x12" + _encode_varint(len(packed)) + packed)
        if self.dtype:
            d = self.dtype.encode()
            parts.append(b"\x1a" + _encode_varint(len(d)) + d)
        if self.crc32c is not None:
            parts.append(b"\x20" + _encode_varint(self.crc32c & 0xFFFFFFFF))
        return parts

    def ByteSize(self) -> int:  # noqa: N802 — pb API
        return sum(len(p) for p in self._parts())


def _parse_tensor(buf: memoryview) -> Tensor:
    t = Tensor()
    for field, wt, val in _scan(buf):
        if field == 1 and wt == _LEN:
            t.tensor_data = val
        elif field == 2:
            if wt == _LEN:  # packed repeated int32 (protobuf's default)
                pos = 0
                while pos < len(val):
                    v, pos = _decode_varint(val, pos)
                    t.shape.append(_int32(v))
            elif wt == _VARINT:  # non-packed encoder
                t.shape.append(_int32(val))
        elif field == 3 and wt == _LEN:
            t.dtype = bytes(val).decode()
        elif field == 4 and wt == _VARINT:
            t.crc32c = val & 0xFFFFFFFF
    return t


class TensorRequest:
    __slots__ = ("request_id", "tensor", "_wire_len")

    def __init__(self, request_id: str = "", tensor: Optional[Tensor] = None):
        self.request_id = request_id
        self.tensor = tensor if tensor is not None else Tensor()
        self._wire_len: Optional[int] = None

    def _parts(self) -> List[BytesLike]:
        parts: List[BytesLike] = []
        if self.request_id:
            r = self.request_id.encode()
            parts.append(b"\x0a" + _encode_varint(len(r)) + r)
        sub = self.tensor._parts()
        parts.append(b"\x12" + _encode_varint(sum(len(p) for p in sub)))
        parts.extend(sub)
        return parts

    def ByteSize(self) -> int:  # noqa: N802 — pb API
        if self._wire_len is not None:
            return self._wire_len
        return sum(len(p) for p in self._parts())


class TensorResponse:
    __slots__ = ("status", "result_tensor", "_wire_len")

    def __init__(self, status: str = "",
                 result_tensor: Optional[Tensor] = None):
        self.status = status
        self.result_tensor = result_tensor
        self._wire_len: Optional[int] = None

    def HasField(self, name: str) -> bool:  # noqa: N802 — pb API
        if name != "result_tensor":
            raise ValueError(f"TensorResponse has no presence field {name!r}")
        return self.result_tensor is not None

    def _parts(self) -> List[BytesLike]:
        parts: List[BytesLike] = []
        if self.status:
            s = self.status.encode()
            parts.append(b"\x0a" + _encode_varint(len(s)) + s)
        if self.result_tensor is not None:
            sub = self.result_tensor._parts()
            parts.append(b"\x12" + _encode_varint(sum(len(p) for p in sub)))
            parts.extend(sub)
        return parts

    def ByteSize(self) -> int:  # noqa: N802 — pb API
        if self._wire_len is not None:
            return self._wire_len
        return sum(len(p) for p in self._parts())


# ----------------------------------------------------------------------
# gRPC (de)serializer callables
# ----------------------------------------------------------------------

def serialize_request(msg) -> bytes:
    """TensorRequest -> wire bytes. Accepts the shim (single-join
    zero-intermediate path) or a real wire_pb2 message (interop /
    legacy call sites)."""
    if isinstance(msg, TensorRequest):
        return b"".join(msg._parts())
    return msg.SerializeToString()


def serialize_response(msg) -> bytes:
    if isinstance(msg, TensorResponse):
        return b"".join(msg._parts())
    return msg.SerializeToString()


def parse_request(data: bytes) -> TensorRequest:
    req = TensorRequest()
    buf = memoryview(data)
    for field, wt, val in _scan(buf):
        if field == 1 and wt == _LEN:
            req.request_id = bytes(val).decode()
        elif field == 2 and wt == _LEN:
            req.tensor = _parse_tensor(val)
    req._wire_len = len(data)
    return req


def parse_response(data: bytes) -> TensorResponse:
    resp = TensorResponse()
    buf = memoryview(data)
    for field, wt, val in _scan(buf):
        if field == 1 and wt == _LEN:
            resp.status = bytes(val).decode()
        elif field == 2 and wt == _LEN:
            resp.result_tensor = _parse_tensor(val)
    resp._wire_len = len(data)
    return resp


# ----------------------------------------------------------------------
# zero-copy tensor payload helpers
# ----------------------------------------------------------------------

def tensor_payload(arr) -> Tuple[BytesLike, Tuple[int, ...], str, int]:
    """array -> (payload_view, shape, dtype_name, bytes_copied).

    Contiguous little-endian arrays (the hot path: every jit output)
    yield their OWN buffer as a memoryview — zero copies here; the one
    remaining copy is the final join into the gRPC message buffer.
    Non-contiguous or big-endian inputs must materialize (counted)."""
    a = np.asarray(arr)
    copied = 0
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
        copied = a.nbytes
    shape = tuple(a.shape)  # before ascontiguousarray (0-d promotion)
    if not a.flags.c_contiguous:
        a = np.ascontiguousarray(a)
        copied = a.nbytes
    # memoryview over the array's buffer, flattened to 1-D bytes: the
    # uint8 reinterpret-view (no data movement) also covers dtypes the
    # buffer protocol rejects (ml_dtypes bfloat16). The view keeps the
    # array alive; 0-d reshapes to 1-d first.
    view = memoryview(a.reshape(-1).view(np.uint8))
    return view, shape, a.dtype.name, copied


def tensor_view(msg, *, check_crc: bool = True) -> np.ndarray:
    """Tensor message -> zero-copy (read-only) ndarray view over the
    message's payload bytes. Length-validated; crc32c verified when
    declared and the native codec is built (same contract as the old
    copying decoder)."""
    from dnn_tpu.io.serialization import PayloadCorruptError, _np_dtype

    if check_crc and msg.HasField("crc32c"):
        from dnn_tpu.native import crc32c, native_available

        if native_available():
            got = crc32c(msg.tensor_data)
            if got != msg.crc32c:
                raise PayloadCorruptError(
                    f"tensor payload corrupt: crc32c {got:#010x} != "
                    f"declared {msg.crc32c:#010x}")
    dt = _np_dtype(msg.dtype)
    shape = tuple(int(s) for s in msg.shape)
    expect = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
    if len(msg.tensor_data) != expect:
        raise ValueError(
            f"tensor payload is {len(msg.tensor_data)} bytes but shape "
            f"{shape} dtype {msg.dtype} needs {expect}")
    return np.frombuffer(msg.tensor_data, dtype=dt).reshape(shape)


def make_tensor(arr, *, crc: bool = True) -> Tensor:
    """array -> Tensor shim with a zero-copy payload view (and the
    payload-copy counter fed when the input forced a materialization).
    Checksummed under the same policy as the legacy encoder: only when
    the native codec is built (Python crc is a per-byte loop)."""
    from dnn_tpu import obs
    from dnn_tpu.utils.metrics import labeled

    view, shape, dtype, copied = tensor_payload(arr)
    if copied:
        m = obs.metrics()
        if m is not None:
            m.inc(labeled("comm.payload_bytes_copied_total",
                          reason="noncontiguous"), copied)
    checksum = None
    if crc:
        from dnn_tpu.native import crc32c, native_available

        if native_available():
            checksum = crc32c(view)
    return Tensor(tensor_data=view, shape=shape, dtype=dtype,
                  crc32c=checksum)
