"""gRPC edge service: the cross-host / interop transport.

Re-implements the reference's NodeService server (node.py:34-133) on top of
the same wire protocol (dnn_tpu/comm/wire.proto), with the differences the
rebuild mandates (SURVEY §5):

  * the stage computation is a jit-compiled JAX program on a TPU device,
    not a torch module on CPU (node.py:52-54);
  * one channel per downstream neighbor, opened once and reused — the
    reference opens a fresh insecure channel per request per hop
    (node.py:73);
  * HealthCheck is actually used (clients probe it; the reference's version
    had no caller — SURVEY §3.4);
  * errors still relay upward as status strings in the response chain, for
    behavioral parity (node.py:91-100).

This path exists for multi-host deployments without ICI and for interop
with reference nodes; the intra-pod fast path is the SPMD mesh runtime
(dnn_tpu/parallel/pipeline.py) with zero gRPC hops.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

import grpc
import numpy as np

from dnn_tpu import obs
from dnn_tpu.comm import wire_pb2 as pb
from dnn_tpu.io.serialization import (
    PayloadCorruptError,
    decode_tensor,
    encode_tensor,
)
from dnn_tpu.utils.metrics import labeled

log = logging.getLogger("dnn_tpu.comm")

SERVICE_NAME = "node_service.NodeService"

# Transient codes worth retrying, shared by the edge client and the server's
# downstream relay; anything else (INVALID_ARGUMENT, UNIMPLEMENTED, ...) is a
# real error and surfaces immediately.
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
    # a receiver detected payload corruption (crc32c mismatch) — the
    # pipeline is stateless per request, so resending is safe and likely
    # to succeed
    grpc.StatusCode.DATA_LOSS,
})
# DEADLINE_EXCEEDED is deliberately NOT retryable: a deadline here always
# spans the entire remaining pipeline (see _forward / pipeline_budget), so
# when it expires, resending toward the same hung stage can only duplicate
# every downstream stage's work — the timeout surfaces upward instead.

# Per-stage slice of the pipeline deadline budget: generous for one stage's
# jit-compiled forward + one LAN hop (first-call XLA compiles included). A
# hop covering k downstream stages gets k * this as its OVERALL budget; the
# edge client covering the whole pipeline gets num_parts * this + margin
# (see dnn_tpu.comm.client.pipeline_budget).
PER_STAGE_BUDGET_S = 30.0


def _tensor_msg(arr) -> pb.Tensor:
    data, shape, dtype = encode_tensor(arr)
    from dnn_tpu.native import crc32c, native_available

    msg = pb.Tensor(tensor_data=data, shape=list(shape), dtype=dtype)
    # Checksum only when the native codec is built: the Python fallback is a
    # per-byte loop that would add seconds per MB on the transport hot path.
    # Field absent == "not checksummed", same as a reference peer.
    if native_available():
        msg.crc32c = crc32c(data)
    return msg


def _tensor_arr(msg: pb.Tensor) -> np.ndarray:
    from dnn_tpu.native import crc32c, native_available

    if msg.HasField("crc32c") and native_available():
        got = crc32c(msg.tensor_data)
        if got != msg.crc32c:
            raise PayloadCorruptError(
                f"tensor payload corrupt: crc32c {got:#010x} != "
                f"declared {msg.crc32c:#010x}"
            )
    return decode_tensor(msg.tensor_data, list(msg.shape), msg.dtype)


class StageServer:
    """Serves one pipeline stage (the reference's per-node role,
    node.py:34-113). `engine` supplies the staged model; `node_id` selects
    which part this process owns via the shared topology config."""

    def __init__(self, engine, node_id: str):
        # Warm the native codec NOW (a synchronous g++ compile on first
        # build) so it never runs inside an async RPC handler, where it
        # would freeze the event loop for the duration of the compile.
        from dnn_tpu.native import native_available

        native_available()
        self.engine = engine
        self.config = engine.config
        self.node = self.config.node_by_id(node_id)
        self.part_index = self.node.part_index
        self.is_last = self.part_index == self.config.num_parts - 1
        nxt = self.config.next_node(self.node)
        self.next_address = nxt.address if nxt else None
        self._next_channel: Optional[grpc.aio.Channel] = None

    # --- RPC implementations (names/signatures fixed by the protocol) ---

    async def SendTensor(self, request: pb.TensorRequest, context) -> pb.TensorResponse:
        nid = self.node.id
        result_msg = None
        t_handler = time.perf_counter()
        m = obs.metrics()
        if m is not None:
            m.inc(labeled("comm.payload_bytes_total", direction="in",
                          stage=nid), request.ByteSize())
        # continue the sender's trace (or start fresh); the tree crosses
        # every relay hop because _forward re-tags the request_id it
        # forwards with its own span
        root = obs.continue_or_start("stage.request", request.request_id,
                                     stage=nid, part=self.part_index)
        try:
            try:
                x = _tensor_arr(request.tensor)
            except PayloadCorruptError as e:
                # Fail the RPC itself (not a status string) so the sender's
                # retry loop sees DATA_LOSS and resends — transient wire
                # corruption must not become a terminal pipeline error.
                log.warning("corrupt payload on %s: %s", nid, e)
                root.end(error="payload_corrupt")
                await context.abort(grpc.StatusCode.DATA_LOSS, str(e))
            with root.child("stage.compute", part=self.part_index):
                # np.asarray forces device completion — the span measures
                # the stage's real compute, not its dispatch
                y = np.asarray(self.engine.run_stage(self.part_index, x))
            if self.is_last:
                pred = int(np.argmax(y))
                log.info("final stage done (node %s), prediction=%d", nid, pred)
                status = f"[{nid}] Processing complete. Prediction: {pred}"
                result_msg = _tensor_msg(y)
            else:
                resp = await self._forward(request.request_id, y,
                                           parent=root)
                status = f"[{nid}] Forwarded. Next node status: {resp.status}"
                if resp.HasField("result_tensor"):
                    result_msg = resp.result_tensor
        except grpc.aio.AbortError:
            raise  # the DATA_LOSS abort above must fail the RPC, not relay
        except grpc.aio.AioRpcError as e:
            log.error("forward from %s to %s failed: %s", nid, self.next_address, e.details())
            status = f"[{nid}] Error forwarding: {e.details()}"
        except Exception as e:  # noqa: BLE001 — status-string relay, like node.py:96-100
            log.exception("error processing tensor on %s", nid)
            status = f"[{nid}] Error: {e}"
        finally:
            root.end()
        if m is not None:
            m.observe_hist(
                labeled("comm.rpc_latency_seconds", method="SendTensor",
                        role="server", stage=nid),
                time.perf_counter() - t_handler)
        resp_msg = pb.TensorResponse(status=status, result_tensor=result_msg)
        if m is not None:
            m.inc(labeled("comm.payload_bytes_total", direction="out",
                          stage=nid), resp_msg.ByteSize())
        return resp_msg

    async def HealthCheck(self, request: pb.Empty, context) -> pb.HealthCheckResponse:
        return pb.HealthCheckResponse(is_healthy=True)

    async def SendMessage(self, request: pb.MessageRequest, context) -> pb.MessageReply:
        log.info("message for %s from %s", self.node.id, request.sender_id)
        return pb.MessageReply(
            confirmation_text=f"[{self.node.id}] got msg '{request.message_text}'"
        )

    # --- plumbing ---

    async def _forward(
        self, request_id: str, y: np.ndarray, *, retries: int = 2,
        backoff: float = 0.2, timeout: Optional[float] = None,
        parent=None,
    ) -> pb.TensorResponse:
        """Relay downstream with bounded retries on transient failures,
        reusing the shared channel across attempts (gRPC reconnects a broken
        channel on the next call) — the per-hop resilience the reference
        lacks (SURVEY §5: failures only become status strings, "No retry").

        Deadline discipline: the relayed call spans the ENTIRE remaining
        pipeline (response-chain semantics, SURVEY §3.3), so this hop gets
        an OVERALL budget that scales with remaining depth —
        `PER_STAGE_BUDGET_S * downstream_stages` — shared across all
        attempts and backoff sleeps (each attempt's gRPC deadline is the
        budget REMAINING, mirroring NodeClient.send_tensor). Deeper stages
        therefore hold strictly smaller budgets than the hops above them,
        even when retryable failures arrive late (e.g. a crc32c DATA_LOSS
        after most of the downstream compute), so a downstream error
        status always has time to ride back up before any upstream
        deadline fires. DEADLINE_EXCEEDED itself is not retryable (see
        RETRYABLE_CODES): the expired budget already covered the whole
        remaining pipeline.

        The relayed request_id is RE-TAGGED with this hop's span
        (obs.tag_request_id), so the downstream stage's spans nest under
        this hop's `rpc.forward` — one tree per request across the whole
        chain; retries count into comm.retries_total{stage=...} with the
        trace id in the log line, so a backoff storm is visible and
        attributable instead of silent."""
        sp = obs.start_span("rpc.forward", parent=parent,
                            target=self.next_address)
        request = pb.TensorRequest(
            request_id=obs.tag_request_id(request_id, sp)
            if sp else request_id,
            tensor=_tensor_msg(y))
        if self._next_channel is None:
            self._next_channel = grpc.aio.insecure_channel(self.next_address)
        call = self._next_channel.unary_unary(
            f"/{SERVICE_NAME}/SendTensor",
            request_serializer=pb.TensorRequest.SerializeToString,
            response_deserializer=pb.TensorResponse.FromString,
        )
        if timeout is None:
            timeout = PER_STAGE_BUDGET_S * max(
                self.config.num_parts - self.part_index - 1, 1
            )
        deadline = time.monotonic() + timeout
        attempt = 0
        m = obs.metrics()
        try:
            while True:
                remaining = deadline - time.monotonic()
                t_try = time.perf_counter()
                if m is not None:
                    # per ATTEMPT, like the edge client: relayed bytes
                    # must reconcile with the downstream stage's
                    # direction="in" count even through retries
                    m.inc(labeled("comm.payload_bytes_total",
                                  direction="out", stage=self.node.id),
                          request.ByteSize())
                try:
                    t_send_wall = time.time() if sp else 0.0
                    resp = await call(request, timeout=max(remaining, 0.001))
                    if sp:
                        # clock-offset sampling fields for cross-host
                        # stitching, as in client.send_tensor: the
                        # successful attempt's wall-clock window only
                        sp.set(cs=t_send_wall, cr=time.time())
                    if m is not None:
                        m.observe_hist(
                            labeled("comm.rpc_latency_seconds",
                                    method="forward", role="client",
                                    stage=self.node.id),
                            time.perf_counter() - t_try)
                    sp.set(attempts=attempt + 1)
                    return resp
                except grpc.aio.AioRpcError as e:
                    # NOTE: the shared channel is deliberately NOT closed
                    # between attempts — other requests may have calls in
                    # flight on it, and gRPC reconnects a broken channel on
                    # the next call anyway.
                    if m is not None and \
                            e.code() == grpc.StatusCode.DEADLINE_EXCEEDED:
                        m.inc(labeled("comm.deadline_exceeded_total",
                                      stage=self.node.id))
                    delay = backoff * (2 ** attempt)
                    out_of_budget = deadline - time.monotonic() <= delay
                    if e.code() not in RETRYABLE_CODES or attempt >= retries \
                            or out_of_budget:
                        sp.set(error=str(e.code()), attempts=attempt + 1)
                        raise
                    if m is not None:
                        m.inc(labeled("comm.retries_total",
                                      stage=self.node.id))
                    log.warning(
                        "forward %s -> %s failed (%s), retry %d/%d in "
                        "%.2fs [trace=%s]",
                        self.node.id, self.next_address, e.code(),
                        attempt + 1, retries, delay, sp.trace_id or "-",
                    )
                    await asyncio.sleep(delay)
                    attempt += 1
        finally:
            sp.end()

    async def close(self):
        if self._next_channel is not None:
            await self._next_channel.close()
            self._next_channel = None


def _resolve_port(servicer: StageServer, node_id: str, port: Optional[int]) -> int:
    bind_port = port if port is not None else servicer.node.port
    if bind_port is None:
        raise ValueError(
            f"node '{node_id}' has no address in the config; serving a stage "
            "requires nodes[].address with an IP:Port (config.json:6)"
        )
    return bind_port


def _handlers(servicer: StageServer):
    handlers = {
        "SendTensor": grpc.unary_unary_rpc_method_handler(
            servicer.SendTensor,
            request_deserializer=pb.TensorRequest.FromString,
            response_serializer=pb.TensorResponse.SerializeToString,
        ),
        "HealthCheck": grpc.unary_unary_rpc_method_handler(
            servicer.HealthCheck,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.HealthCheckResponse.SerializeToString,
        ),
        "SendMessage": grpc.unary_unary_rpc_method_handler(
            servicer.SendMessage,
            request_deserializer=pb.MessageRequest.FromString,
            response_serializer=pb.MessageReply.SerializeToString,
        ),
    }
    # the LM daemon's per-token streaming front (wire.proto GenerateStream);
    # stage servers don't implement it and callers get UNIMPLEMENTED
    if hasattr(servicer, "GenerateStream"):
        handlers["GenerateStream"] = grpc.unary_stream_rpc_method_handler(
            servicer.GenerateStream,
            request_deserializer=pb.TensorRequest.FromString,
            response_serializer=pb.TensorResponse.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


async def serve_stage(engine, node_id: str, *, port: Optional[int] = None,
                      metrics_port: Optional[int] = None):
    """Start the gRPC server for this node's stage and block until
    termination (the rebuild of serve(), node.py:114-133).
    `metrics_port` (None = off, 0 = ephemeral) additionally serves the
    observability endpoint — GET /metrics (Prometheus text format:
    per-stage RPC latency, payload bytes, retry/deadline counters, XLA
    compile telemetry, device/host memory gauges), /trace (Chrome-trace
    JSON), /debugz (flight ring), POST /profilez (on-demand device
    profile; no auto-trigger — that needs the LM daemon's step loop) —
    over stdlib HTTP."""
    obs.install_compile_telemetry()
    servicer = StageServer(engine, node_id)
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((_handlers(servicer),))
    bind_port = _resolve_port(servicer, node_id, port)
    listen = f"[::]:{bind_port}"
    if server.add_insecure_port(listen) == 0:
        # grpc reports bind failure as port 0, not an exception (the
        # reference prints-and-exits on the same failure, node.py:124-126)
        raise RuntimeError(f"failed to bind gRPC server to {listen}")
    metrics_srv = None
    if metrics_port is not None:
        metrics_srv = obs.serve_metrics(metrics_port)
    log.info("gRPC stage server %s listening on %s (part %d)",
             node_id, listen, servicer.part_index)
    await server.start()
    try:
        await server.wait_for_termination()
    finally:
        await servicer.close()
        await server.stop(grace=1)
        if metrics_srv is not None:
            metrics_srv.close()


def start_stage_server_in_background(engine, node_id: str, *, port: Optional[int] = None):
    """Test/embedding helper: run serve_stage on a daemon thread; returns
    (thread, stop_callback)."""
    import threading

    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    async def _run():
        # grpc.aio binds to the event loop current at construction time, so
        # the server (and the servicer's forwarding channel) must be created
        # inside this thread's loop, not the caller's.
        try:
            servicer = StageServer(engine, node_id)
            server = grpc.aio.server()
            server.add_generic_rpc_handlers((_handlers(servicer),))
            bind_port = _resolve_port(servicer, node_id, port)
            if server.add_insecure_port(f"[::]:{bind_port}") == 0:
                raise RuntimeError(f"failed to bind gRPC server to [::]:{bind_port}")
            await server.start()
            state["servicer"], state["server"] = servicer, server
            state["done"] = asyncio.Event()
        except BaseException as e:  # surface startup failure to the caller
            state["error"] = e
            raise
        finally:
            started.set()
        await state["done"].wait()
        # drain one cycle so the stop() future resolves before the loop ends
        await asyncio.sleep(0.05)

    def _thread_main():
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(_run())
        except BaseException:
            if "error" not in state:
                raise  # startup succeeded; die loudly on later failures
            # startup error already recorded and re-raised to the caller

    t = threading.Thread(target=_thread_main, daemon=True)
    t.start()
    if not started.wait(timeout=15):
        raise RuntimeError(f"stage server for {node_id} failed to start")
    if "error" in state:
        t.join(timeout=5)
        raise RuntimeError(
            f"stage server for {node_id} failed to start: {state['error']}"
        ) from state["error"]

    def stop():
        async def _stop():
            await state["servicer"].close()
            await state["server"].stop(grace=0.2)
            state["done"].set()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=10)
        t.join(timeout=5)

    return t, stop
