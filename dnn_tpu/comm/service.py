"""gRPC edge service: the cross-host / interop transport.

Re-implements the reference's NodeService server (node.py:34-133) on top of
the same wire protocol (dnn_tpu/comm/wire.proto), with the differences the
rebuild mandates (SURVEY §5):

  * the stage computation is a jit-compiled JAX program on a TPU device,
    not a torch module on CPU (node.py:52-54);
  * one channel per downstream neighbor, opened once and reused — the
    reference opens a fresh insecure channel per request per hop
    (node.py:73);
  * HealthCheck is actually used (clients probe it; the reference's version
    had no caller — SURVEY §3.4);
  * errors still relay upward as status strings in the response chain, for
    behavioral parity (node.py:91-100).

PR 7 makes the hop itself pluggable (comm/transport.py): each downstream
edge NEGOTIATES `device | shm | grpc` at first forward (a wire-compatible
SendMessage handshake — reference peers land on grpc), payloads ride
zero-copy at both ends (comm/wirecodec.py), and the streamed `Relay` RPC
replaces the nested hold-every-hop-open unary chain with
forward-and-ack-upstream semantics so microbatches overlap across
processes. Every hop's RPC histogram and span carries a `transport`
label, so the fleet collector reads the transport's effect directly.

This path exists for multi-host deployments without ICI and for interop
with reference nodes; the intra-pod fast path is the SPMD mesh runtime
(dnn_tpu/parallel/pipeline.py) with zero gRPC hops.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional

import grpc
import numpy as np

from dnn_tpu import obs
from dnn_tpu.chaos import inject as _chaos_inject
from dnn_tpu.comm import transport as _tx
from dnn_tpu.comm import wire_pb2 as pb
from dnn_tpu.comm import wirecodec as wc
from dnn_tpu.comm.transport import PER_STAGE_BUDGET_S  # noqa: F401 — the
# budget constant lives with the other transport budgets now; re-exported
# here because the edge client (and external callers) import it from the
# service module since PR 0.
from dnn_tpu.io.serialization import PayloadCorruptError
from dnn_tpu.utils.metrics import labeled

log = logging.getLogger("dnn_tpu.comm")

SERVICE_NAME = "node_service.NodeService"

# Transient codes worth retrying, shared by the edge client and the server's
# downstream relay; anything else (INVALID_ARGUMENT, UNIMPLEMENTED, ...) is a
# real error and surfaces immediately.
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
    # a receiver detected payload corruption (crc32c mismatch) — the
    # pipeline is stateless per request, so resending is safe and likely
    # to succeed
    grpc.StatusCode.DATA_LOSS,
})
# DEADLINE_EXCEEDED is deliberately NOT retryable: a deadline here always
# spans the entire remaining pipeline (see _forward / pipeline_budget), so
# when it expires, resending toward the same hung stage can only duplicate
# every downstream stage's work — the timeout surfaces upward instead.


def full_jitter_delay(backoff: float, attempt: int) -> float:
    """FULL-JITTER exponential backoff: uniform in (0, backoff *
    2^attempt], shared by the edge client and the stage forward ladder.
    Deterministic backoff meant every caller that failed together
    retried together — a retry storm re-spiking the very stage that was
    recovering; jitter decorrelates the herd. The small floor keeps the
    delay > 0 so budget checks still terminate ladders. (Uses `random`,
    never in traced code — chaos-plan determinism lives in
    chaos/plan.decide, not here.)"""
    import random

    return max(backoff * (2 ** attempt) * random.random(),
               backoff * 0.05)


def _tensor_msg(arr) -> wc.Tensor:
    """array -> wire Tensor, zero-copy (the payload rides as a memoryview
    of the array's own buffer until the single join into the gRPC message
    — comm/wirecodec.py). Checksummed only when the native codec is
    built, same policy as before; field absent == "not checksummed",
    same as a reference peer."""
    return wc.make_tensor(arr)


def _tensor_arr(msg) -> np.ndarray:
    """wire Tensor -> zero-copy (read-only) ndarray view over the
    message payload; crc-verified when declared. Raises
    PayloadCorruptError on checksum mismatch."""
    return wc.tensor_view(msg)


class StageServer:
    """Serves one pipeline stage (the reference's per-node role,
    node.py:34-113). `engine` supplies the staged model; `node_id` selects
    which part this process owns via the shared topology config.
    `transport` is this server's DOWNSTREAM hop preference
    (auto | grpc | shm | device — comm/transport.py; default follows the
    engine's config)."""

    def __init__(self, engine, node_id: str,
                 transport: Optional[str] = None):
        # Warm the native codec NOW (a synchronous g++ compile on first
        # build) so it never runs inside an async RPC handler, where it
        # would freeze the event loop for the duration of the compile.
        from dnn_tpu.native import native_available

        native_available()
        self.engine = engine
        self.config = engine.config
        self.node = self.config.node_by_id(node_id)
        self.part_index = self.node.part_index
        self.is_last = self.part_index == self.config.num_parts - 1
        nxt = self.config.next_node(self.node)
        self.next_address = nxt.address if nxt else None
        self._next_channel: Optional[grpc.aio.Channel] = None
        if transport is None:
            transport = getattr(engine, "transport", "auto")
        if transport not in _tx.TRANSPORTS:
            raise ValueError(
                f"transport must be one of {_tx.TRANSPORTS}, got "
                f"{transport!r}")
        self.transport = transport
        self._thost = _tx.TransportHost(stage=self.node.id)
        self._negotiated: Optional[_tx.Negotiated] = None
        self._hop_warm = False  # one successful send on the downstream hop
        self._neg_lock = asyncio.Lock()

    #: streamed-relay accept window: how many decoded microbatches may sit
    #: acked-but-not-yet-computed per stream. Depth trades upstream overlap
    #: against per-stage memory (window * activation bytes); a full queue
    #: stalls acks, so backpressure propagates upstream hop by hop.
    ACCEPT_WINDOW = 4

    # --- RPC implementations (names/signatures fixed by the protocol) ---

    def _ingress(self, tensor):
        """Inbound payload -> (activation, transport_name). Tickets
        resolve through the transport host (device mailbox / shm);
        inline tensors decode zero-copy. shm payloads are COPIED out of
        their ring slot here (the slot is sender-owned and may be
        released + overwritten the moment the sender stops waiting —
        e.g. its deadline expires mid-compute; one memcpy is the price
        of a race-free license, and still no serialization).
        TransportError is fail-loud at the RPC boundary
        (INVALID_ARGUMENT), never a silent mis-decode."""
        if self._thost.is_ticket(tensor):
            if tensor.dtype == _tx.TICKET_DTYPE_DEV:
                return self._thost.resolve(tensor), "device"
            return np.array(self._thost.resolve(tensor)), "shm"
        return _tensor_arr(tensor), "grpc"

    async def SendTensor(self, request, context):
        nid = self.node.id
        result_msg = None
        t_handler = time.perf_counter()
        # propagated deadline (dl= request_id segment): the remaining
        # budget the SENDER granted the rest of the pipeline — our
        # downstream forward must fit inside it minus our own elapsed
        inbound_dl = _tx.extract_deadline(request.request_id)
        m = obs.metrics()
        if m is not None:
            m.inc(labeled("comm.payload_bytes_total", direction="in",
                          stage=nid), request.ByteSize())
        # continue the sender's trace (or start fresh); the tree crosses
        # every relay hop because _forward re-tags the request_id it
        # forwards with its own span
        root = obs.continue_or_start("stage.request", request.request_id,
                                     stage=nid, part=self.part_index)
        t_in = "grpc"
        try:
            try:
                x, t_in = self._ingress(request.tensor)
            except PayloadCorruptError as e:
                # Fail the RPC itself (not a status string) so the sender's
                # retry loop sees DATA_LOSS and resends — transient wire
                # corruption must not become a terminal pipeline error.
                log.warning("corrupt payload on %s: %s", nid, e)
                root.end(error="payload_corrupt")
                await context.abort(grpc.StatusCode.DATA_LOSS, str(e))
            except _tx.TransportError as e:
                # a ticket this process cannot resolve is a deployment
                # error (mis-negotiated transport), not data corruption
                log.warning("transport ticket error on %s: %s", nid, e)
                root.end(error="transport_ticket")
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                    str(e))
            root.set(transport=t_in)
            with root.child("stage.compute", part=self.part_index):
                # the barrier forces device completion — the span
                # measures the stage's real compute, not its dispatch.
                # The output STAYS device-resident: a device-negotiated
                # downstream hop hands it on without ever pulling it to
                # the host (the sender's make_request decides).
                # OFF-LOOP (CON001): _compute_stage blocks on device
                # completion; running it inline held the event loop for
                # the full stage compute (first call: the jit compile),
                # stalling every concurrent RPC — including the Relay
                # acks that free upstream send windows. The streamed
                # path already computed via to_thread; the unary path
                # now matches.
                y = await asyncio.to_thread(self._compute_stage, x)
            if self.is_last:
                y = np.asarray(y)
                pred = int(np.argmax(y))
                log.info("final stage done (node %s), prediction=%d", nid, pred)
                status = f"[{nid}] Processing complete. Prediction: {pred}"
                result_msg = _tensor_msg(y)
            else:
                remaining_dl = None
                if inbound_dl is not None:
                    remaining_dl = inbound_dl - (time.perf_counter()
                                                 - t_handler)
                resp = await self._forward(request.request_id, y,
                                           parent=root,
                                           inbound_budget=remaining_dl)
                status = f"[{nid}] Forwarded. Next node status: {resp.status}"
                if resp.HasField("result_tensor"):
                    result_msg = resp.result_tensor
        except grpc.aio.AbortError:
            raise  # the DATA_LOSS abort above must fail the RPC, not relay
        except grpc.aio.AioRpcError as e:
            log.error("forward from %s to %s failed: %s", nid, self.next_address, e.details())
            status = f"[{nid}] Error forwarding: {e.details()}"
        except Exception as e:  # noqa: BLE001 — status-string relay, like node.py:96-100
            log.exception("error processing tensor on %s", nid)
            status = f"[{nid}] Error: {e}"
        finally:
            root.end()
        if m is not None:
            m.observe_hist(
                labeled("comm.rpc_latency_seconds", method="SendTensor",
                        role="server", stage=nid, transport=t_in),
                time.perf_counter() - t_handler)
        resp_msg = wc.TensorResponse(status=status, result_tensor=result_msg)
        if m is not None:
            m.inc(labeled("comm.payload_bytes_total", direction="out",
                          stage=nid), resp_msg.ByteSize())
        return resp_msg

    async def HealthCheck(self, request: pb.Empty, context) -> pb.HealthCheckResponse:
        return pb.HealthCheckResponse(is_healthy=True)

    async def SendMessage(self, request: pb.MessageRequest, context) -> pb.MessageReply:
        if request.sender_id.startswith(_tx.HELLO_SENDER):
            # transport negotiation side-channel (comm/transport.py):
            # answer with this process's proof-backed accept/decline.
            # Rides the reference's own SendMessage RPC, so the
            # handshake is wire-compatible by construction.
            return pb.MessageReply(
                confirmation_text=self._thost.answer_hello(
                    request.message_text))
        log.info("message for %s from %s", self.node.id, request.sender_id)
        return pb.MessageReply(
            confirmation_text=f"[{self.node.id}] got msg '{request.message_text}'"
        )

    # --- streamed relay (non-nested MPMD forwarding) -------------------

    async def Relay(self, request_iterator, context):
        """Streamed relay: the non-nested replacement for the unary
        SendTensor chain. Each inbound frame (one microbatch, possibly
        chunked) is ACKED UPSTREAM as soon as it is accepted — the
        upstream sender's window advances while THIS stage computes, so
        microbatch m+1 runs on stage i while microbatch m runs on stage
        i+1 (the MPMD overlap the nested chain could never express:
        node.py:84 holds every hop open for the full downstream
        latency). Results ride back asynchronously, tagged `res:<seq>:`.

        Non-idempotent by design (the ack already released the upstream
        sender's payload slot), so this path is NEVER retried — a broken
        stream surfaces to the caller, which falls back to the unary
        path for a fresh attempt.

        Acks are EAGER: inbound frames are decoded and acknowledged as
        they arrive into a bounded accept queue (ACCEPT_WINDOW deep),
        while a separate consumer runs the stage computes in order — so
        the measured ack latency is the TRANSPORT cost of the hop, and
        the upstream stage pipelines up to the window depth ahead. A
        full queue stalls the reader, which stalls acks — backpressure
        propagates upstream hop by hop. shm payloads are copied out of
        their ring slot at accept time (one memcpy — the ack is the
        sender's license to overwrite the slot); device/grpc payloads
        need no copy.

        Frames carry transport tickets when the upstream hop negotiated
        device/shm: the streamed schedule and the payload transport
        compose."""
        nid = self.node.id
        m = obs.metrics()
        out_q: asyncio.Queue = asyncio.Queue()
        _DONE = object()
        ds_state = {"call": None, "pump": None, "writer": None,
                    "consumer": None, "wq": None, "pending": {},
                    "sent_at": {}}

        async def _ensure_downstream():
            if ds_state["call"] is not None:
                return ds_state["call"]
            await self._ensure_negotiated()
            if self._next_channel is None:
                self._next_channel = grpc.aio.insecure_channel(
                    self.next_address)
            # NO per-stream deadline: a relay stream lives as long as
            # the upstream keeps feeding it (a per-hop budget would
            # kill any healthy run longer than one hop's slice); its
            # lifetime is bounded by the upstream stream — when that
            # ends or breaks, the cleanup below cancels this call
            call = self._next_channel.stream_stream(
                f"/{SERVICE_NAME}/Relay",
                request_serializer=wc.serialize_request,
                response_deserializer=wc.parse_response,
            )()
            ds_state["call"] = call
            # dedicated writer: the compute loop hands frames to a
            # bounded queue and moves on — the gRPC flush never holds
            # the stage. Backpressure survives: the queue bound (and
            # the shm ring ahead of it) stalls the compute loop when
            # the downstream genuinely can't drain.
            ds_state["wq"] = asyncio.Queue(maxsize=2 * self.ACCEPT_WINDOW)
            ds_state["writer"] = asyncio.ensure_future(
                _write_downstream(call, ds_state["wq"]))
            ds_state["pump"] = asyncio.ensure_future(_pump_downstream(call))
            return call

        async def _write_downstream(call, wq):
            try:
                while True:
                    frame = await wq.get()
                    if frame is None:
                        break
                    await call.write(frame)
            except Exception as e:  # noqa: BLE001 — surface, don't vanish
                # a dead writer must tell the upstream NOW — otherwise
                # the client only learns at its own deadline
                log.warning("relay downstream write failed on %s: %s",
                            nid, e)
                await out_q.put(wc.TensorResponse(
                    status=_tx.result_status(
                        -1, f"[{nid}] Error forwarding: {e}")))
                await out_q.put(_DONE)
            finally:
                try:
                    await call.done_writing()
                except Exception:  # noqa: BLE001 — already-broken call
                    pass

        async def _pump_downstream(call):
            """Relay downstream results upstream; downstream ACKS free
            this stage's sender resources (shm slots / mailbox) and
            stamp the hop latency — submit -> downstream-accept, the
            time THIS stage would have been blocked under the nested
            chain."""
            neg = self._negotiated
            try:
                async for resp in call:
                    seq = _tx.parse_ack(resp.status)
                    if seq is not None:
                        req = ds_state["pending"].pop(seq, None)
                        if req is not None and neg is not None:
                            neg.sender.sent_ok(req)
                        t_sent = ds_state["sent_at"].pop(seq, None)
                        if m is not None and t_sent is not None:
                            # DELIVERY latency (submit -> downstream
                            # accept): includes queueing when the
                            # accept window backs up — the backpressure
                            # signal, distinct from hop OCCUPANCY
                            dt = time.perf_counter() - t_sent
                            m.observe(labeled("comm.hop_ack_seconds",
                                              stage=nid,
                                              transport=neg.name), dt)
                            m.observe_hist(
                                labeled("comm.rpc_latency_seconds",
                                        method="relay_hop", role="client",
                                        stage=nid, transport=neg.name),
                                dt)
                        self._hop_warm = True
                        continue
                    await out_q.put(resp)
            finally:
                await out_q.put(_DONE)

        async def _forward_one(base_rid, seq, y, root, neg):
            """Forward one computed microbatch downstream: streamed when
            the peer speaks Relay, else the bounded-retry unary chain
            (reference peers) — THIS stage's ack-early overlap survives
            either way."""
            if neg.relay_ok:
                await _ensure_downstream()
                sp = obs.start_span("rpc.forward", parent=root,
                                    target=self.next_address,
                                    transport=neg.name, streamed=True)
                t0 = time.perf_counter()
                # fast path: a non-blocking make (free shm slot = one
                # memcpy). When the ring is FULL the make must not run
                # on the event loop — the loop processes the very acks
                # that free slots, so a blocking wait here deadlocks
                # the stream until the ring timeout; the slow path
                # waits on a worker thread instead (honest backpressure)
                rid_out = obs.tag_request_id(base_rid, sp) if sp else base_rid
                req_out = neg.sender.make_request_nowait(y, rid_out)
                if req_out is None:
                    req_out = await asyncio.to_thread(
                        neg.sender.make_request, y, rid_out)
                ds_state["pending"][seq] = req_out
                ds_state["sent_at"][seq] = t0
                for sub in _tx.split_requests(req_out, seq):
                    await ds_state["wq"].put(sub)
                if m is not None:
                    # hop OCCUPANCY: how long this stage was held by
                    # the hop before it could move to the next
                    # microbatch — under the nested chain this is the
                    # full downstream round trip (see _forward); here
                    # it is the payload handoff (shm-ring/mailbox write
                    # + frame enqueue, including any backpressure stall
                    # when the ring or the writer queue is full)
                    m.observe(labeled("comm.hop_seconds", stage=nid,
                                      transport=neg.name,
                                      mode="streamed"),
                              time.perf_counter() - t0)
                sp.end()
                return
            resp = await self._forward(base_rid, y, parent=root)
            human = f"[{nid}] Forwarded. Next node status: {resp.status}"
            await out_q.put(wc.TensorResponse(
                status=_tx.result_status(seq, human),
                result_tensor=resp.result_tensor
                if resp.HasField("result_tensor") else None))

        accept_q: asyncio.Queue = asyncio.Queue(maxsize=self.ACCEPT_WINDOW)

        async def _read_inputs():
            """Eager accept: decode + ack each frame as it arrives; the
            bounded accept queue is the pipelining window (full queue ->
            reads stall -> acks stall -> backpressure upstream)."""
            asm = _tx.ChunkAssembler()
            try:
                async for frame in request_iterator:
                    done = asm.add(frame)
                    if done is None:
                        continue
                    base_rid, seq, tensor = done
                    t0 = time.perf_counter()
                    # _ingress copies shm payloads out of their slot:
                    # the ack below licenses the sender to overwrite it
                    x, t_in = self._ingress(tensor)
                    await accept_q.put((base_rid, seq, x, t_in, t0))
                    # ack upstream NOW: the sender's window advances
                    # while this stage's compute queue drains
                    await out_q.put(wc.TensorResponse(
                        status=_tx.ack_status(seq)))
            finally:
                await accept_q.put(None)

        async def _compute_loop():
            try:
                while True:
                    item = await accept_q.get()
                    if item is None:
                        break
                    base_rid, seq, x, t_in, t0 = item
                    root = obs.continue_or_start(
                        "stage.request", base_rid, stage=nid,
                        part=self.part_index, transport=t_in, seq=seq)
                    try:
                        with root.child("stage.compute",
                                        part=self.part_index):
                            y = await asyncio.to_thread(
                                self._compute_stage, x)
                        if self.is_last:
                            y = np.asarray(y)
                            await out_q.put(wc.TensorResponse(
                                status=_tx.result_status(
                                    seq, f"[{nid}] Processing complete. "
                                         f"Prediction: {int(np.argmax(y))}"),
                                result_tensor=_tensor_msg(y)))
                        else:
                            neg = await self._ensure_negotiated()
                            await _forward_one(base_rid, seq, y, root, neg)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — PER-ITEM
                        # degradation, matching the unary chain: one bad
                        # microbatch answers its own seq with an error
                        # status and the stream lives on
                        log.warning("relay item %s failed on %s: %s",
                                    seq, nid, e)
                        root.set(error=str(e))
                        await out_q.put(wc.TensorResponse(
                            status=_tx.result_status(
                                seq, f"[{nid}] Error: {e}")))
                    finally:
                        root.end()
                    if m is not None:
                        m.observe_hist(
                            labeled("comm.rpc_latency_seconds",
                                    method="Relay", role="server",
                                    stage=nid, transport=t_in),
                            time.perf_counter() - t0)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — infrastructure
                # failure outside any one item: ends the stream
                log.exception("relay stream failure on %s", nid)
                await out_q.put(wc.TensorResponse(
                    status=_tx.result_status(-1, f"[{nid}] Error: {e}")))
            finally:
                if ds_state["writer"] is not None:
                    # drain the writer, whose own finally closes the
                    # downstream send side (done_writing)
                    await ds_state["wq"].put(None)
                    await ds_state["writer"]
                else:
                    await out_q.put(_DONE)

        async def _pump_inputs():
            """Reader + consumer; decode/ingress errors surface like
            compute errors (status-string relay)."""
            ds_state["consumer"] = asyncio.ensure_future(_compute_loop())
            try:
                await _read_inputs()
            except (_tx.TransportError, PayloadCorruptError,
                    ValueError) as e:
                log.warning("relay ingress error on %s: %s", nid, e)
                await out_q.put(wc.TensorResponse(
                    status=_tx.result_status(-1, f"[{nid}] Error: {e}")))
                await accept_q.put(None)
            await ds_state["consumer"]

        reader = asyncio.ensure_future(_pump_inputs())
        try:
            while True:
                item = await out_q.get()
                if item is _DONE:
                    break
                yield item
        finally:
            # cancel EVERY task this stream spawned — cancelling the
            # reader alone would strand a consumer blocked on a full
            # writer queue after a downstream failure (a leak per
            # broken stream) — and tear down the downstream call
            reader.cancel()
            for key in ("pump", "writer", "consumer"):
                if ds_state[key] is not None:
                    ds_state[key].cancel()
            if ds_state["call"] is not None:
                ds_state["call"].cancel()
            # release any sender resources stranded by a broken stream
            neg = self._negotiated
            if neg is not None:
                for req in ds_state["pending"].values():
                    neg.sender.cleanup(req)
            ds_state["pending"].clear()

    # --- plumbing ---

    def _compute_stage(self, x):
        """Run this process's stage and BLOCK until the device finished
        (honest compute spans/timings), without pulling the result to
        the host — the transport decides whether host bytes ever exist:
        grpc/shm senders np.asarray on encode, the device sender hands
        the device-resident array through the mailbox untouched."""
        from dnn_tpu.utils.tracing import device_sync

        y = self.engine.run_stage(self.part_index, x)
        device_sync(y)
        return y

    def _next_device(self):
        """The downstream stage's device when it lives in this process
        (the relay runtime pins one device per stage) — lets the device
        sender start the D2D transfer before the control message."""
        relay = getattr(self.engine, "_relay", None)
        nxt = self.part_index + 1
        if relay is not None and nxt < len(relay.devices):
            return relay.devices[nxt]
        return None

    async def _ensure_negotiated(self) -> _tx.Negotiated:
        """Negotiate the downstream hop once (comm/transport.py). A
        transport-level failure (downstream not up yet) yields an
        UNCACHED grpc verdict so the handshake is retried on the next
        forward; an explicit misconfig raises (fail-loud)."""
        async with self._neg_lock:
            if self._negotiated is not None:
                return self._negotiated
            if self.transport == "grpc":
                self._negotiated = _tx.Negotiated(
                    "grpc", _tx.GrpcSender(), reason="explicit")
                return self._negotiated
            if self._next_channel is None:
                self._next_channel = grpc.aio.insecure_channel(
                    self.next_address)
            offer, probe = _tx.build_offer(self.transport)
            try:
                call = self._next_channel.unary_unary(
                    f"/{SERVICE_NAME}/SendMessage",
                    request_serializer=pb.MessageRequest.SerializeToString,
                    response_deserializer=pb.MessageReply.FromString,
                )
                try:
                    reply = await call(
                        pb.MessageRequest(sender_id=_tx.HELLO_SENDER,
                                          message_text=json.dumps(offer)),
                        timeout=10.0)
                except grpc.aio.AioRpcError as e:
                    # no verdict — this forward rides grpc, handshake
                    # retried next time
                    return _tx.Negotiated(
                        "grpc", _tx.GrpcSender(),
                        reason=f"hello failed: {e.code()}")
                self._negotiated = _tx.conclude(
                    offer, reply.confirmation_text,
                    transport=self.transport, target=self.next_address,
                    device=self._next_device())
                return self._negotiated
            finally:
                _tx.close_probe(probe)

    async def _forward(
        self, request_id: str, y: np.ndarray, *, retries: int = 2,
        backoff: float = 0.2, timeout: Optional[float] = None,
        parent=None, inbound_budget: Optional[float] = None,
    ):
        """Relay downstream with bounded retries on transient failures,
        reusing the shared channel across attempts (gRPC reconnects a broken
        channel on the next call) — the per-hop resilience the reference
        lacks (SURVEY §5: failures only become status strings, "No retry").

        The hop rides the NEGOTIATED transport (comm/transport.py):
        device/shm sends carry a ticket (the payload stays in the mailbox
        / shm ring until the response lands, so a transport-level retry
        resends the same ticket safely); grpc sends carry the inline
        zero-copy tensor — byte-identical to the reference wire.

        Deadline discipline: the relayed call spans the ENTIRE remaining
        pipeline (response-chain semantics, SURVEY §3.3), so this hop gets
        an OVERALL budget that scales with remaining depth — derived from
        the negotiated transport (transport.hop_budget_s): grpc keeps the
        reference-compatible PER_STAGE_BUDGET_S slice per downstream
        stage; a WARM device/shm hop budgets seconds per stage instead of
        inheriting the 30 s serialization+compile margin. The budget is
        shared across all attempts and backoff sleeps (each attempt's
        gRPC deadline is the budget REMAINING, mirroring
        NodeClient.send_tensor). DEADLINE_EXCEEDED itself is not
        retryable (see RETRYABLE_CODES): the expired budget already
        covered the whole remaining pipeline.

        The relayed request_id is RE-TAGGED with this hop's span
        (obs.tag_request_id), so the downstream stage's spans nest under
        this hop's `rpc.forward` — one tree per request across the whole
        chain; retries count into comm.retries_total{stage=...} with the
        trace id in the log line, so a backoff storm is visible and
        attributable instead of silent."""
        neg = await self._ensure_negotiated()
        sp = obs.start_span("rpc.forward", parent=parent,
                            target=self.next_address, transport=neg.name)
        downstream = max(self.config.num_parts - self.part_index - 1, 1)
        if timeout is None:
            timeout = _tx.hop_budget_s(neg.name, downstream,
                                       warm=self._hop_warm)
        if inbound_budget is not None:
            # never grant downstream more than the sender still has:
            # the propagated deadline caps the derived budget, so a
            # nearly-dead request can't spend a fresh full ladder at
            # every remaining hop (the floor keeps gRPC's deadline
            # validation happy; an already-expired budget fails fast)
            timeout = max(min(timeout, inbound_budget), 0.001)
        # non-blocking make when a slot is free; with concurrent
        # in-flight requests the shm ring can fill, and the WAIT must
        # leave the loop free to process the downstream responses that
        # release slots — so the full make runs on a worker thread.
        # The forwarded request_id re-tags the deadline with what THIS
        # hop grants (<= what it was granted).
        rid_out = obs.tag_request_id(request_id, sp) if sp else request_id
        rid_out = _tx.tag_deadline(rid_out, timeout)
        request = neg.sender.make_request_nowait(y, rid_out)
        if request is None:
            request = await asyncio.to_thread(
                neg.sender.make_request, y, rid_out)
        if self._next_channel is None:
            self._next_channel = grpc.aio.insecure_channel(self.next_address)
        call = self._next_channel.unary_unary(
            f"/{SERVICE_NAME}/SendTensor",
            request_serializer=wc.serialize_request,
            response_deserializer=wc.parse_response,
        )
        deadline = time.monotonic() + timeout
        attempt = 0
        m = obs.metrics()
        nid = self.node.id
        completed = False
        try:
            while True:
                remaining = deadline - time.monotonic()
                # refresh the propagated deadline per attempt (see the
                # edge client): the wire advertises the budget LEFT
                request.request_id = _tx.tag_deadline(
                    rid_out, max(remaining, 0.001))
                t_try = time.perf_counter()
                if m is not None:
                    # per ATTEMPT, like the edge client: relayed bytes
                    # must reconcile with the downstream stage's
                    # direction="in" count even through retries
                    m.inc(labeled("comm.payload_bytes_total",
                                  direction="out", stage=nid),
                          request.ByteSize())
                try:
                    _chaos_inject.perturb_rpc("stage", self.next_address)
                    t_send_wall = time.time() if sp else 0.0
                    resp = await call(request, timeout=max(remaining, 0.001))
                    dt = time.perf_counter() - t_try
                    if sp:
                        # clock-offset sampling fields for cross-host
                        # stitching, as in client.send_tensor: the
                        # successful attempt's wall-clock window only
                        sp.set(cs=t_send_wall, cr=time.time())
                    if m is not None:
                        m.observe_hist(
                            labeled("comm.rpc_latency_seconds",
                                    method="forward", role="client",
                                    stage=nid, transport=neg.name),
                            dt)
                        # exact-quantile per-hop series (the bench's
                        # regression-asserted number rides this);
                        # mode="nested": the sender was held for the
                        # full downstream round trip
                        m.observe(labeled("comm.hop_seconds",
                                          stage=nid, transport=neg.name,
                                          mode="nested"),
                                  dt)
                    sp.set(attempts=attempt + 1)
                    completed = True
                    self._hop_warm = True
                    return resp
                except (grpc.RpcError, PayloadCorruptError) as e:
                    # NOTE: the shared channel is deliberately NOT closed
                    # between attempts — other requests may have calls in
                    # flight on it, and gRPC reconnects a broken channel on
                    # the next call anyway. grpc.RpcError (not the aio
                    # subclass alone) so injected transport faults walk
                    # the same ladder real ones do; PayloadCorruptError
                    # maps to the DATA_LOSS retry policy like the edge
                    # client's.
                    code = e.code() if isinstance(e, grpc.RpcError) \
                        else grpc.StatusCode.DATA_LOSS
                    if m is not None and \
                            code == grpc.StatusCode.DEADLINE_EXCEEDED:
                        m.inc(labeled("comm.deadline_exceeded_total",
                                      stage=nid))
                    # full jitter (see client._backoff_delay): the
                    # budget check uses the worst-case delay so the
                    # ladder never outlives the propagated deadline
                    worst = backoff * (2 ** attempt)
                    out_of_budget = deadline - time.monotonic() <= worst
                    if code not in RETRYABLE_CODES or attempt >= retries \
                            or out_of_budget:
                        sp.set(error=str(code), attempts=attempt + 1)
                        raise
                    delay = full_jitter_delay(backoff, attempt)
                    if m is not None:
                        m.inc(labeled("comm.retries_total",
                                      stage=nid,
                                      outcome=code.name.lower()))
                    log.warning(
                        "forward %s -> %s failed (%s), retry %d/%d in "
                        "%.2fs [trace=%s]",
                        nid, self.next_address, code,
                        attempt + 1, retries, delay, sp.trace_id or "-",
                    )
                    await asyncio.sleep(delay)
                    attempt += 1
        finally:
            # in a FINALLY, not the except branch: a cancelled handler
            # (upstream deadline mid-forward) must still release the
            # ticket's shm slot / mailbox entry, or four cancellations
            # wedge the 4-slot ring for good
            if completed:
                neg.sender.sent_ok(request)
            else:
                neg.sender.cleanup(request)
            sp.end()

    async def close(self):
        if self._next_channel is not None:
            await self._next_channel.close()
            self._next_channel = None
        neg, self._negotiated = self._negotiated, None
        if neg is not None:
            neg.sender.close()
        self._thost.close()


def _resolve_port(servicer: StageServer, node_id: str, port: Optional[int]) -> int:
    bind_port = port if port is not None else servicer.node.port
    if bind_port is None:
        raise ValueError(
            f"node '{node_id}' has no address in the config; serving a stage "
            "requires nodes[].address with an IP:Port (config.json:6)"
        )
    return bind_port


def _handlers(servicer: StageServer):
    handlers = {
        "SendTensor": grpc.unary_unary_rpc_method_handler(
            servicer.SendTensor,
            request_deserializer=wc.parse_request,
            response_serializer=wc.serialize_response,
        ),
        "HealthCheck": grpc.unary_unary_rpc_method_handler(
            servicer.HealthCheck,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.HealthCheckResponse.SerializeToString,
        ),
        "SendMessage": grpc.unary_unary_rpc_method_handler(
            servicer.SendMessage,
            request_deserializer=pb.MessageRequest.FromString,
            response_serializer=pb.MessageReply.SerializeToString,
        ),
    }
    # streamed relay (stage servers): the non-nested MPMD forward path.
    # An ADDITIVE method like GenerateStream — reference peers never call
    # it, callers probing it on a reference server get UNIMPLEMENTED and
    # fall back to the unary chain.
    if hasattr(servicer, "Relay"):
        handlers["Relay"] = grpc.stream_stream_rpc_method_handler(
            servicer.Relay,
            request_deserializer=wc.parse_request,
            response_serializer=wc.serialize_response,
        )
    # the LM daemon's per-token streaming front (wire.proto GenerateStream);
    # stage servers don't implement it and callers get UNIMPLEMENTED
    if hasattr(servicer, "GenerateStream"):
        handlers["GenerateStream"] = grpc.unary_stream_rpc_method_handler(
            servicer.GenerateStream,
            request_deserializer=wc.parse_request,
            response_serializer=wc.serialize_response,
        )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


async def serve_stage(engine, node_id: str, *, port: Optional[int] = None,
                      metrics_port: Optional[int] = None,
                      transport: Optional[str] = None):
    """Start the gRPC server for this node's stage and block until
    termination (the rebuild of serve(), node.py:114-133).
    `transport` sets the downstream hop preference (auto | grpc | shm |
    device; None follows the engine config — see comm/transport.py).
    `metrics_port` (None = off, 0 = ephemeral) additionally serves the
    observability endpoint — GET /metrics (Prometheus text format:
    per-stage RPC latency with per-transport labels, payload bytes,
    retry/deadline counters, XLA compile telemetry, device/host memory
    gauges), /trace (Chrome-trace JSON), /debugz (flight ring), POST
    /profilez (on-demand device profile; no auto-trigger — that needs
    the LM daemon's step loop) — over stdlib HTTP."""
    obs.install_compile_telemetry()
    servicer = StageServer(engine, node_id, transport=transport)
    server = grpc.aio.server(options=_tx.GRPC_MSG_OPTIONS)
    server.add_generic_rpc_handlers((_handlers(servicer),))
    bind_port = _resolve_port(servicer, node_id, port)
    listen = f"[::]:{bind_port}"
    if server.add_insecure_port(listen) == 0:
        # grpc reports bind failure as port 0, not an exception (the
        # reference prints-and-exits on the same failure, node.py:124-126)
        raise RuntimeError(f"failed to bind gRPC server to {listen}")
    metrics_srv = None
    if metrics_port is not None:
        metrics_srv = obs.serve_metrics(metrics_port)
    log.info("gRPC stage server %s listening on %s (part %d, transport=%s)",
             node_id, listen, servicer.part_index, servicer.transport)
    await server.start()
    # loop-lag sanitizer (analysis/sanitize.py): env-gated tripwire for
    # blocking calls the AST pass can't see through an indirection —
    # the transport/chaos probes run their stage children with it on
    # and assert the bound from the served /debugz. Installed AFTER
    # startup so the native-codec warm compile doesn't count.
    from dnn_tpu.analysis import sanitize as _sanitize

    lagmon = _sanitize.maybe_install(where=f"serve_stage:{node_id}")
    try:
        await server.wait_for_termination()
    finally:
        if lagmon is not None:
            lagmon.stop()
        await servicer.close()
        await server.stop(grace=1)
        if metrics_srv is not None:
            metrics_srv.close()


def start_stage_server_in_background(engine, node_id: str, *,
                                     port: Optional[int] = None,
                                     transport: Optional[str] = None):
    """Test/embedding helper: run serve_stage on a daemon thread; returns
    (thread, stop_callback)."""
    import threading

    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    async def _run():
        # grpc.aio binds to the event loop current at construction time, so
        # the server (and the servicer's forwarding channel) must be created
        # inside this thread's loop, not the caller's.
        try:
            servicer = StageServer(engine, node_id, transport=transport)
            server = grpc.aio.server(options=_tx.GRPC_MSG_OPTIONS)
            server.add_generic_rpc_handlers((_handlers(servicer),))
            bind_port = _resolve_port(servicer, node_id, port)
            if server.add_insecure_port(f"[::]:{bind_port}") == 0:
                raise RuntimeError(f"failed to bind gRPC server to [::]:{bind_port}")
            await server.start()
            state["servicer"], state["server"] = servicer, server
            state["done"] = asyncio.Event()
        except BaseException as e:  # surface startup failure to the caller
            state["error"] = e
            raise
        finally:
            started.set()
        await state["done"].wait()
        # drain one cycle so the stop() future resolves before the loop ends
        await asyncio.sleep(0.05)

    def _thread_main():
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(_run())
        except BaseException:
            if "error" not in state:
                raise  # startup succeeded; die loudly on later failures
            # startup error already recorded and re-raised to the caller

    t = threading.Thread(target=_thread_main, daemon=True)
    t.start()
    if not started.wait(timeout=15):
        raise RuntimeError(f"stage server for {node_id} failed to start")
    if "error" in state:
        t.join(timeout=5)
        raise RuntimeError(
            f"stage server for {node_id} failed to start: {state['error']}"
        ) from state["error"]

    def stop():
        async def _stop():
            await state["servicer"].close()
            await state["server"].stop(grace=0.2)
            state["done"].set()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=10)
        t.join(timeout=5)

    return t, stop
