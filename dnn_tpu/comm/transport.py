"""Pluggable inter-stage transport: device-native hops, shm, gRPC.

PR 5's fleet-stitched trace put the warm 2-stage cifar pipeline at 75.9%
bubble (STUDIES.md §10): each gRPC hop is a nested unary RPC held open
for the full downstream latency, and every payload round-trips through
host serialization copies. This module makes the hop a NEGOTIATED,
pluggable layer — ROADMAP item 1 — with gRPC demoted to the cross-pod /
reference-interop fallback:

    device   same-process hops move the activation device-to-device with
             no host serialization at all: the jit output rides a
             process-global mailbox as a ticket (tiny gRPC control
             message), and the receiver `jax.device_put`s it onto its
             stage device — the RelayExecutor hop, formalized. For
             mesh-resident activations, `make_hop_program` is the
             compiled ppermute send/recv (XLA CollectivePermute over
             ICI) the SPMD runtime uses; its switch branches are
             PRG001-audited (analysis/program.audit_transport_programs).
    shm      same-host cross-process hops write the payload ONCE into a
             POSIX shared-memory ring slot; the receiver maps a zero-
             copy numpy view. Same-host reachability is PROVEN at
             handshake (the server attaches the client's probe segment
             and echoes a nonce out of it), never inferred from
             hostnames.
    grpc     the reference wire protocol, unchanged bytes (wire.proto),
             now zero-copy at both ends (comm/wirecodec.py) and — when
             both peers are dnn_tpu — non-nested: the streamed Relay
             path acks upstream as soon as a microbatch is accepted, so
             stages overlap across processes (the MPMD schedule,
             arxiv 2412.14374) instead of holding every hop open.

Negotiation is a single SendMessage RPC (sender_id
`dnn_tpu.transport.hello`, JSON offer/accept in the text fields) —
wire-compatible by construction: a reference peer answers with its
normal confirmation string, which fails to parse as an accept, and the
ladder lands on grpc. `auto` walks device -> shm -> grpc and records a
`transport_fallback` flight event when it degrades; an EXPLICIT
`--transport device|shm` that cannot be satisfied fails loud
(TransportMisconfigError), never silently downgrades.

Deadlines follow the negotiated transport: a warm device/shm hop budgets
seconds, not the 30 s gRPC margin sized for serialization + LAN + jit
compiles (hop_budget_s). Streamed relay hops are non-idempotent (the
ack already released the upstream sender) and are never retried.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dnn_tpu import obs
from dnn_tpu.chaos import inject as _chaos_inject
from dnn_tpu.comm import wirecodec as wc
from dnn_tpu.utils.metrics import labeled

log = logging.getLogger("dnn_tpu.comm")

TRANSPORTS = ("auto", "grpc", "shm", "device")

#: channel/server options every dnn_tpu gRPC endpoint shares: the
#: stock 4 MB message cap silently breaks KV-sized unary payloads —
#: a gpt2 row handoff (control/handoff.py) packs ~7 MB, and kvtier
#: block payloads (kvtier/migrate.py) scale with prefix length — so
#: both sides raise it to one bound, high enough for any single
#: tensor the serving stack ships, low enough to still catch runaway
#: frames. (The streamed relay chunks its frames and never needed
#: this; unary KV tensors cannot chunk.)
GRPC_MSG_OPTIONS = [
    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
    ("grpc.max_send_message_length", 64 * 1024 * 1024),
]

# The negotiation side-channel rides SendMessage with this sender_id
# prefix; every dnn_tpu server (stage + LM daemon) routes it to
# answer_hello / decline_hello instead of its normal text handling.
HELLO_SENDER = "dnn_tpu.transport.hello"

# Ticket payloads ride the ordinary Tensor message with these dtype
# markers. They are only ever sent AFTER a successful negotiation, so a
# reference peer never sees one; an un-negotiated ticket arriving at a
# dnn_tpu server is a loud INVALID_ARGUMENT, not a silent mis-decode.
TICKET_DTYPE_DEV = "dnn.dev1"
TICKET_DTYPE_SHM = "dnn.shm1"
TICKET_DTYPES = (TICKET_DTYPE_DEV, TICKET_DTYPE_SHM)

# One token per process / per host: the proof substrate for the device
# (same-process) rung; shm is proven by the probe-segment attach, not by
# token comparison.
PROC_TOKEN = uuid.uuid4().hex


def host_token() -> str:
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = "-"
    return f"{socket.gethostname()}:{boot}"


# ----------------------------------------------------------------------
# deadline budgets (satellite: per-hop deadlines follow the transport)
# ----------------------------------------------------------------------

# The reference-compatible gRPC per-stage slice (moved here from
# comm/service.py, which re-exports it): compute budget for one stage's
# jit-compiled forward (first-call XLA compiles included) plus the gRPC
# wire margin (serialize + LAN + deserialize of MB-scale payloads).
STAGE_COMPUTE_BUDGET_S = 25.0
HOP_MARGIN_S = {"grpc": 5.0, "shm": 1.0, "device": 0.5}
PER_STAGE_BUDGET_S = STAGE_COMPUTE_BUDGET_S + HOP_MARGIN_S["grpc"]  # 30.0
# After a hop's first successful send, the downstream stage's programs
# are compiled; device/shm hops then budget per-stage seconds instead of
# inheriting the compile-inclusive slice. grpc keeps the full slice
# always — its budget arithmetic is part of the reference-compatible
# contract (client.pipeline_budget strictly dominating the first hop's
# server-side budget).
WARM_STAGE_COMPUTE_BUDGET_S = 5.0


def hop_budget_s(transport: str, downstream_stages: int, *,
                 warm: bool = False) -> float:
    """Overall budget for one hop covering `downstream_stages` stages,
    derived from the NEGOTIATED transport. `warm`: at least one send on
    this hop already succeeded (device/shm only — see above)."""
    name = "grpc" if transport not in HOP_MARGIN_S else transport
    compute = STAGE_COMPUTE_BUDGET_S
    if warm and name != "grpc":
        compute = WARM_STAGE_COMPUTE_BUDGET_S
    return (compute + HOP_MARGIN_S[name]) * max(downstream_stages, 1)


class TransportError(RuntimeError):
    """Base for transport negotiation/resolution failures."""


class TransportMisconfigError(TransportError):
    """An EXPLICITLY requested transport cannot be satisfied on this
    hop (e.g. --transport device across processes). Fail-loud by
    design: auto-degrading an explicit request would hide a deployment
    error behind a 100x slower wire."""


# ----------------------------------------------------------------------
# device mailbox (same-process zero-serialization hops)
# ----------------------------------------------------------------------

class _DeviceMailbox:
    """Process-global rendezvous for device-resident activations: the
    sender parks the jit output under a ticket, the receiving stage
    (same process, possibly another thread/event loop) picks it up and
    `device_put`s it onto its own stage device. Entries are peeked, not
    popped, so a transport-level retry can resend the same ticket; the
    SENDER drops the entry once the hop's response lands."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, Any] = {}

    def put(self, value) -> str:
        ticket = uuid.uuid4().hex
        with self._lock:
            self._entries[ticket] = value
        return ticket

    def peek(self, ticket: str):
        with self._lock:
            return self._entries.get(ticket)

    def drop(self, ticket: str):
        with self._lock:
            self._entries.pop(ticket, None)

    def __len__(self):
        with self._lock:
            return len(self._entries)


MAILBOX = _DeviceMailbox()


def make_hop_program(mesh, axis_name: str = "stage"):
    """Compiled device send/recv for mesh-resident activations: ONE
    program, `lax.switch` over the hop index, branch i a single
    `lax.ppermute` moving stage i's row to stage i+1 (XLA
    CollectivePermute over ICI on real pods). Every branch must issue
    the identical collective sequence or ranks deadlock — the same SPMD
    contract as the pipeline's stage switch, and the analyzer's PRG001
    pass audits exactly this program
    (analysis/program.audit_transport_programs).

    Returns `hop(hop_index, buf)` jitted; `buf` is sharded P(axis_name)
    with one (1, ...) row per stage."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    if n < 2:
        raise ValueError(f"hop program needs >= 2 stages on '{axis_name}'")

    def branch(i):
        def b(x):
            return lax.ppermute(x, axis_name, [(i, i + 1)])
        return b

    branches = [branch(i) for i in range(n - 1)]

    def per_device(hop, buf):
        return lax.switch(hop, branches, buf)

    shuttled = jax.shard_map(
        per_device, mesh=mesh, in_specs=(P(), P(axis_name)),
        out_specs=P(axis_name), check_vma=False)
    return jax.jit(shuttled)


# ----------------------------------------------------------------------
# shm ring (same-host cross-process hops)
# ----------------------------------------------------------------------

class _ShmSlot:
    def __init__(self, nbytes: int):
        from multiprocessing import shared_memory

        self.shm = shared_memory.SharedMemory(
            create=True, size=max(int(nbytes), 1),
            name=f"dnn_tpu_{uuid.uuid4().hex[:16]}")
        self.busy = False

    def ensure(self, nbytes: int):
        if self.shm.size < nbytes:
            old = self.shm
            self.shm = None
            try:
                old.unlink()
                old.close()
            except (OSError, BufferError):
                pass
            from multiprocessing import shared_memory

            self.shm = shared_memory.SharedMemory(
                create=True, size=int(nbytes),
                name=f"dnn_tpu_{uuid.uuid4().hex[:16]}")

    def close(self):
        if self.shm is not None:
            try:
                self.shm.unlink()
                self.shm.close()
            except (OSError, BufferError):
                # BufferError: a receiver-side zero-copy view still pins
                # the mapping; the segment is already unlinked, and the
                # mmap goes with the last view.
                pass
            self.shm = None


class ShmRing:
    """Sender-owned ring of reusable shared-memory slots, one hop's
    in-flight window. A slot is busy from `write` until the receiving
    side's response/ack frees it (the receiver consumes the payload into
    device memory synchronously inside its handler, so a freed slot is
    safe to overwrite). Segments grow in place (new name) when a payload
    outsizes them; unlinked on close."""

    def __init__(self, slots: int = 4):
        self._slots: List[Optional[_ShmSlot]] = [None] * max(slots, 1)
        self._lock = threading.Lock()
        self._free = threading.Condition(self._lock)

    def _acquire_locked(self, nbytes: int):
        """Find + mark a free slot; caller holds the lock. None if all
        slots are busy."""
        for i, s in enumerate(self._slots):
            if s is None or not s.busy:
                if s is None:
                    s = self._slots[i] = _ShmSlot(nbytes)
                else:
                    s.ensure(nbytes)
                s.busy = True
                return i, s
        return None

    def write(self, view: memoryview, timeout: float = 30.0) -> Tuple[str, int]:
        """Copy `view` into a free slot (THE one host copy of the shm
        path); returns (segment_name, slot_index). BLOCKS while all
        slots are in flight — callers on an event loop must use
        write_nowait first and fall back to a worker thread."""
        with self._free:
            if not self._free.wait_for(
                    lambda: any(s is None or not s.busy for s in self._slots),
                    timeout=timeout):
                raise TransportError(
                    "shm ring exhausted: no slot freed within "
                    f"{timeout}s ({len(self._slots)} slots)")
            idx, slot = self._acquire_locked(len(view))
        slot.shm.buf[: len(view)] = view
        return slot.shm.name, idx

    def write_nowait(self, view: memoryview) -> Optional[Tuple[str, int]]:
        """Non-blocking write: None when every slot is in flight (the
        event-loop fast path — a free slot costs one memcpy, never a
        wait)."""
        with self._free:
            got = self._acquire_locked(len(view))
            if got is None:
                return None
            idx, slot = got
        slot.shm.buf[: len(view)] = view
        return slot.shm.name, idx

    def release(self, idx: int):
        with self._free:
            s = self._slots[idx]
            if s is not None:
                s.busy = False
            self._free.notify_all()

    def close(self):
        with self._lock:
            for s in self._slots:
                if s is not None:
                    s.close()
            self._slots = [None] * len(self._slots)


# ----------------------------------------------------------------------
# senders (the per-hop client side, shared by NodeClient and the stage
# server's downstream forward)
# ----------------------------------------------------------------------

class Sender:
    """One negotiated hop. `make_request(arr, request_id)` builds the
    wire message (inline tensor or ticket); `sent_ok`/`cleanup` manage
    per-send resources; senders are thread-compatible for the unary
    path (one in-flight send per sender at a time on shm)."""

    name = "grpc"
    zero_serialization = False

    def make_request(self, arr, request_id: str) -> wc.TensorRequest:
        raise NotImplementedError

    def make_request_nowait(self, arr, request_id: str
                            ) -> Optional[wc.TensorRequest]:
        """Non-blocking variant for event-loop callers: None when the
        send would have to WAIT for a resource (shm ring full) — the
        caller then retries `make_request` off-loop. Default: nothing
        to wait on."""
        return self.make_request(arr, request_id)

    def sent_ok(self, request: wc.TensorRequest):
        """Called once the hop's response landed (payload consumed)."""

    def cleanup(self, request: wc.TensorRequest):
        """Called when the send is abandoned (terminal failure)."""

    def close(self):
        pass


class GrpcSender(Sender):
    name = "grpc"

    def make_request(self, arr, request_id: str) -> wc.TensorRequest:
        return wc.TensorRequest(request_id=request_id,
                                tensor=wc.make_tensor(arr))


class DeviceSender(Sender):
    """Same-process hop: the activation never leaves device-resident
    form. `device` (optional) is the RECEIVING stage's device — pinning
    the transfer here overlaps it with the control message instead of
    serializing it into the receiver's handler."""

    name = "device"
    zero_serialization = True

    def __init__(self, device=None):
        self.device = device

    def make_request(self, arr, request_id: str) -> wc.TensorRequest:
        val = arr
        if self.device is not None:
            import jax

            val = jax.device_put(arr, self.device)
        ticket = MAILBOX.put(val)
        return wc.TensorRequest(
            request_id=request_id,
            tensor=wc.Tensor(tensor_data=ticket.encode(),
                             shape=(), dtype=TICKET_DTYPE_DEV))

    def _ticket(self, request) -> str:
        return bytes(request.tensor.tensor_data).decode()

    def sent_ok(self, request):
        MAILBOX.drop(self._ticket(request))

    cleanup = sent_ok


class ShmSender(Sender):
    """Same-host cross-process hop: one host copy into a shared ring
    slot; the ticket (segment name + layout) rides the control RPC."""

    name = "shm"

    def __init__(self, slots: int = 4):
        self._ring = ShmRing(slots)

    @staticmethod
    def _ticket(request_id: str, seg: str, idx: int, view, shape, dtype
                ) -> wc.TensorRequest:
        meta = json.dumps({"seg": seg, "slot": idx, "nbytes": len(view),
                           "shape": list(shape), "dtype": dtype})
        return wc.TensorRequest(
            request_id=request_id,
            tensor=wc.Tensor(tensor_data=meta.encode(),
                             shape=(), dtype=TICKET_DTYPE_SHM))

    def make_request(self, arr, request_id: str) -> wc.TensorRequest:
        view, shape, dtype, _copied = wc.tensor_payload(arr)
        seg, idx = self._ring.write(view)
        return self._ticket(request_id, seg, idx, view, shape, dtype)

    def make_request_nowait(self, arr, request_id: str
                            ) -> Optional[wc.TensorRequest]:
        view, shape, dtype, _copied = wc.tensor_payload(arr)
        got = self._ring.write_nowait(view)
        if got is None:
            return None
        return self._ticket(request_id, got[0], got[1], view, shape, dtype)

    def _slot(self, request) -> int:
        return json.loads(bytes(request.tensor.tensor_data).decode())["slot"]

    def sent_ok(self, request):
        self._ring.release(self._slot(request))

    cleanup = sent_ok

    def close(self):
        self._ring.close()


# ----------------------------------------------------------------------
# negotiation
# ----------------------------------------------------------------------

def _ladder(transport: str) -> List[str]:
    if transport == "auto":
        return ["device", "shm"]
    if transport in ("device", "shm"):
        return [transport]
    return []


def build_offer(transport: str) -> Tuple[dict, Optional[object]]:
    """-> (offer_dict, probe_shm_or_None). The caller owns the probe
    segment (close+unlink after the handshake)."""
    want = _ladder(transport)
    offer = {"v": 1, "want": want, "proc": PROC_TOKEN,
             "host": host_token(), "nonce": uuid.uuid4().hex}
    probe = None
    if "shm" in want:
        try:
            from multiprocessing import shared_memory

            probe = shared_memory.SharedMemory(
                create=True, size=64,
                name=f"dnn_tpu_probe_{uuid.uuid4().hex[:12]}")
            nb = offer["nonce"].encode()
            probe.buf[: len(nb)] = nb
            probe.buf[len(nb)] = 0
            offer["shm_probe"] = probe.name
        except (OSError, ImportError, ValueError):
            offer["want"] = [w for w in want if w != "shm"]
    return offer, probe


def answer_hello(text: str, *, allow: Tuple[str, ...] = ("device", "shm"),
                 stage: str = "") -> str:
    """Server side of the handshake: pick the highest rung of the
    client's ladder this process can PROVE. Returns the accept/decline
    JSON (the SendMessage confirmation_text)."""
    try:
        offer = json.loads(text)
        want = list(offer.get("want", ()))
        nonce = str(offer.get("nonce", ""))
    except (json.JSONDecodeError, AttributeError, TypeError):
        return json.dumps({"v": 1, "ok": False, "reason": "bad offer"})
    m = obs.metrics()
    if "device" in want and "device" in allow \
            and offer.get("proc") == PROC_TOKEN:
        chosen = "device"
    elif "shm" in want and "shm" in allow and offer.get("shm_probe"):
        # proof, not inference: attach the client's probe segment and
        # read the nonce out of the mapped bytes
        try:
            from multiprocessing import shared_memory

            probe = shared_memory.SharedMemory(name=offer["shm_probe"])
            try:
                raw = bytes(probe.buf[:64]).split(b"\x00", 1)[0].decode()
            finally:
                probe.close()
            if raw != nonce:
                return json.dumps({"v": 1, "ok": False, "relay": True,
                                   "reason": "shm probe nonce mismatch"})
            chosen = "shm"
        except (OSError, ValueError):
            return json.dumps({"v": 1, "ok": False, "relay": True,
                               "reason": "shm probe unreachable"})
    else:
        # declines still advertise the streamed Relay RPC: a cross-host
        # dnn_tpu peer keeps the non-nested schedule on the grpc rung
        return json.dumps({"v": 1, "ok": False, "relay": True,
                           "reason": "no common transport"})
    if m is not None:
        m.inc(labeled("comm.transport_negotiations_total",
                      chosen=chosen, role="server", stage=stage or "-"))
    return json.dumps({"v": 1, "ok": True, "chosen": chosen,
                       "proc": PROC_TOKEN, "nonce": nonce, "relay": True})


def decline_hello(reason: str = "transport negotiation not supported "
                                "on this endpoint") -> str:
    """For dnn_tpu endpoints that opt out (the LM daemon's text front —
    prompt payloads are tiny); the client's ladder lands on grpc."""
    return json.dumps({"v": 1, "ok": False, "reason": reason})


class Negotiated:
    """Outcome of one hop's handshake. `relay_ok` is only meaningful
    when `relay_known` — a hop that never completed a handshake
    (explicit grpc, hello transport failure) probes the Relay RPC
    lazily instead of assuming either way."""

    __slots__ = ("name", "sender", "relay_ok", "relay_known", "reason")

    def __init__(self, name: str, sender: Sender, *, relay_ok: bool = False,
                 relay_known: bool = False, reason: str = ""):
        self.name = name
        self.sender = sender
        self.relay_ok = relay_ok
        self.relay_known = relay_known
        self.reason = reason


def close_probe(probe):
    """Release the handshake's shm probe segment (idempotent)."""
    if probe is not None:
        try:
            probe.close()
            probe.unlink()
        except (OSError, BufferError):
            pass


def conclude(offer: dict, reply_text: str, *, transport: str,
             target: str = "", device=None, shm_slots: int = 4
             ) -> Negotiated:
    """Shared handshake tail: interpret the peer's SendMessage reply for
    `offer`. Raises TransportMisconfigError when an explicit request
    cannot be satisfied; `auto` degrades to grpc with a
    `transport_fallback` flight event (a silent fallback must be
    observable, never invisible)."""
    want = list(offer.get("want", ()))
    if transport != "auto" and not want:
        raise TransportMisconfigError(
            f"transport={transport!r} unavailable on this host "
            f"(shared memory unsupported)")
    try:
        acc = json.loads(reply_text)
        if not isinstance(acc, dict):
            raise TypeError
    except (json.JSONDecodeError, TypeError):
        acc = {"ok": False, "reason": "peer is not transport-aware "
                                      "(reference protocol)"}
    ok = bool(acc.get("ok")) and acc.get("chosen") in want \
        and acc.get("nonce") == offer.get("nonce")
    m = obs.metrics()
    if ok:
        chosen = acc["chosen"]
        sender: Sender = DeviceSender(device) if chosen == "device" \
            else ShmSender(shm_slots)
        if m is not None:
            m.inc(labeled("comm.transport_negotiations_total",
                          chosen=chosen, role="client", target=target))
        return Negotiated(chosen, sender, relay_ok=bool(acc.get("relay")),
                          relay_known=True)
    reason = str(acc.get("reason", "declined"))
    if transport != "auto":
        raise TransportMisconfigError(
            f"transport={transport!r} to {target or 'peer'} refused: "
            f"{reason}")
    obs.flight.record("transport_fallback", target=target,
                      wanted=want, chosen="grpc", reason=reason)
    if m is not None:
        m.inc(labeled("comm.transport_negotiations_total",
                      chosen="grpc", role="client", target=target))
    log.info("transport negotiation with %s fell back to grpc (%s)",
             target or "peer", reason)
    # a dnn_tpu peer's decline still advertises Relay; a reference
    # peer's non-JSON reply leaves relay_ok False (unary chain only)
    return Negotiated("grpc", GrpcSender(), reason=reason,
                      relay_ok=bool(acc.get("relay")), relay_known=True)


def negotiate_over(send_message_fn, *, transport: str = "auto",
                   target: str = "", device=None,
                   shm_slots: int = 4) -> Negotiated:
    """Run the handshake through `send_message_fn(sender_id, text) ->
    reply_text` (sync; the caller owns the RPC plumbing and its
    timeout). Transport-level RPC errors propagate to the caller (the
    endpoint may simply not be up yet — don't cache a verdict)."""
    if transport not in TRANSPORTS:
        raise ValueError(
            f"transport must be one of {TRANSPORTS}, got {transport!r}")
    if transport == "grpc":
        return Negotiated("grpc", GrpcSender(), reason="explicit")
    offer, probe = build_offer(transport)
    try:
        reply = send_message_fn(HELLO_SENDER, json.dumps(offer))
        return conclude(offer, reply, transport=transport, target=target,
                        device=device, shm_slots=shm_slots)
    finally:
        close_probe(probe)


# ----------------------------------------------------------------------
# receiver side: ticket resolution
# ----------------------------------------------------------------------

class TransportHost:
    """Per-server receiver state: answers hellos and resolves inbound
    tickets into arrays. Caches shm attachments per segment name (one
    mmap per segment lifetime, not per message)."""

    #: max cached shm attachments. Senders retire a segment whenever a
    #: payload outgrows its ring slot (fresh name per growth), and the
    #: receiver has no other signal that the old name is dead — an
    #: unbounded cache would strand one unlinked mmap per growth for
    #: the server's lifetime. LRU eviction unmaps stale segments while
    #: comfortably covering live rings (slots x peers << 64).
    MAX_SHM_ATTACHMENTS = 64

    def __init__(self, *, stage: str = ""):
        self.stage = stage
        self._lock = threading.Lock()
        # insertion-ordered: move-to-end on hit makes eviction LRU
        self._shm_attached: Dict[str, object] = {}

    # -- handshake --
    def answer_hello(self, text: str) -> str:
        return answer_hello(text, stage=self.stage)

    # -- data plane --
    @staticmethod
    def is_ticket(msg) -> bool:
        return msg.dtype in TICKET_DTYPES

    def resolve(self, msg):
        """Ticket Tensor -> the activation (device array for device
        hops, zero-copy host view for shm). Fail-loud on unknown or
        stale tickets — a ticket can only legitimately arrive after
        negotiation against THIS process."""
        if msg.dtype == TICKET_DTYPE_DEV:
            ticket = bytes(msg.tensor_data).decode()
            val = MAILBOX.peek(ticket)
            if val is None:
                raise TransportError(
                    f"device ticket {ticket[:8]}... not in this process's "
                    "mailbox (mis-negotiated or already consumed)")
            return val
        if msg.dtype == TICKET_DTYPE_SHM:
            meta = json.loads(bytes(msg.tensor_data).decode())
            name, nbytes = meta["seg"], int(meta["nbytes"])
            with self._lock:
                shm = self._shm_attached.get(name)
                if shm is not None:
                    # LRU refresh
                    self._shm_attached.pop(name)
                    self._shm_attached[name] = shm
                else:
                    from multiprocessing import shared_memory

                    try:
                        shm = shared_memory.SharedMemory(name=name)
                    except OSError as e:
                        raise TransportError(
                            f"shm segment {name} unreachable: {e}") from e
                    self._shm_attached[name] = shm
                    while len(self._shm_attached) > self.MAX_SHM_ATTACHMENTS:
                        _stale_name, stale = next(
                            iter(self._shm_attached.items()))
                        self._shm_attached.pop(_stale_name)
                        try:
                            stale.close()
                        except (OSError, BufferError):
                            pass  # a live view pins it; unmaps with it
            from dnn_tpu.io.serialization import _np_dtype

            dt = _np_dtype(meta["dtype"])
            shape = tuple(int(s) for s in meta["shape"])
            count = int(np.prod(shape)) if shape else 1
            if count * dt.itemsize != nbytes or nbytes > shm.size:
                raise TransportError(
                    f"shm ticket layout invalid: {meta}")
            return np.frombuffer(shm.buf, dtype=dt,
                                 count=count).reshape(shape)
        raise TransportError(f"not a transport ticket: dtype={msg.dtype!r}")

    def close(self):
        with self._lock:
            for shm in self._shm_attached.values():
                try:
                    shm.close()
                except (OSError, BufferError):
                    # a zero-copy view handed to a still-running stage
                    # computation pins the mapping; it unmaps with the
                    # last view
                    pass
            self._shm_attached.clear()


# ----------------------------------------------------------------------
# streamed relay framing (chunking + seq tags on request_id)
# ----------------------------------------------------------------------

# request_id transport segments (opaque to reference peers, stripped
# before the payload reaches any stage/LM handler):
#   s=<seq>           microbatch sequence number within one relay stream
#   c=<i>/<n>         chunk i of n for one oversized inline payload
_SEQ_PREFIX = "s="
_CHUNK_PREFIX = "c="

# Inline gRPC payloads above this ride the Relay stream in chunks (the
# default gRPC message cap is 4 MB; the reference's unary path simply
# breaks there). Tickets are never chunked — they are bytes-tiny.
CHUNK_BYTES = 1 << 20


# Relay response status conventions (the Relay RPC is dnn_tpu-only, so
# these are free to be machine-readable): an `ack:<seq>` frees the
# sender's payload slot for that microbatch; a `res:<seq>:<human text>`
# carries the final result (or an error status string) for it.
_ACK_PREFIX = "ack:"
_RES_PREFIX = "res:"


def ack_status(seq: int) -> str:
    return f"{_ACK_PREFIX}{seq}"


def parse_ack(status: str) -> Optional[int]:
    if status.startswith(_ACK_PREFIX):
        try:
            return int(status[len(_ACK_PREFIX):])
        except ValueError:
            return None
    return None


def result_status(seq: int, human: str) -> str:
    return f"{_RES_PREFIX}{seq}:{human}"


def parse_result(status: str) -> Tuple[Optional[int], str]:
    """-> (seq_or_None, human_status). Tolerates plain statuses (unary
    responses relayed through)."""
    if status.startswith(_RES_PREFIX):
        rest = status[len(_RES_PREFIX):]
        seq_s, _, human = rest.partition(":")
        try:
            return int(seq_s), human
        except ValueError:
            pass
    return None, status


def tag_seq(request_id: str, seq: int, chunk: Optional[Tuple[int, int]] = None
            ) -> str:
    rid = f"{request_id}:{_SEQ_PREFIX}{seq}"
    if chunk is not None:
        rid += f":{_CHUNK_PREFIX}{chunk[0]}/{chunk[1]}"
    return rid


def parse_seq(request_id: str) -> Tuple[str, Optional[int],
                                        Optional[Tuple[int, int]]]:
    """-> (base_request_id, seq_or_None, (chunk_i, chunk_n)_or_None)."""
    base, seq, chunk = [], None, None
    for seg in (request_id or "").split(":"):
        if seg.startswith(_SEQ_PREFIX):
            try:
                seq = int(seg[len(_SEQ_PREFIX):])
                continue
            except ValueError:
                pass
        if seg.startswith(_CHUNK_PREFIX):
            try:
                i, n = seg[len(_CHUNK_PREFIX):].split("/")
                chunk = (int(i), int(n))
                continue
            except ValueError:
                pass
        base.append(seg)
    return ":".join(base), seq, chunk


_DL_PREFIX = "dl="


def tag_deadline(request_id: str, remaining_s: float) -> str:
    """Append (or replace) the propagated-deadline segment: the
    REMAINING budget, in seconds, the sender grants the rest of the
    pipeline. Rides the existing request_id field like the trace tag
    (`tr=`) and the relay segments (`s=`/`c=`) — opaque to reference
    peers, skipped by parse_gen_options — so downstream hops can cap
    their own retry/forward budgets to it instead of over-spending a
    nearly-dead deadline (comm/client.py, comm/service.py,
    runtime/lm_server.py all honor it)."""
    return (f"{strip_deadline(request_id)}:"
            f"{_DL_PREFIX}{max(float(remaining_s), 0.001):.3f}")


def extract_deadline(request_id: str) -> Optional[float]:
    """The inbound `dl=` budget in seconds, or None when the sender
    propagated none (reference clients)."""
    for seg in (request_id or "").split(":"):
        if seg.startswith(_DL_PREFIX):
            try:
                return float(seg[len(_DL_PREFIX):])
            except ValueError:
                return None
    return None


def strip_deadline(request_id: str) -> str:
    if _DL_PREFIX not in (request_id or ""):
        return request_id
    return ":".join(seg for seg in request_id.split(":")
                    if not seg.startswith(_DL_PREFIX))


def split_requests(request: wc.TensorRequest, seq: int,
                   chunk_bytes: int = CHUNK_BYTES) -> List[wc.TensorRequest]:
    """One logical send -> the Relay stream's frames. Small payloads and
    tickets pass through whole (one frame); oversized inline payloads
    split into chunk frames (zero-copy memoryview slices — chunking adds
    no host copies on the send side)."""
    t = request.tensor
    data = t.tensor_data
    if t.dtype in TICKET_DTYPES or len(data) <= chunk_bytes:
        return [wc.TensorRequest(request_id=tag_seq(request.request_id, seq),
                                 tensor=t)]
    view = memoryview(data)
    n = (len(view) + chunk_bytes - 1) // chunk_bytes
    out = []
    for i in range(n):
        part = view[i * chunk_bytes:(i + 1) * chunk_bytes]
        # chunk 0 carries the logical header (shape/dtype/crc); later
        # chunks carry payload only
        frame_t = wc.Tensor(tensor_data=part,
                            shape=t.shape if i == 0 else (),
                            dtype=t.dtype if i == 0 else "",
                            crc32c=t.crc32c if i == 0 else None)
        out.append(wc.TensorRequest(
            request_id=tag_seq(request.request_id, seq, (i, n)),
            tensor=frame_t))
    return out


class ChunkAssembler:
    """Receiver-side reassembly for the Relay stream: in-order chunks
    of one sequence are filled into a single preallocated buffer (ONE
    copy total — the reassembly itself)."""

    def __init__(self):
        self._cur: Optional[dict] = None

    def add(self, request: wc.TensorRequest
            ) -> Optional[Tuple[str, int, wc.Tensor]]:
        """-> (base_request_id, seq, whole_tensor) when a logical
        payload completes, else None."""
        if _chaos_inject.perturb_relay():
            # injected relay-frame drop: the frame vanishes in
            # "transit" — the sender's seq never answers, surfacing as
            # an explicit stream error at the client (never a silent
            # loss; relay_corrupt raises PayloadCorruptError here
            # instead, the per-item DATA_LOSS path)
            return None
        base, seq, chunk = parse_seq(request.request_id)
        seq = 0 if seq is None else seq
        t = request.tensor
        if chunk is None:
            return base, seq, t
        i, n = chunk
        if i == 0:
            self._cur = {"base": base, "seq": seq, "n": n,
                         "shape": list(t.shape), "dtype": t.dtype,
                         "crc": t.crc32c, "parts": [],
                         "next": 0}
        cur = self._cur
        if cur is None or cur["seq"] != seq or cur["next"] != i:
            raise TransportError(
                f"relay chunk out of order: got {i}/{n} for seq {seq}")
        cur["parts"].append(t.tensor_data)
        cur["next"] += 1
        if cur["next"] < cur["n"]:
            return None
        self._cur = None
        whole = bytearray(sum(len(p) for p in cur["parts"]))
        off = 0
        for p in cur["parts"]:
            whole[off:off + len(p)] = p
            off += len(p)
        return cur["base"], seq, wc.Tensor(
            tensor_data=memoryview(whole), shape=cur["shape"],
            dtype=cur["dtype"], crc32c=cur["crc"])
