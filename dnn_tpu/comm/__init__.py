from dnn_tpu.comm.service import StageServer, serve_stage
from dnn_tpu.comm.client import NodeClient

__all__ = ["StageServer", "serve_stage", "NodeClient"]
