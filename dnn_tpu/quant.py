"""Weight-only int8 quantization for serving.

The reference has no quantization story at all — its weights ride the wire
and the forward at full precision (/root/reference/node.py:294-325 loads the
f32 `.pth`; node_service.proto:26-30 ships raw f32 bytes). For a serving
framework that is a real capability gap: autoregressive decode reads every
weight once per generated token, so decode throughput is bounded by
HBM bandwidth, not FLOPs. Halving (bf16) or quartering (int8) the bytes
per weight is the direct lever.

Scheme (the standard weight-only recipe, TPU-shaped):

  * **Symmetric per-output-channel int8.** For a (in, out) kernel, one
    f32 scale per output column: ``scale = max|W[:, j]| / 127``,
    ``q = round(W / scale)``. No zero-points — symmetric quant keeps the
    matmul a plain dot (no cross terms), and transformer weights are
    near-zero-mean so asymmetry buys nothing.
  * **Scales stay out of the matmul.** Per-channel scales commute with
    the contraction, so the apply path computes ``(x @ q) * scale`` —
    the dequant is a cheap epilogue on the (small) output, never a
    materialized f32 copy of the weight. See `_linear_int8` in
    dnn_tpu/ops/nn.py.
  * **Quantized params keep the pytree shape.** A quantized linear is
    ``{"q": int8, "scale": f32, "bias"?}`` in place of
    ``{"kernel", "bias"?}``; everything else (layer norms, embeddings,
    biases) is untouched. Because every matmul in the framework funnels
    through `ops.nn.linear`, the same quantized tree drops into
    `make_apply*`, the KV-cache decoders, the continuous-batching server,
    and the stage-sharded pipeline with zero per-path changes.
  * **Stacked layouts quantize per layer.** A stacked kernel
    (L, in, out) gets (L, 1, out) scales; `lax.scan` slices both in
    lockstep, so each layer sees its own (1, out) scales.

What is deliberately NOT here: activation quantization (int8 x int8 with
dynamic ranges) — it changes numerics class and needs calibration data;
weight-only at bf16 activations is the accuracy-free point on the curve.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "quantize_tensor",
    "quantize_tensor_int4",
    "dequantize_tensor",
    "quantize_linear",
    "quantize_tree",
    "quantize_gpt",
    "param_bytes",
]


def quantize_tensor(w, *, axis: int = -2) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization of `w` with scales reduced over `axis`
    (kept as size-1, so q * scale broadcasts back to w's shape).

    Default axis=-2 is the contraction (input) dim of an (in, out) or
    stacked (L, in, out) kernel -> per-output-channel (and per-layer)
    scales."""
    w = jnp.asarray(w)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_tensor(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


INT4_GROUP = 64  # input channels per int4 scale group (the GPTQ/AWQ-class
# default: small enough that one outlier only poisons 64 rows' worth of
# resolution, large enough that scales stay <1% of the weight bytes)


def quantize_tensor_int4(w, *, group: int = INT4_GROUP):
    """GROUP-WISE symmetric int4: one f32 scale per (group of `group`
    input channels, output channel). Per-channel scales are enough at
    int8 (127 levels absorb a column's dynamic range) but not at int4 —
    7 levels against a whole column's max quantizes typical weights to
    ~9% relative error, while 64-row groups cut that ~3x (measured in
    tests/test_int4.py). Storage is NATIVE jnp.int4 (XLA S4): on TPU the
    HBM layout packs two values per byte and the s4->bf16 convert fuses
    into the matmul's operand read, the same fusion the int8 path rides.

    Returns (q (..., in, out) int4, scale (..., in/group, out) f32).
    Group-wise scales do NOT commute with the full contraction — the
    apply path (ops.nn._linear_int4) runs one batched dot per group and
    applies scales before the group-sum, still epilogue-only math."""
    w = jnp.asarray(w)
    in_dim = w.shape[-2]
    if in_dim % group:
        raise ValueError(
            f"input dim {in_dim} not divisible by int4 group {group}")
    g_count = in_dim // group
    wg = w.reshape(*w.shape[:-2], g_count, group, w.shape[-1])
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 7.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(wg.astype(jnp.float32) / scale), -7, 7)
    return (q.astype(jnp.int4).reshape(w.shape), scale[..., 0, :])


def quantize_linear(params, *, bits: int = 8, int4_group: int = INT4_GROUP):
    """{"kernel", "bias"?} -> {"q", "scale", "bias"?} (see ops.nn.linear).
    bits=4 selects the group-wise int4 scheme (quantize_tensor_int4);
    ops.nn.linear dispatches on q's dtype."""
    if bits == 4:
        q, scale = quantize_tensor_int4(params["kernel"], group=int4_group)
    elif bits == 8:
        q, scale = quantize_tensor(params["kernel"])
    else:
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    out = {"q": q, "scale": scale}
    if "bias" in params:
        out["bias"] = params["bias"]
    return out


def _default_should_quantize(path: str, kernel) -> bool:
    # matmul kernels only (2D, or 3D layer-stacked); convs (4D HWIO) and
    # tiny projections are left alone — no bandwidth to win there. MoE
    # routers stay f32: routing *decisions* must not flip under
    # quantization noise (dnn_tpu/parallel/moe.py computes them in f32
    # for the same reason), and a router is <0.1% of bytes anyway.
    if path.endswith("/router"):
        return False
    return kernel.ndim in (2, 3) and min(kernel.shape[-2:]) >= 32


def quantize_tree(params, *, should_quantize: Optional[Callable] = None,
                  bits: int = 8, int4_group: int = INT4_GROUP):
    """Walk a parameter pytree of nested dicts; replace every
    {"kernel": ...} linear dict the predicate accepts with its int8 form.

    Works on raw `gpt.init` trees, `prepare_stacked` trees (the stacked
    blocks quantize per-layer), and per-stage pipeline shards alike —
    anything made of nested dicts. MoE expert stacks (a dict holding raw
    (E, in, out) `wi`/`wo` arrays, dnn_tpu/parallel/moe.py:init_moe) are
    recognized structurally and quantized in place — int8 `wi`/`wo` with
    per-(expert, channel) `wi_scale`/`wo_scale` keys the expert FFN
    dequantizes in its epilogue; the router is left f32 (see
    `_default_should_quantize`). Key names and leading-E shapes are
    preserved, so the EP sharding specs apply unchanged."""
    pred = should_quantize or _default_should_quantize

    def walk(node, path):
        if isinstance(node, dict):
            if "kernel" in node and hasattr(node["kernel"], "ndim"):
                if pred(path, node["kernel"]):
                    return quantize_linear(node, bits=bits,
                                           int4_group=int4_group)
                return node
            # MoE expert stacks (2-layer wi/wo or gated Mixtral
            # wg/wu/wd) stay int8: their epilogue dequant is
            # per-(expert, channel) (parallel/moe.py) and the routed FFN
            # has no group-wise apply path — int4 here would need its
            # own dispatch for <0.2x the win int4 buys the dense kernels
            # (experts are already 1/E-sharded per device). ndim 3 is
            # the raw (E, in, out) stack, 4 the prepare_stacked form
            # with its leading L — quantize_tensor's axis=-2 scale is
            # per-(..., channel) either way.
            for ks in (("wi", "wo"), ("wg", "wu", "wd")):
                # dtype/scale guards make this IDEMPOTENT like the
                # kernel->q rename: re-quantizing an int8 stack would
                # overwrite its real scales with ~1.0 (amax of int8
                # values) and silently corrupt the model
                if all(k in node and hasattr(node[k], "ndim")
                       and node[k].ndim in (3, 4)
                       and node[k].dtype != jnp.int8
                       and (k + "_scale") not in node for k in ks):
                    out = {k: walk(v, f"{path}/{k}")
                           for k, v in node.items() if k not in ks}
                    for kk in ks:
                        out[kk], out[kk + "_scale"] = quantize_tensor(
                            node[kk])
                    return out
            return {k: walk(v, f"{path}/{k}") for k, v in node.items()}
        return node

    return walk(params, "")


def quantize_gpt(prepared, *, quantize_head: bool = True, bits: int = 8,
                 int4_group: int = INT4_GROUP):
    """Quantize a GPT parameter tree (raw or prepare_stacked form).

    Quantizes the qkv/proj/fc/mlp-proj kernels (and optionally lm_head);
    embeddings, layer norms, and biases stay f32 — together they are <1%
    of bytes but carry the model's dynamic range. `bits=4` selects the
    group-wise int4 scheme (quantize_tensor_int4): half the weight bytes
    of int8 again, at a measured (not free) accuracy cost — compare
    logits on a held-out batch before serving int4."""

    def pred(path, kernel):
        if not _default_should_quantize(path, kernel):
            return False
        if "lm_head" in path:
            return quantize_head
        return True

    return quantize_tree(prepared, should_quantize=pred, bits=bits,
                         int4_group=int4_group)


def param_bytes(tree) -> int:
    """Total HBM bytes of all array leaves (for compression-ratio
    checks). int4 leaves count 0.5 bytes/element — the TPU HBM layout
    packs two S4 values per byte (host-side numpy views pad to one byte,
    so dtype.itemsize would double-count them). Delegates to the one
    canonical pricing walk (utils/flops.tree_weight_bytes — also the
    serving goodput MBU denominator, so the two can never drift)."""
    from dnn_tpu.utils.flops import tree_weight_bytes

    return int(tree_weight_bytes(tree))
