"""Mesh/GSPMD sharding-safety analyzer: rules SHD001-SHD009.

ROADMAP items 1 and 2 put the mesh code and the zero1 paths on real
chips; the classic failure modes there are SILENT — an accidentally
replicated weight tree erases the ZeRO memory win ("Automatic
Cross-Replica Sharding of Weight Update", 2004.13336), and an implicit
all-gather XLA inserts to repair mismatched shardings turns
tensor-parallel decode into a comms-bound crawl (the pjit pitfalls of
2204.06514). This module is the instrument built BEFORE those PRs, in
three connected pieces riding the findings/fingerprint/baseline/SARIF
machinery of analysis/lint.py and analysis/program.py:

  1. AST rules SHD001-006 (`check_source`, merged into `lint_source`;
     pure ast, NO jax import at module scope so the lint path stays
     importable anywhere):
       SHD001  hard-coded device-count arithmetic — `len(jax.devices())
               // 2`-style code breaks the moment a replica gets a
               different chip count; mesh axis sizes should flow.
       SHD002  mesh-axis-name string literal drifting from the
               declaring Mesh/make_mesh site (module-scoped resolution,
               the CON004 discipline applied to axis names).
       SHD003  shard_map with sharded in_specs whose out_specs are
               missing/`P()`-everything while the mapped body issues NO
               collective — the output is either mis-declared or an
               implicit full gather.
       SHD004  host materialization (`.item()`, `np.asarray`, host
               callbacks) reachable from an spmd-mapped body through a
               same-module call chain (the CON001 closure engine; the
               direct tainted case is TPU002's).
       SHD005  per-host RNG divergence: a PRNGKey created inside an
               spmd body and consumed without `fold_in` of the axis
               index — every rank draws identical "randomness".
       SHD006  donation of a sharded argument whose declared donor
               sharding matches no output sharding — the donation
               silently dies (XLA only aliases matching layouts).

  2. A device-free sharded-program audit (`run_shard_audit`) over the
     REAL programs — the zero1 train step, llama dp x tp, the stacked
     pipeline placement, the expert-parallel moe ffn — lowered once on
     CPU with forced virtual devices:
       SHD007  allocation-sized all-gather: any collective in the
               OPTIMIZED HLO whose result is weight-tree-sized is the
               accidental-replication repair (threshold priced via
               utils/flops.tree_weight_bytes).
       SHD008  per-shard memory bill: expected per-device bytes from
               the declared PartitionSpecs vs the actual buffer sizes
               the program lowered — a supposedly-sharded leaf that
               lowers replicated fails.
       SHD009  sharding-contract mismatch: the compiled program's
               input/output sharding attributes disagree with the
               contract declared next to the code.
     Donation-aliasing under NamedSharding rides the existing PRG003
     (hlo_audit.count_aliased), and branch-collective consistency is
     the mesh-axis-aware PRG001 (analysis/program.py).

  3. The sharding-contract API: `@contract(name)` registers a
     PartitionSpec builder NEXT TO the code it describes (train.py's
     zero1/llama specs, pipeline.py's stage placement); the audit
     builds the real program from the contract and verifies the
     compiled sharding attributes match the declaration — so the
     upcoming GSPMD serving PR ships with its contract checked in CI
     from day one.

CPU-only by design: jit signatures and GSPMD partitioning decisions are
backend-independent, so a bill/contract verdict computed on 8 virtual
host devices transfers to a TPU slice of the same mesh shape.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from dnn_tpu.analysis.findings import Finding

__all__ = [
    "check_source", "contract", "get_contract", "contract_names",
    "memory_bill", "collective_allocation_findings", "contract_findings",
    "audit_zero1_train", "audit_llama_dp_tp", "audit_stacked_pipeline",
    "audit_moe_ep", "run_shard_audit",
]

# ----------------------------------------------------------------------
# sharding-contract registry
# ----------------------------------------------------------------------

# name -> PartitionSpec-tree builder, registered next to the code it
# describes. Modules register at import; the audit imports them lazily
# (shardcheck itself must stay jax-free at module scope).
_CONTRACTS: Dict[str, Callable] = {}

# modules whose import populates the registry (grow this list when a
# new subsystem declares a contract)
_CONTRACT_MODULES = ("dnn_tpu.train", "dnn_tpu.parallel.pipeline")


def contract(name: str):
    """Decorator: register `fn` as the sharding contract `name`. The
    builder returns the INTENDED PartitionSpec tree for its subject
    (given shape pytrees / meshes as its own signature demands); the
    audit verifies the compiled program matches it. Re-registration
    overwrites (module reload)."""

    def register(fn: Callable) -> Callable:
        _CONTRACTS[name] = fn
        return fn

    return register


def _load_contracts():
    import importlib

    for mod in _CONTRACT_MODULES:
        importlib.import_module(mod)


def get_contract(name: str) -> Callable:
    if name not in _CONTRACTS:
        _load_contracts()
    return _CONTRACTS[name]


def contract_names() -> List[str]:
    _load_contracts()
    return sorted(_CONTRACTS)


# ----------------------------------------------------------------------
# AST pass: SHD001-006
# ----------------------------------------------------------------------

_DEVICE_COUNT_CALLS = {"device_count", "local_device_count"}
_DEVICE_LIST_CALLS = {"devices", "local_devices"}
_ARITH_OPS = (ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Sub, ast.Pow)
_SPMD_WRAPPERS = {"shard_map", "pmap"}
_COLLECTIVE_NAMES = {
    "psum", "ppermute", "all_gather", "all_to_all", "psum_scatter",
    "pmean", "pmin", "pmax", "pbroadcast", "all_gather_invariant",
}
_AXIS_KWARGS = {"axis_name", "axis_names"}
_HOST_MAT_METHODS = {"item", "tolist"}
_HOST_MAT_NP = {"asarray", "array", "ascontiguousarray", "copy", "save"}
_HOST_CALLBACKS = {"pure_callback", "io_callback", "call_host",
                   "device_get"}
_KEY_CTORS = {"PRNGKey", "key"}
_KEY_CONSUMERS = {
    "normal", "uniform", "split", "bernoulli", "categorical", "randint",
    "truncated_normal", "gumbel", "choice", "permutation", "bits",
    "exponential", "laplace", "poisson",
}


def _callee(call: ast.Call) -> str:
    try:
        return ast.unparse(call.func)
    except Exception:  # pragma: no cover — exotic nodes
        return ""


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _is_device_count_expr(node) -> bool:
    """`jax.device_count()`, `jax.local_device_count()`, or
    `len(jax.devices())` (the count, not the list)."""
    if not isinstance(node, ast.Call):
        return False
    name = _last(_callee(node))
    if name in _DEVICE_COUNT_CALLS:
        return True
    if name == "len" and node.args and isinstance(node.args[0], ast.Call):
        return _last(_callee(node.args[0])) in _DEVICE_LIST_CALLS
    return False


def _p_calls(node) -> List[ast.Call]:
    """Every P(...) / PartitionSpec(...) call in a subtree."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and \
                _last(_callee(n)) in ("P", "PartitionSpec"):
            out.append(n)
    return out


def _p_axis_literals(node) -> Set[str]:
    """String-literal axis names inside the P(...) calls of a subtree."""
    axes: Set[str] = set()
    for p in _p_calls(node):
        for a in list(p.args) + [kw.value for kw in p.keywords]:
            for c in ast.walk(a):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    axes.add(c.value)
    return axes


def _spec_is_sharded(node) -> bool:
    """True when a specs expression carries at least one named axis —
    a string literal inside a P(...) call, or a non-literal P argument
    (an axis-name constant like DATA_AXIS counts as sharded)."""
    for p in _p_calls(node):
        for a in list(p.args) + [kw.value for kw in p.keywords]:
            if isinstance(a, ast.Constant):
                if isinstance(a.value, str):
                    return True
            else:
                return True  # Name/attribute axis: assume sharded
    return False


def _spec_all_replicated(node) -> Optional[bool]:
    """True when EVERY P(...) in the expression is an argument-free
    `P()` and the expression holds nothing but those literals (tuples/
    lists/None). None (undecidable) when non-P names appear."""
    ps = _p_calls(node)
    if not ps:
        return None
    if any(p.args or p.keywords for p in ps):
        return False
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id not in ("P", "PartitionSpec"):
            return None
        if isinstance(n, ast.Attribute):
            return None
    return True


class _SpmdIndex(ast.NodeVisitor):
    """spmd-mapped function names/nodes for one module: defs decorated
    with shard_map/pmap, names passed to them, and (via the checker)
    everything lexically nested inside."""

    def __init__(self):
        self.names: Set[str] = set()
        self.nodes: Set[int] = set()

    def visit_Call(self, node: ast.Call):
        if _last(_callee(node)) in _SPMD_WRAPPERS:
            for a in node.args:
                targets = a.elts if isinstance(a, (ast.List, ast.Tuple)) \
                    else [a]
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.names.add(t.id)
        self.generic_visit(node)

    def _visit_def(self, node):
        for dec in node.decorator_list:
            name = _last(_callee(dec)) if isinstance(dec, ast.Call) else \
                _last(ast.unparse(dec)) if isinstance(
                    dec, (ast.Name, ast.Attribute)) else ""
            if name in _SPMD_WRAPPERS:
                self.nodes.add(id(node))
        self.generic_visit(node)

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def


def _walk_own(fn):
    """A function's own body, excluding nested def subtrees (they are
    judged as their own functions) — the concurrency-pass discipline."""
    stack = [fn]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield child
            stack.append(child)


def _walk_functions(tree):
    stack = [(tree, [])]
    while stack:
        node, anc = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, anc
                stack.append((child, anc + [child]))
            else:
                stack.append((child, anc))


class _ShardChecker:
    def __init__(self, tree: ast.Module, path: str, src_lines: List[str]):
        self.tree = tree
        self.path = path
        self.src_lines = src_lines
        self.findings: List[Finding] = []
        self._flagged: Set[Tuple[str, int]] = set()
        self.spmd = _SpmdIndex()
        self.spmd.visit(tree)
        self.module_defs: Dict[str, ast.AST] = {}
        for fn, _anc in _walk_functions(tree):
            self.module_defs.setdefault(fn.name, fn)
        self.declared_axes = self._declared_axes()
        self.host_fns = self._host_closure()

    # -- plumbing ------------------------------------------------------

    def _flag(self, rule: str, node, message: str):
        line = getattr(node, "lineno", 0)
        if (rule, line) in self._flagged:
            return
        self._flagged.add((rule, line))
        snippet = ""
        if 0 < line <= len(self.src_lines):
            snippet = self.src_lines[line - 1].strip()
        self.findings.append(Finding(rule=rule, path=self.path, line=line,
                                     message=message, snippet=snippet))

    def _is_spmd_fn(self, fn, ancestors) -> bool:
        return any(id(n) in self.spmd.nodes or n.name in self.spmd.names
                   for n in ancestors + [fn])

    # -- SHD002 index: axis names declared at Mesh/make_mesh sites -----

    def _declared_axes(self) -> Set[str]:
        axes: Set[str] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _last(_callee(node))
            if name == "Mesh":
                cands = list(node.args[1:2]) + [
                    kw.value for kw in node.keywords
                    if kw.arg in ("axis_names", "axis_name")]
                for c in cands:
                    elts = c.elts if isinstance(c, (ast.Tuple, ast.List)) \
                        else [c]
                    for e in elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            axes.add(e.value)
            elif name == "make_mesh":
                for c in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    if isinstance(c, ast.Dict):
                        for k in c.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                axes.add(k.value)
                    elif isinstance(c, (ast.Tuple, ast.List)):
                        for e in c.elts:
                            if isinstance(e, ast.Constant) and \
                                    isinstance(e.value, str):
                                axes.add(e.value)
        return axes

    # -- SHD004 index: host-materializing closure ----------------------

    def _directly_materializes(self, fn) -> bool:
        for node in _walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = _callee(node)
            name = _last(callee)
            if name in _HOST_CALLBACKS:
                return True
            if name in _HOST_MAT_NP and \
                    callee.split(".")[0] in ("np", "numpy"):
                return True
            if name in _HOST_MAT_METHODS and \
                    isinstance(node.func, ast.Attribute):
                return True
        return False

    def _called_names(self, fn) -> Set[str]:
        out: Set[str] = set()
        for node in _walk_own(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name):
                    out.add(f.id)
                elif isinstance(f, ast.Attribute) and isinstance(
                        f.value, ast.Name) and f.value.id == "self":
                    out.add(f.attr)
        return out

    def _host_closure(self) -> Set[str]:
        """Module function names whose bodies reach host materialization
        (direct, or through same-module calls) — the CON001 fixpoint
        applied to device->host transfers."""
        host = {name for name, fn in self.module_defs.items()
                if self._directly_materializes(fn)}
        changed = True
        while changed:
            changed = False
            for name, fn in self.module_defs.items():
                if name in host:
                    continue
                if self._called_names(fn) & host:
                    host.add(name)
                    changed = True
        return host

    # -- driver --------------------------------------------------------

    def run(self) -> List[Finding]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.BinOp):
                self._check_shd001(node)
            elif isinstance(node, ast.Call):
                name = _last(_callee(node))
                if name in _SPMD_WRAPPERS or name in ("pjit", "jit"):
                    self._check_shd003(node, name)
                    self._check_shd006(node)
                if self.declared_axes:
                    self._check_shd002(node)
        for fn, ancestors in _walk_functions(self.tree):
            if self._is_spmd_fn(fn, ancestors):
                self._check_shd004(fn)
                self._check_shd005(fn)
        return self.findings

    # -- SHD001 --------------------------------------------------------

    def _check_shd001(self, node: ast.BinOp):
        if not isinstance(node.op, _ARITH_OPS):
            return
        pairs = ((node.left, node.right), (node.right, node.left))
        for count_side, other in pairs:
            if _is_device_count_expr(count_side) and isinstance(
                    other, ast.Constant) and isinstance(other.value, int):
                self._flag(
                    "SHD001", node,
                    "arithmetic on a global device count with a "
                    "hard-coded integer — breaks the moment a replica "
                    "gets a different chip count; size from "
                    "mesh.shape[axis] instead")
                return

    # -- SHD002 --------------------------------------------------------

    def _axis_use_literals(self, call: ast.Call) -> List[ast.Constant]:
        """String literals used AS AXIS NAMES at this call site."""
        name = _last(_callee(call))
        out: List[ast.Constant] = []

        def strs(node):
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
                else [node]
            return [e for e in elts
                    if isinstance(e, ast.Constant) and
                    isinstance(e.value, str)]

        if name in ("P", "PartitionSpec"):
            for a in call.args:
                out.extend(strs(a))
        elif name in _COLLECTIVE_NAMES and len(call.args) >= 2:
            out.extend(strs(call.args[1]))
        elif name == "axis_index" and call.args:
            out.extend(strs(call.args[0]))
        for kw in call.keywords:
            if kw.arg in _AXIS_KWARGS:
                out.extend(strs(kw.value))
        return out

    def _check_shd002(self, call: ast.Call):
        for lit in self._axis_use_literals(call):
            if lit.value not in self.declared_axes:
                self._flag(
                    "SHD002", lit,
                    f"axis name {lit.value!r} does not match any axis "
                    "declared at this module's Mesh/make_mesh site(s) "
                    f"({sorted(self.declared_axes)}) — a drifted axis "
                    "literal fails at runtime on the real mesh (or "
                    "silently no-ops a collective)")

    # -- SHD003 --------------------------------------------------------

    def _resolve_mapped(self, node) -> Optional[ast.AST]:
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            return self.module_defs.get(node.id)
        return None

    def _body_has_collective(self, fn) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) and \
                    _last(_callee(n)) in _COLLECTIVE_NAMES:
                return True
        return False

    def _check_shd003(self, call: ast.Call, wrapper: str):
        kwargs = {kw.arg: kw.value for kw in call.keywords}
        in_specs = kwargs.get("in_specs") or kwargs.get("in_shardings")
        out_specs = kwargs.get("out_specs") or kwargs.get("out_shardings")
        if in_specs is None or not _spec_is_sharded(in_specs):
            return
        if out_specs is None:
            if wrapper not in _SPMD_WRAPPERS:
                return  # jit/pjit: GSPMD infers outputs; omission is fine
            replicated = True  # shard_map without out_specs = undeclared
        else:
            replicated = _spec_all_replicated(out_specs)
        if not replicated:
            return
        mapped = self._resolve_mapped(call.args[0]) if call.args else None
        if mapped is None or self._body_has_collective(mapped):
            return  # a reduction to replicated via psum etc. is legit
        self._flag(
            "SHD003", call,
            f"{wrapper} consumes sharded operands but declares every "
            "output replicated (missing/P()-everything out specs) with "
            "no collective in the mapped body — either the outputs are "
            "mis-declared or the program pays an implicit full gather")

    # -- SHD004 --------------------------------------------------------

    def _check_shd004(self, fn):
        for node in _walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            called = None
            if isinstance(f, ast.Name):
                called = f.id
            elif isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name) and f.value.id == "self":
                called = f.attr
            if called in self.host_fns and called != fn.name:
                self._flag(
                    "SHD004", node,
                    f"`{called}` reaches host materialization "
                    "(.item()/np.*/host callback) and is called from an "
                    "spmd-mapped body — a per-rank device->host sync "
                    "inside the mapped program; keep the chain on "
                    "device (jnp.*)")

    # -- SHD005 --------------------------------------------------------

    def _is_key_ctor(self, node) -> bool:
        return isinstance(node, ast.Call) and \
            _last(_callee(node)) in _KEY_CTORS and \
            "random" in _callee(node)

    def _fold_has_axis_index(self, call: ast.Call) -> bool:
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(a):
                if isinstance(n, ast.Call) and \
                        _last(_callee(n)) == "axis_index":
                    return True
        return False

    def _check_shd005(self, fn):
        unfolded: Set[str] = set()
        for node in _walk_own(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                call = node.value
                name = _last(_callee(call))
                is_unfolded_key = self._is_key_ctor(call)
                if name == "fold_in":
                    # fold_in of the axis index decorrelates ranks; a
                    # fold of anything else keeps every rank identical
                    feeds_key = any(
                        self._is_key_ctor(a) or (
                            isinstance(a, ast.Name) and a.id in unfolded)
                        for a in call.args)
                    is_unfolded_key = feeds_key and \
                        not self._fold_has_axis_index(call)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        if is_unfolded_key:
                            unfolded.add(t.id)
                        else:
                            unfolded.discard(t.id)
            if isinstance(node, ast.Call):
                callee = _callee(node)
                if "random" in callee and \
                        _last(callee) in _KEY_CONSUMERS:
                    for a in node.args:
                        hit = (isinstance(a, ast.Name) and
                               a.id in unfolded) or self._is_key_ctor(a)
                        if hit:
                            self._flag(
                                "SHD005", node,
                                "PRNG key created inside an spmd body "
                                "is consumed without fold_in of the "
                                "axis index — every rank draws the "
                                "SAME 'random' values; "
                                "fold_in(key, lax.axis_index(axis)) "
                                "first")
                            if isinstance(a, ast.Name):
                                unfolded.discard(a.id)

    # -- SHD006 --------------------------------------------------------

    def _spec_strings(self, node) -> List[str]:
        """Canonical per-position spec strings of a shardings literal:
        one entry per top-level element (tuple/list), else a single
        entry. '' when a position holds no P(...) literal."""
        elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
            else [node]
        out = []
        for e in elts:
            ps = _p_calls(e)
            out.append(ast.unparse(ps[0]).replace("PartitionSpec", "P")
                       if ps else "")
        return out

    def _check_shd006(self, call: ast.Call):
        kwargs = {kw.arg: kw.value for kw in call.keywords}
        donate = kwargs.get("donate_argnums")
        ins = kwargs.get("in_shardings") or kwargs.get("in_specs")
        outs = kwargs.get("out_shardings") or kwargs.get("out_specs")
        if donate is None or ins is None or outs is None:
            return
        try:
            idxs = ast.literal_eval(donate)
        except (ValueError, SyntaxError):
            return
        if isinstance(idxs, int):
            idxs = (idxs,)
        in_strs = self._spec_strings(ins)
        out_strs = [s for s in self._spec_strings(outs) if s]
        if not out_strs:
            return
        for i in idxs:
            if not isinstance(i, int) or i >= len(in_strs):
                continue
            spec = in_strs[i]
            if not spec or spec == "P()":
                continue  # replicated donors alias against anything
            if spec not in out_strs:
                self._flag(
                    "SHD006", call,
                    f"donated argument {i} is sharded {spec} but no "
                    "declared output carries that sharding — XLA only "
                    "aliases matching layouts, so this donation "
                    "silently dies and the step pays a full copy")


def check_source(src: str, path: str = "<string>") -> List[Finding]:
    """SHD001-006 over one module's source. Called by lint_source (the
    merged lint walk); returns raw findings — occurrence assignment
    happens in the caller."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []  # lint.py already reports TPU000
    return _ShardChecker(tree, path, src.splitlines()).run()


# ----------------------------------------------------------------------
# program audit: SHD007-009 over the real sharded programs
# ----------------------------------------------------------------------

def _shard_nbytes(sharding, shape, dtype) -> int:
    import numpy as np

    shard = sharding.shard_shape(tuple(shape))
    n = 1
    for d in shard:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def _leaf_paths(tree, is_leaf=None):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def memory_bill(shapes, declared_specs, actual_shardings, mesh, *,
                where: str = "<program>", label: str = "params"
                ) -> Tuple[dict, List[Finding]]:
    """SHD008: the static per-shard memory bill. For every leaf, the
    expected per-device bytes follow from the DECLARED PartitionSpec
    (NamedSharding.shard_shape on the global shape); the actual bytes
    follow from the sharding the compiled program assigned. A leaf whose
    declaration shards it but whose program replicates it erases the
    memory win the spec promised — that is the 2004.13336 failure mode,
    caught on paper."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec_leaves = dict(_leaf_paths(
        declared_specs, is_leaf=lambda x: isinstance(x, P)))
    findings: List[Finding] = []
    expected_total = actual_total = global_total = 0
    mismatches: List[dict] = []
    n_leaves = 0
    for (path, leaf), (_, actual) in zip(
            _leaf_paths(shapes), _leaf_paths(actual_shardings)):
        n_leaves += 1
        spec = spec_leaves.get(path, P())
        declared = NamedSharding(mesh, spec)
        exp = _shard_nbytes(declared, leaf.shape, leaf.dtype)
        act = _shard_nbytes(actual, leaf.shape, leaf.dtype)
        import numpy as np

        glob = int(np.prod(leaf.shape, dtype=np.int64) or 1) * \
            np.dtype(leaf.dtype).itemsize
        expected_total += exp
        actual_total += act
        global_total += glob
        if act != exp:
            entry = {"leaf": path, "spec": str(spec),
                     "expected_bytes": exp, "actual_bytes": act,
                     "global_bytes": glob}
            mismatches.append(entry)
            if act >= glob and exp < glob:
                msg = (f"leaf {path} declared {spec} lowers REPLICATED "
                       f"({act} B/device vs declared {exp} B) — the "
                       "sharding annotation bought no memory")
            else:
                msg = (f"leaf {path} per-device bytes {act} != declared "
                       f"{exp} (spec {spec})")
            findings.append(Finding(
                rule="SHD008", path=where, line=0, message=msg,
                snippet=f"{label}:{path}"))
    report = {
        "leaves": n_leaves,
        "expected_per_device_bytes": expected_total,
        "actual_per_device_bytes": actual_total,
        "global_bytes": global_total,
        "mismatches": mismatches,
    }
    return report, findings


def contract_findings(name: str, declared_specs, actual_shardings,
                      shapes, mesh, *, where: str) -> List[Finding]:
    """SHD009: the compiled program's shardings vs the declared contract
    — per leaf, the actual per-device shard shape must equal the shape
    the contract's PartitionSpec produces."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec_leaves = dict(_leaf_paths(
        declared_specs, is_leaf=lambda x: isinstance(x, P)))
    findings = []
    for (path, leaf), (_, actual) in zip(
            _leaf_paths(shapes), _leaf_paths(actual_shardings)):
        spec = spec_leaves.get(path, P())
        want = NamedSharding(mesh, spec).shard_shape(tuple(leaf.shape))
        got = actual.shard_shape(tuple(leaf.shape))
        if tuple(want) != tuple(got):
            findings.append(Finding(
                rule="SHD009", path=where, line=0,
                message=f"contract {name!r}: leaf {path} lowered with "
                        f"per-device shard {tuple(got)} but the "
                        f"declared spec {spec} demands {tuple(want)} — "
                        "the implementation drifted from its contract",
                snippet=f"{name}:{path}"))
    return findings


_OPT_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                    "all-to-all", "collective-permute")


def collective_allocation_findings(optimized_hlo: str, tree_bytes: float,
                                   *, frac: float = 0.25,
                                   where: str = "<program>"
                                   ) -> Tuple[dict, List[Finding]]:
    """SHD007: walk the optimized HLO for collectives whose RESULT is
    weight-tree-sized. A healthy sharded step's largest gather is one
    leaf (zero1 gathers each updated param leaf, ~single-digit % of the
    tree); a collective at >= `frac` of the whole tree is the
    replication-repair all-gather GSPMD inserts around mismatched
    shardings — the 2204.06514 comms-bound failure, caught at lowering
    time."""
    import re

    import numpy as np

    sizes: List[Tuple[str, int]] = []
    pat = re.compile(
        r"=\s*(?:\(?)([a-z0-9]+)\[([0-9,]*)\][^=]*?\s"
        r"(all-gather|all-reduce|reduce-scatter|all-to-all|"
        r"collective-permute)\(")
    for line in optimized_hlo.splitlines():
        m = pat.search(line)
        if not m:
            continue
        dtype_s, dims, op = m.groups()
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        try:
            itemsize = np.dtype(
                dtype_s.replace("bf16", "float16")
                .replace("f", "float").replace("s", "int")
                .replace("u", "uint").replace("pred", "bool")).itemsize
        except TypeError:
            itemsize = 4
        sizes.append((op, n * itemsize))
    biggest = max((b for _, b in sizes), default=0)
    threshold = max(int(frac * tree_bytes), 1)
    findings = []
    for op, nbytes in sizes:
        if nbytes >= threshold:
            findings.append(Finding(
                rule="SHD007", path=where, line=0,
                message=f"{op} result is {nbytes / 1e3:.1f} kB — "
                        f">= {frac:.0%} of the {tree_bytes / 1e3:.1f} kB "
                        "weight tree; an allocation-sized collective is "
                        "the accidental-replication repair, not a "
                        "sharded step",
                snippet=f"{op}:{nbytes}"))
    report = {"collectives": len(sizes), "largest_bytes": biggest,
              "tree_bytes": int(tree_bytes),
              "largest_frac": (biggest / tree_bytes) if tree_bytes else 0.0,
              "threshold_frac": frac}
    return report, findings


def _aval_tree(shapes, shardings):
    import jax

    return jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        shapes, shardings,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))


def _input_shardings_tree(compiled, *example_trees):
    """compiled.input_shardings -> one sharding pytree per positional
    argument (jax already returns them mirroring the arg pytrees; the
    example trees just pin the expected arity)."""
    ins = list(compiled.input_shardings[0])
    assert len(ins) == len(example_trees), (len(ins), len(example_trees))
    return ins


def _output_shardings_tree(compiled, out_example):
    """compiled.output_shardings mirrors the output pytree already."""
    del out_example
    return compiled.output_shardings


# -- the audited programs ----------------------------------------------

def _tiny_gpt_cfg():
    from dnn_tpu.models.gpt import GPTConfig

    return GPTConfig(vocab_size=64, block_size=64, n_layer=2, n_head=2,
                     n_embd=32)


def audit_zero1_train(*, data: int = 2, model: int = 4,
                      batch: int = 4, seq: int = 17) -> dict:
    """The zero1 (dp x tp + ZeRO-1) GPT train step, built FROM its
    declared contracts and audited end to end: per-shard memory bill for
    params AND optimizer moments (SHD008), contract conformance on the
    step's param/opt outputs (SHD009), full donation aliasing under
    NamedSharding (PRG003, donate=True), allocation-sized collectives in
    the optimized HLO (SHD007), and the sharding-aware recompile census
    (a resharded call is a new program — pinned so the count is a
    choice, not an accident)."""
    import jax
    import jax.numpy as jnp
    import optax

    from dnn_tpu import train as T
    from dnn_tpu.analysis.program import recompile_census
    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
    from dnn_tpu.utils.flops import tree_weight_bytes
    from dnn_tpu.utils.hlo_audit import count_aliased_compiled

    cfg = _tiny_gpt_cfg()
    mesh = make_mesh({DATA_AXIS: data, MODEL_AXIS: model})
    where = "train.make_sharded_train_step[zero1]"
    findings: List[Finding] = []

    shapes = jax.eval_shape(lambda k: gpt.init(k, cfg),
                            jax.random.PRNGKey(0))
    param_specs = get_contract("train.gpt_dp_tp.params")(shapes)
    opt = optax.adam(1e-3)
    opt_shapes = jax.eval_shape(opt.init, shapes)
    opt_specs = get_contract("train.zero1.opt_state")(
        opt_shapes, shapes, param_specs, mesh)

    apply_fn = gpt.make_apply(cfg)
    step = T.make_sharded_train_step(
        lambda p, b: T.next_token_loss(apply_fn, p, b),
        opt, mesh, param_specs, zero1=True, donate=True)

    p_avals = _aval_tree(shapes, T.specs_to_shardings(mesh, param_specs))
    o_avals = _aval_tree(opt_shapes, T.specs_to_shardings(mesh, opt_specs))
    batch_aval = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    compiled = step.lower(p_avals, o_avals, batch_aval).compile()

    # SHD008: the bill, on the program's INPUT shardings
    p_in, o_in, _ = _input_shardings_tree(
        compiled, shapes, opt_shapes, batch_aval)
    bill_p, f_p = memory_bill(shapes, param_specs, p_in, mesh,
                              where=where, label="params")
    bill_o, f_o = memory_bill(opt_shapes, opt_specs, o_in, mesh,
                              where=where, label="opt_state")
    findings += f_p + f_o

    # SHD009: the step's param/opt OUTPUTS must still match the
    # contract (an internal with_sharding_constraint drifting from the
    # declaration shows up here, not on the inputs)
    out_shardings = _output_shardings_tree(
        compiled, (shapes, opt_shapes, jax.ShapeDtypeStruct(
            (), jnp.float32)))
    findings += contract_findings(
        "train.gpt_dp_tp.params", param_specs, out_shardings[0],
        shapes, mesh, where=where)
    findings += contract_findings(
        "train.zero1.opt_state", opt_specs, out_shardings[1],
        opt_shapes, mesh, where=where)

    # SHD007: optimized-HLO collective allocation walk
    tree_bytes = tree_weight_bytes(shapes)
    try:
        hlo = "\n".join(m.to_string()
                        for m in compiled.runtime_executable()
                        .hlo_modules())
    except Exception:  # pragma: no cover — compiled.as_text fallback
        hlo = compiled.as_text()
    alloc, f_a = collective_allocation_findings(hlo, tree_bytes,
                                                where=where)
    findings += f_a

    # PRG003 under NamedSharding: with donate=True every (params + opt)
    # leaf must alias an output. GSPMD donations resolve in the COMPILED
    # HLO's input_output_alias header (jit only emits buffer_donor hints
    # at the StableHLO level once shardings are in play), so the count
    # reads the optimized module, not lowered.as_text().
    expected = len(jax.tree.leaves(shapes)) + len(jax.tree.leaves(
        opt_shapes, is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct)))
    aliased = count_aliased_compiled(hlo)
    if aliased < expected:
        findings.append(Finding(
            rule="PRG003", path=where, line=0,
            message=f"only {aliased}/{expected} donated sharded buffers "
                    "are aliased to outputs — un-aliased donations copy "
                    "every step",
            snippet=f"aliased={aliased} expected={expected}"))

    # sharding-aware census: identical avals under different shardings
    # ARE different programs — pin that the step holds exactly two in a
    # sharded-vs-replicated sweep (the count is a choice, not a leak)
    repl = jax.tree.map(lambda s: jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec()), T.specs_to_shardings(
            mesh, param_specs))
    census = recompile_census(
        [(p_avals, o_avals, batch_aval),
         (_aval_tree(shapes, repl), o_avals, batch_aval),
         (p_avals, o_avals, batch_aval)],
        bound=2, where=where)
    findings += census["findings"]

    return {"mesh": dict(mesh.shape),
            "bill": {"params": bill_p, "opt_state": bill_o},
            "donation": {"aliased": aliased, "expected": expected},
            "collectives": alloc,
            "sharding_census": {k: census[k]
                                for k in ("calls", "programs", "bound")},
            "findings": findings}


def audit_llama_dp_tp(*, data: int = 2, model: int = 4,
                      batch: int = 4, seq: int = 17) -> dict:
    """The llama dp x tp train step (the PR-2 configuration whose
    init-partitioning drift motivated this analyzer): bill + contract +
    allocation-sized collectives, no zero1."""
    import jax
    import jax.numpy as jnp
    import optax

    from dnn_tpu import train as T
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
    from dnn_tpu.utils.flops import tree_weight_bytes

    cfg = llama.PRESETS["llama-test"]
    mesh = make_mesh({DATA_AXIS: data, MODEL_AXIS: model})
    where = "train.make_sharded_train_step[llama_dp_tp]"
    findings: List[Finding] = []

    shapes = jax.eval_shape(lambda k: llama.init(k, cfg),
                            jax.random.PRNGKey(0))
    param_specs = get_contract("train.llama_dp_tp.params")(shapes)
    opt = optax.sgd(1e-2)
    opt_shapes = jax.eval_shape(opt.init, shapes)

    apply_fn = llama.make_apply(cfg)
    step = T.make_sharded_train_step(
        lambda p, b: T.next_token_loss(apply_fn, p, b),
        opt, mesh, param_specs)

    p_avals = _aval_tree(shapes, T.specs_to_shardings(mesh, param_specs))
    batch_aval = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    compiled = step.lower(p_avals, opt_shapes, batch_aval).compile()

    p_in, _, _ = _input_shardings_tree(
        compiled, shapes, opt_shapes, batch_aval)
    bill, f_b = memory_bill(shapes, param_specs, p_in, mesh,
                            where=where, label="params")
    findings += f_b

    out_shardings = _output_shardings_tree(
        compiled, (shapes, opt_shapes,
                   jax.ShapeDtypeStruct((), jnp.float32)))
    findings += contract_findings(
        "train.llama_dp_tp.params", param_specs, out_shardings[0],
        shapes, mesh, where=where)

    tree_bytes = tree_weight_bytes(shapes)
    try:
        hlo = "\n".join(m.to_string()
                        for m in compiled.runtime_executable()
                        .hlo_modules())
    except Exception:  # pragma: no cover
        hlo = compiled.as_text()
    alloc, f_a = collective_allocation_findings(hlo, tree_bytes,
                                                where=where)
    findings += f_a

    return {"mesh": dict(mesh.shape), "bill": {"params": bill},
            "collectives": alloc, "findings": findings}


def audit_stacked_pipeline(*, stages: int = 2, feature: int = 8,
                           batch: int = 4) -> dict:
    """The stacked pipeline's declared placement
    (pipeline.stacked_param_placement): each device must hold exactly
    its 1/S stage slice of every stacked leaf — bill + contract over
    the lowered spmd_pipeline_stacked program."""
    import jax
    import jax.numpy as jnp

    from dnn_tpu import train as T
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.parallel.pipeline import spmd_pipeline_stacked

    if len(jax.devices()) < stages:
        return {"skipped": f"need {stages} devices", "findings": []}
    mesh = make_mesh({STAGE_AXIS: stages})
    where = "parallel/pipeline.spmd_pipeline_stacked"

    def block(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    stacked_shapes = {
        "w": jax.ShapeDtypeStruct((stages, feature, feature),
                                  jnp.float32),
        "b": jax.ShapeDtypeStruct((stages, feature), jnp.float32),
    }
    specs = get_contract("pipeline.stacked_param_placement")(
        stacked_shapes)
    sharded = _aval_tree(stacked_shapes, T.specs_to_shardings(mesh, specs))
    x_aval = jax.ShapeDtypeStruct((batch, feature), jnp.float32)

    def pipe_step(sp, x):
        return spmd_pipeline_stacked(block, sp, x, mesh=mesh,
                                     num_microbatches=2)

    compiled = jax.jit(pipe_step).lower(sharded, x_aval).compile()
    p_in, _ = _input_shardings_tree(compiled, stacked_shapes, x_aval)
    bill, findings = memory_bill(stacked_shapes, specs, p_in, mesh,
                                 where=where, label="stacked")
    findings += contract_findings(
        "pipeline.stacked_param_placement", specs, p_in,
        stacked_shapes, mesh, where=where)
    return {"mesh": dict(mesh.shape), "bill": {"stacked": bill},
            "findings": findings}


def audit_moe_ep(*, experts: int = 4, ep: int = 2, batch: int = 4,
                 seq: int = 4, d: int = 8) -> dict:
    """The expert-parallel moe ffn (parallel/moe.make_moe_ffn_ep):
    mesh-axis-aware branch-collective consistency plus the per-axis
    collective signature of the traced program (the routing all_to_all /
    psum schedule every rank must agree on)."""
    import jax
    import jax.numpy as jnp

    from dnn_tpu.analysis.program import (
        axis_collective_signature,
        check_branch_collectives,
    )
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh
    from dnn_tpu.parallel.moe import init_moe, make_moe_ffn_ep

    if len(jax.devices()) < ep:
        return {"skipped": f"need {ep} devices", "findings": []}
    mesh = make_mesh({EXPERT_AXIS: ep})
    params = jax.eval_shape(
        lambda k: init_moe(k, d, experts), jax.random.PRNGKey(0))
    apply = make_moe_ffn_ep(mesh)
    x = jax.ShapeDtypeStruct((batch, seq, d), jnp.float32)
    closed = jax.make_jaxpr(apply)(params, x)
    findings = check_branch_collectives(closed,
                                        "parallel/moe.make_moe_ffn_ep")
    sig = axis_collective_signature(closed)
    return {"mesh": dict(mesh.shape),
            "collective_signature": list(sig),
            "findings": findings}


def run_shard_audit() -> Tuple[dict, List[Finding]]:
    """The full sharded-program audit. Returns (report, findings) —
    same shape as program.run_program_audit, same gate."""
    from dnn_tpu.analysis.findings import assign_occurrences

    report: Dict[str, dict] = {}
    findings: List[Finding] = []
    report["zero1"] = audit_zero1_train()
    report["llama_dp_tp"] = audit_llama_dp_tp()
    report["pipeline_stacked"] = audit_stacked_pipeline()
    report["moe_ep"] = audit_moe_ep()
    for section in report.values():
        findings.extend(section.pop("findings", []))
    return report, assign_occurrences(findings)
