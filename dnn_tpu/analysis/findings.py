"""Findings, rule registry, and the baseline suppression gate.

A Finding is one hazard at one source location. Its fingerprint is
line-number-free on purpose (rule + repo-relative path + the stripped
source line + an occurrence counter for identical lines), so a checked-in
baseline survives unrelated edits above a finding but dies with the line
it suppresses — a stale suppression is reported, never silently kept.

The baseline file (analysis/baseline.json) is the explicit list of
findings HEAD is allowed to carry. Every entry must name its fingerprint
and a one-line justification; `python -m dnn_tpu.analysis` exits nonzero
on any finding NOT in the baseline (a new hazard) and warns on any
baseline entry that no longer fires (a stale suppression to delete).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Sequence, Tuple

__all__ = ["Finding", "RULES", "assign_occurrences", "load_baseline",
           "diff_against_baseline", "render_finding"]

# rule id -> (title, one-line description for the CLI/README table)
RULES: Dict[str, Tuple[str, str]] = {
    "TPU001": (
        "traced-value Python branching",
        "Python `if`/`while` on a traced value inside a jitted/traced "
        "function — raises ConcretizationTypeError at trace time or forces "
        "a host sync; use lax.cond/jnp.where/lax.while_loop.",
    ),
    "TPU002": (
        "implicit host transfer",
        "float()/int()/bool()/.item()/.tolist()/np.asarray() on a traced "
        "value inside a traced function — a device->host sync (or trace "
        "error) on the hot path; keep the value on device (jnp.*).",
    ),
    "TPU003": (
        "PRNG key reuse",
        "the same PRNG key consumed by more than one jax.random draw "
        "without an intervening split/fold_in — correlated 'randomness'; "
        "split first: `key, sub = jax.random.split(key)`.",
    ),
    "TPU004": (
        "use after donation",
        "a buffer passed at a donate_argnums position is read again after "
        "the call — donated buffers are invalidated (XLA may already have "
        "overwritten them); rebind from the call's result instead.",
    ),
    "TPU005": (
        "recompile hazard (raw scalar / static arg in loop)",
        "a Python value derived from a loop variable reaches a jitted "
        "callable raw (weak-type churn recompiles silently when call "
        "sites disagree; pin with jnp.int32(...)/jnp.asarray(...)) or at "
        "a static_argnums position (one compile per distinct value).",
    ),
    "TPU006": (
        "divergent collectives across SPMD branches",
        "branches of lax.cond/lax.switch (or a Python if/else) inside a "
        "shard_map/pmap body issue different collective sequences — ranks "
        "taking different branches deadlock the program (SPMD requires "
        "identical collective order on every rank).",
    ),
    # program-pass (jaxpr-level) findings — same gate, different detector
    "PRG001": (
        "divergent collectives across compiled branches",
        "a cond/switch in a lowered program has branches with different "
        "collective sequences (jaxpr walk — catches dynamically built "
        "branch lists the AST pass cannot resolve).",
    ),
    "PRG002": (
        "allocation-sized constant baked into program",
        "a jaxpr constant at allocation scale (closed-over concrete "
        "array) — the compiled program carries a private copy per "
        "compilation instead of taking the buffer as an argument.",
    ),
    "PRG003": (
        "donation coverage gap",
        "a decode-step program does not alias (donate) its cache inputs "
        "to outputs — every step then pays a full cache copy.",
    ),
    "PRG004": (
        "recompile census over bound",
        "a shape sweep of an entrypoint compiles more distinct programs "
        "than its documented bound (e.g. the bucket ladder length).",
    ),
    # concurrency pass (analysis/concurrency.py) — same gate, the
    # asyncio/threads/shared-state hazard family PRs 4/7/8 shipped
    "CON001": (
        "blocking call on the event loop",
        "a blocking primitive (Condition.wait, blocking Lock.acquire, "
        "time.sleep, queue get/put, subprocess wait/communicate, or a "
        "registered slow-path helper like ShmRing.write) is reachable "
        "from an async def body — every RPC on that loop stalls behind "
        "it; await an async form or asyncio.to_thread it.",
    ),
    "CON002": (
        "unguarded Future settle",
        "Future.set_result/set_exception without a done()/cancelled() "
        "guard or enclosing try/except — settling a future its caller "
        "already cancelled raises InvalidStateError and kills the "
        "settling thread.",
    ),
    "CON003": (
        "acquire without finally-release",
        "a registered resource pair (transport ticket slot, shm ring "
        "slot, breaker half-open probe slot, raw lock) is acquired with "
        "no release in a `finally` — a cancelled or raising path leaks "
        "it until the pool/ring/breaker wedges.",
    ),
    "CON004": (
        "lock-order cycle",
        "`with lock:` nesting in this module takes two locks in both "
        "orders on different paths — two threads interleaving the "
        "paths deadlock; impose one global order.",
    ),
    "CON005": (
        "cross-context unlocked write",
        "a mutable attribute is written from a Thread(target=...) "
        "context AND from event-loop-reachable code without a lock or "
        "an explicit `# conc: single-writer` annotation.",
    ),
    "CON006": (
        "condition/thread lifecycle misuse",
        "Condition.notify outside its lock (lost wakeup / "
        "RuntimeError), or a non-daemon thread started without a join "
        "path (strands interpreter exit).",
    ),
    # sharding pass (analysis/shardcheck.py): mesh/GSPMD safety —
    # SHD001-006 are AST rules merged into lint_source, SHD007-009 fire
    # from the sharded-program audit over the real train/pipeline/moe
    # programs
    "SHD001": (
        "hard-coded device-count arithmetic",
        "arithmetic on jax.device_count()/len(jax.devices()) with an "
        "integer literal — breaks the moment a replica gets a different "
        "chip count; size from mesh.shape[axis] instead (comparisons, "
        "i.e. capability checks, are fine).",
    ),
    "SHD002": (
        "mesh-axis-name drift",
        "a string-literal axis name at a P(...)/collective/axis_index "
        "site does not match any axis declared at this module's "
        "Mesh/make_mesh site — fails at runtime on the real mesh or "
        "silently no-ops a collective.",
    ),
    "SHD003": (
        "sharded inputs, replicated outputs, no collective",
        "shard_map consumes sharded operands but declares every output "
        "replicated (missing or P()-everything out_specs) while the "
        "mapped body issues no collective — a mis-declared output or an "
        "implicit full gather.",
    ),
    "SHD004": (
        "host materialization reachable from spmd body",
        "a same-module call chain from a shard_map/pmap-mapped body "
        "reaches host materialization (.item()/np.*/host callback) — a "
        "per-rank device->host sync inside the mapped program.",
    ),
    "SHD005": (
        "per-host RNG divergence in spmd region",
        "a PRNG key created inside an spmd-mapped body is consumed "
        "without fold_in of the axis index — every rank draws the SAME "
        "'random' values; fold_in(key, lax.axis_index(axis)) first.",
    ),
    "SHD006": (
        "donation with mismatched donor/output sharding",
        "a donated argument is declared with a sharding no output "
        "carries — XLA only aliases matching layouts, so the donation "
        "silently dies and the step pays a full copy.",
    ),
    "SHD007": (
        "allocation-sized collective (accidental replication)",
        "a collective in the optimized HLO of a sharded program whose "
        "result is weight-tree-sized — the replication-repair "
        "all-gather GSPMD inserts around mismatched shardings; a "
        "healthy step's largest gather is one parameter leaf.",
    ),
    "SHD008": (
        "per-shard memory bill violation",
        "a leaf's actual per-device bytes in the compiled program "
        "disagree with the bytes its declared PartitionSpec promises — "
        "a supposedly-sharded leaf lowering replicated erases the "
        "sharding's memory win.",
    ),
    "SHD009": (
        "sharding-contract mismatch",
        "the compiled program's sharding attributes disagree with the "
        "PartitionSpec contract declared next to the code "
        "(shardcheck.contract) — the implementation drifted from its "
        "declaration.",
    ),
    # protocol pass (analysis/protocol.py): serving state machines as
    # checked transition tables
    "PRO001": (
        "unreachable protocol state",
        "a declared state of a serving state machine (breaker, drain, "
        "supervisor, relay window) is unreachable from the initial "
        "state over the declared edges.",
    ),
    "PRO002": (
        "absorbing non-terminal state",
        "a non-terminal state has no outgoing edge — once entered, the "
        "machine is stuck there forever (the 'unsettled half-open "
        "probe slot sheds traffic forever' shape).",
    ),
    "PRO003": (
        "undeclared protocol transition",
        "a code transition site (state-attr assignment / flight-event "
        "record / protocol status call) does not map to any declared "
        "edge of its machine — the implementation drifted from the "
        "checked table.",
    ),
    "PRO004": (
        "stale protocol edge",
        "a declared edge has no code transition site — the table "
        "promises behavior the implementation no longer has.",
    ),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative (or "<program>" for program-pass findings)
    line: int
    message: str
    snippet: str  # stripped source line (fingerprint component)
    occurrence: int = 0  # disambiguates identical (rule, path, snippet)

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.snippet}|{self.occurrence}"
            .encode()).hexdigest()[:12]
        return f"{self.rule}:{self.path}:{h}"


def render_finding(f: Finding) -> str:
    title = RULES.get(f.rule, ("?", ""))[0]
    loc = f"{f.path}:{f.line}" if f.line else f.path
    out = f"{loc}: {f.rule} [{title}] {f.message}"
    if f.snippet:
        out += f"\n    | {f.snippet}"
    out += f"\n    fingerprint: {f.fingerprint}"
    return out


def assign_occurrences(findings: Sequence[Finding]) -> List[Finding]:
    """Number identical (rule, path, snippet) findings 0..n-1 in source
    order so each gets a distinct, stable fingerprint."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        k = (f.rule, f.path, f.snippet)
        n = seen.get(k, 0)
        seen[k] = n + 1
        out.append(dataclasses.replace(f, occurrence=n))
    return out


def load_baseline(path) -> List[dict]:
    """Read the suppression file: a list of {fingerprint, justification}
    entries (extra keys — rule/path/snippet — are informational). Every
    entry MUST carry a non-empty justification; an unexplained
    suppression is a config error, not a finding to hide."""
    with open(path) as f:
        data = json.load(f)
    entries = data["suppressions"] if isinstance(data, dict) else data
    for e in entries:
        if not e.get("fingerprint"):
            raise ValueError(f"baseline entry missing fingerprint: {e}")
        if not str(e.get("justification", "")).strip():
            raise ValueError(
                f"baseline entry {e['fingerprint']} has no justification — "
                "every suppressed finding must say why it stays")
    return entries


def diff_against_baseline(findings: Sequence[Finding], entries: Sequence[dict]):
    """(new_findings, suppressed_findings, stale_entries). A baseline
    entry suppresses at most one finding with its fingerprint; anything
    beyond the baselined count is new."""
    budget: Dict[str, int] = {}
    for e in entries:
        budget[e["fingerprint"]] = budget.get(e["fingerprint"], 0) + 1
    new, suppressed = [], []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            suppressed.append(f)
        else:
            new.append(f)
    fired = {f.fingerprint for f in suppressed}
    stale = [e for e in entries
             if e["fingerprint"] not in fired]
    return new, suppressed, stale
