"""Static analyzer: trace/shard safety, concurrency hazards, protocol
state machines.

Three static passes over the framework (and over user model code, via
CLI paths), plus one runtime companion:

  * AST lint (analysis/lint.py) — rules TPU001..TPU006 over source text:
    traced-value Python branching, implicit host transfers, PRNG key
    reuse, use-after-donation, loop-scalar recompile hazards, and
    divergent collectives across SPMD branches. No jax import needed.
  * concurrency pass (analysis/concurrency.py) — rules CON001..CON006,
    merged into the same lint walk: blocking primitives reachable from
    async bodies, unguarded Future settles, acquire-without-finally-
    release over registered resource pairs, lock-order cycles,
    cross-context unlocked writes, notify/thread-lifecycle misuse.
  * protocol pass (analysis/protocol.py) — rules PRO001..PRO004: the
    serving stack's state machines (circuit breaker, drain, supervisor,
    relay accept window) declared as transition tables, model-checked
    (reachability, no absorbing non-terminal state) and cross-checked
    against their code transition sites in both directions.
  * program pass (analysis/program.py) — rules PRG001..PRG004 over the
    REAL entrypoints' jaxprs/lowerings: mesh-axis-aware collective-
    sequence consistency across pipeline stage programs, allocation-
    sized baked constants, cache-donation coverage, and a recompile
    census (bucketed decode ladder bound; pipeline/transport pinned at
    one program). Device-free (eval_shape avals), CPU-only.
  * sharding pass (analysis/shardcheck.py) — rules SHD001..SHD009:
    SHD001-006 are AST rules merged into the lint walk (hard-coded
    device-count arithmetic, mesh-axis-name drift, sharded-in/
    replicated-out shard_maps, host materialization reachable from spmd
    bodies, per-host RNG divergence, donation/output sharding
    mismatch); SHD007-009 fire from a compiled audit of the REAL
    sharded programs (zero1 train step, llama dp x tp, stacked
    pipeline, moe EP): allocation-sized collectives, the per-shard
    memory bill, and conformance to sharding contracts declared next
    to the code with `shardcheck.contract`.
  * loop-lag sanitizer (analysis/sanitize.py) — the RUNTIME companion
    for blocking calls no per-module AST pass can see through an
    indirection: an env-gated event-loop self-timer emitting bounded
    flight events, asserted in-run by the transport/chaos probes.

Gate: `python -m dnn_tpu.analysis` — exits nonzero on any finding not in
analysis/baseline.json; baselined findings are enumerated (never hidden)
and each carries a one-line justification. `--diff REV` lints only the
package files changed since REV; `--format sarif` emits SARIF 2.1.0 for
CI annotation. See README "Static analysis".
"""

from dnn_tpu.analysis.findings import (  # noqa: F401
    Finding,
    RULES,
    diff_against_baseline,
    load_baseline,
    render_finding,
)
from dnn_tpu.analysis.lint import lint_paths, lint_source  # noqa: F401
from dnn_tpu.analysis.shardcheck import contract  # noqa: F401

__all__ = ["Finding", "RULES", "lint_paths", "lint_source",
           "load_baseline", "diff_against_baseline", "render_finding",
           "contract"]
