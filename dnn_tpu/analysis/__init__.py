"""Trace/shard-safety static analyzer.

Two passes over the framework (and over user model code, via CLI paths):

  * AST lint (analysis/lint.py) — rules TPU001..TPU006 over source text:
    traced-value Python branching, implicit host transfers, PRNG key
    reuse, use-after-donation, loop-scalar recompile hazards, and
    divergent collectives across SPMD branches. No jax import needed.
  * program pass (analysis/program.py) — rules PRG001..PRG004 over the
    REAL entrypoints' jaxprs/lowerings: collective-sequence consistency
    across pipeline stage programs, allocation-sized baked constants,
    cache-donation coverage, and a recompile census with the bucketed
    decode's ladder bound. Device-free (eval_shape avals), CPU-only.

Gate: `python -m dnn_tpu.analysis` — exits nonzero on any finding not in
analysis/baseline.json; baselined findings are enumerated (never hidden)
and each carries a one-line justification. See README "Static analysis".
"""

from dnn_tpu.analysis.findings import (  # noqa: F401
    Finding,
    RULES,
    diff_against_baseline,
    load_baseline,
    render_finding,
)
from dnn_tpu.analysis.lint import lint_paths, lint_source  # noqa: F401

__all__ = ["Finding", "RULES", "lint_paths", "lint_source",
           "load_baseline", "diff_against_baseline", "render_finding"]
