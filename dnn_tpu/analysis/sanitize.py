"""Loop-lag sanitizer: the runtime companion to the CON001 AST rule.

The AST pass flags blocking calls it can SEE reaching an async body;
a blocking call behind an indirection the per-module analysis cannot
resolve (a callback registry, a duck-typed sender, a C extension) is
invisible to it. This sanitizer is the dynamic tripwire: a periodic
self-timer on the event loop measures how late each tick fires — any
callback that held the loop for >= threshold shows up as exactly that
much tick overshoot, the same way the PR 7 `ShmRing.write` deadlock
held the loop for the full 30 s ring timeout.

Shape follows the obs conventions: OFF by one boolean
(`DNN_TPU_LOOP_SANITIZE`, default off — it is a test/verify-path
instrument, not a production default), bounded (a deque of recent lag
samples, a cap on emitted flight events), and flight-event-emitting —
each breach lands in the ring as a `loop_lag` event with the measured
lag, so `benchmarks/chaos_probe.py` / `relay_transport_probe.py` read
the served /debugz back and assert the bound IN-RUN against the
artifact. A `loop_sanitize_on` event at install proves the sanitizer
actually ran (an assertion against an empty ring must not pass
vacuously).

Env knobs: DNN_TPU_LOOP_SANITIZE=1 enables;
DNN_TPU_LOOP_SANITIZE_THRESHOLD_S overrides the breach threshold
(default 0.25 s — well above scheduler jitter, well below any real
blocking primitive's timeout).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Optional

__all__ = ["LoopLagSanitizer", "enabled", "maybe_install",
           "DEFAULT_THRESHOLD_S"]

ENV_GATE = "DNN_TPU_LOOP_SANITIZE"
ENV_THRESHOLD = "DNN_TPU_LOOP_SANITIZE_THRESHOLD_S"
DEFAULT_THRESHOLD_S = 0.25
DEFAULT_INTERVAL_S = 0.05


def enabled() -> bool:
    return os.environ.get(ENV_GATE, "").lower() in ("1", "on", "true",
                                                    "yes")


class LoopLagSanitizer:
    """Periodic event-loop self-timer. `install()` must run with the
    target loop current (or be handed one); `stop()` cancels the tick.
    Breaches (overshoot >= threshold) are counted, the worst is kept,
    and at most `max_events` land in the flight ring — a loop wedged in
    a tight blocking cycle must not flood the post-mortem record."""

    def __init__(self, threshold_s: float = DEFAULT_THRESHOLD_S,
                 interval_s: float = DEFAULT_INTERVAL_S, *,
                 max_events: int = 32, where: str = ""):
        self.threshold_s = float(threshold_s)
        self.interval_s = float(interval_s)
        self.max_events = int(max_events)
        self.where = where
        self.samples: "deque[float]" = deque(maxlen=256)
        self.breaches = 0
        self.max_lag_s = 0.0
        self._emitted = 0
        self._handle = None
        self._loop = None
        self._stopped = False

    # -- lifecycle -----------------------------------------------------

    def install(self, loop=None) -> "LoopLagSanitizer":
        import asyncio

        from dnn_tpu.obs import flight

        self._loop = loop or asyncio.get_running_loop()
        self._stopped = False
        flight.record("loop_sanitize_on", where=self.where,
                      threshold_ms=round(self.threshold_s * 1e3, 1),
                      interval_ms=round(self.interval_s * 1e3, 1))
        m = self._metrics()
        if m is not None:
            # scrape-time callable: the worst observed lag, live
            m.set_fn("obs.loop_lag_max_seconds", lambda: self.max_lag_s)
        self._arm(time.perf_counter() + self.interval_s)
        return self

    def stop(self):
        self._stopped = True
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    # -- internals -----------------------------------------------------

    @staticmethod
    def _metrics():
        from dnn_tpu import obs

        return obs.metrics()

    def _arm(self, expected: float):
        delay = max(expected - time.perf_counter(), 0.0)
        self._handle = self._loop.call_later(delay, self._tick, expected)

    def _tick(self, expected: float):
        if self._stopped:
            return
        now = time.perf_counter()
        lag = max(now - expected, 0.0)
        self.samples.append(lag)
        if lag > self.max_lag_s:
            self.max_lag_s = lag
        if lag >= self.threshold_s:
            self.breaches += 1
            if self._emitted < self.max_events:
                from dnn_tpu.obs import flight

                self._emitted += 1
                flight.record("loop_lag", where=self.where,
                              lag_ms=round(lag * 1e3, 1),
                              threshold_ms=round(
                                  self.threshold_s * 1e3, 1),
                              breaches=self.breaches)
        self._arm(now + self.interval_s)

    # -- reading back --------------------------------------------------

    def status(self) -> dict:
        return {"where": self.where, "breaches": self.breaches,
                "max_lag_ms": round(self.max_lag_s * 1e3, 1),
                "threshold_ms": round(self.threshold_s * 1e3, 1),
                "samples": len(self.samples)}

    def assert_bounded(self, bound_s: float):
        """Raise AssertionError when any observed lag exceeded
        `bound_s` — the in-run contract the transport/chaos probes
        hold (their bound tolerates first-compile GIL stalls; a
        reintroduced blocking-primitive wait blows well past it)."""
        if self.max_lag_s > bound_s:
            raise AssertionError(
                f"event loop lag {self.max_lag_s * 1e3:.0f} ms exceeds "
                f"the {bound_s * 1e3:.0f} ms bound ({self.breaches} "
                f"breaches >= {self.threshold_s * 1e3:.0f} ms) — a "
                "callback blocked the loop; see `loop_lag` flight "
                "events")


def read_endpoint(base_url: str, timeout: float = 10.0) -> dict:
    """Read a serving process's sanitizer record back off its /debugz
    (the probes' assertion input is the served ARTIFACT, not in-process
    state): -> {installed, breaches, max_lag_ms}. `installed` False
    means the assertion would be vacuous — the caller should fail it."""
    import json as _json
    import urllib.request

    base = base_url.rstrip("/")
    out = {"installed": False, "breaches": 0, "max_lag_ms": 0.0}
    with urllib.request.urlopen(base + "/debugz?format=json",
                                timeout=timeout) as r:
        events = _json.loads(r.read().decode())
    for ev in events:
        if ev.get("kind") == "loop_sanitize_on":
            out["installed"] = True
        elif ev.get("kind") == "loop_lag":
            out["breaches"] += 1
            out["max_lag_ms"] = max(out["max_lag_ms"],
                                    float(ev.get("lag_ms", 0.0)))
    return out


def maybe_install(loop=None, *, where: str = ""
                  ) -> Optional[LoopLagSanitizer]:
    """Env-gated install (the serving entry points call this): returns
    the sanitizer when DNN_TPU_LOOP_SANITIZE is on, else None at the
    cost of one env read."""
    if not enabled():
        return None
    try:
        threshold = float(os.environ.get(ENV_THRESHOLD,
                                         DEFAULT_THRESHOLD_S))
    except ValueError:
        threshold = DEFAULT_THRESHOLD_S
    return LoopLagSanitizer(threshold_s=threshold,
                            where=where).install(loop)
