"""Concurrency-hazard AST pass: rules CON001-CON006.

PRs 4, 7 and 8 each shipped a real concurrency bug in the serving stack
that only hand review or e2e verify caught: the `ShmRing.write`
blocking-wait-on-the-event-loop deadlock (PR 7), the
`set_result`-on-a-cancelled-future InvalidStateError that killed the
batcher thread (PR 4), and the cancelled `_forward` handler that leaked
ticket slots until the relay wedged (PR 7 review round 2). This pass
makes that bug CLASS a CI failure: pure `ast`, no imports of the code
under analysis, same Finding/fingerprint/baseline machinery as the
trace/shard lint (analysis/lint.py).

Scope discipline, mirroring lint.py: whole-package interprocedural
analysis would drown signal in false positives, so reachability is
resolved PER MODULE — an async def (every grpc.aio handler is one)
calling a sibling/method whose body blocks is flagged; a helper in
another module is covered by registering its name in the slow-path
table (_SLOW_HELPERS) or, at runtime, by the loop-lag sanitizer
(analysis/sanitize.py), the dynamic companion for blocking calls no
static pass can see through.

Receiver types are tracked from constructor sites (module scope, class
`__init__`, locals): `q = queue.Queue()` makes `q.get` a blocking call,
`self._free = threading.Condition(...)` makes `self._free.wait`
blocking and associates the condition with its lock. A call that is
awaited, or whose callee lives under `asyncio.`, is never flagged —
and passing a blocking function BY REFERENCE to
`asyncio.to_thread`/`run_in_executor` is the sanctioned fix, which the
pass naturally accepts because no Call node exists.

Suppression: a line containing a `# conc:` annotation (e.g.
`# conc: single-writer` for CON005) suppresses CON findings on that
line — the annotation is the documented claim the rule asks for.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dnn_tpu.analysis.findings import Finding

__all__ = ["check_source", "BLOCKING_TYPES", "SLOW_HELPERS",
           "RESOURCE_PAIRS"]

# ----------------------------------------------------------------------
# registries (extend these when a new blocking helper / resource pair
# enters the codebase — the tables ARE the interprocedural knowledge)
# ----------------------------------------------------------------------

# constructor dotted-suffix -> type tag
BLOCKING_TYPES: Dict[str, str] = {
    "queue.Queue": "queue", "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue", "queue.SimpleQueue": "queue",
    "threading.Lock": "lock", "threading.RLock": "lock",
    "threading.Semaphore": "lock", "threading.BoundedSemaphore": "lock",
    "threading.Condition": "condition", "threading.Event": "event",
    "threading.Barrier": "event",
    "subprocess.Popen": "popen",
    "ShmRing": "shmring",
    "concurrent.futures.Future": "future", "futures.Future": "future",
}

# type tag -> method names that BLOCK the calling thread
_BLOCKING_METHODS: Dict[str, Set[str]] = {
    "queue": {"get", "put", "join"},
    "lock": {"acquire"},
    "condition": {"wait", "wait_for", "acquire"},
    "event": {"wait"},
    "popen": {"wait", "communicate"},
    "shmring": {"write"},
    "future": {"result", "exception"},
}

# dotted suffixes that block regardless of receiver type
_BLOCKING_CALLS = {
    "time.sleep", "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "subprocess.call",
    "urllib.request.urlopen", "socket.create_connection",
}

# registered slow-path helper names (bare method/function name): known
# to block or run device/host work long enough to stall an event loop,
# even when this module cannot see their bodies. ShmRing.write is the
# PR 7 deadlock; device_sync blocks on device completion (so a helper
# like StageServer._compute_stage that calls it is blocking by
# propagation); make_request may block on the shm ring (the nowait
# variant + worker-thread fallback is the sanctioned async form).
SLOW_HELPERS: Set[str] = {"device_sync", "make_request", "block_until_ready"}

# CON003 resource pairs: (acquire method name, receiver-name substring
# hint or None, release names, what leaks). The acquire call must be
# paired with a release inside a `finally` of the same function or an
# enclosing one — the PR 7/8 lesson: releases on the success/except
# paths leak under cancellation, only a finally (or context manager)
# is cancel-safe.
RESOURCE_PAIRS: List[Tuple[str, Optional[str], Set[str], str]] = [
    ("allow", "breaker", {"record", "release"},
     "CircuitBreaker half-open probe slot (an unsettled slot sheds "
     "traffic forever)"),
    ("make_request", None, {"sent_ok", "cleanup"},
     "transport ticket (device mailbox entry / shm ring slot)"),
    ("make_request_nowait", None, {"sent_ok", "cleanup"},
     "transport ticket (device mailbox entry / shm ring slot)"),
    ("write", "ring", {"release"}, "shm ring slot latch"),
    ("write_nowait", "ring", {"release"}, "shm ring slot latch"),
    ("put", "MAILBOX", {"drop", "sent_ok"}, "device mailbox entry"),
    ("acquire", None, {"release"}, "raw lock/semaphore acquisition"),
]
_ACQUIRE_NAMES = {p[0] for p in RESOURCE_PAIRS}

_ANNOTATION = "# conc:"


def _callee(call: ast.Call) -> str:
    try:
        return ast.unparse(call.func)
    except Exception:  # pragma: no cover — exotic nodes
        return ""


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _suffix_match(callee: str, table) -> Optional[str]:
    """Longest dotted-suffix lookup: 'a.b.c' matches keys 'a.b.c',
    'b.c', 'c' — returns the matched value (or the key for sets)."""
    parts = callee.split(".")
    for i in range(len(parts)):
        suffix = ".".join(parts[i:])
        if suffix in table:
            return table[suffix] if isinstance(table, dict) else suffix
    return None


# ----------------------------------------------------------------------
# module indexing: types, threads, call graph
# ----------------------------------------------------------------------

class _ModuleInfo:
    def __init__(self):
        # bound name (module/local/self-dotted) -> type tag
        self.types: Dict[str, str] = {}
        # condition name -> its constructor's lock arg name (unparsed)
        self.cond_locks: Dict[str, str] = {}
        # class name -> set of method names run on a thread
        self.thread_methods: Dict[str, Set[str]] = {}
        # class name -> whether it subclasses threading.Thread
        self.thread_subclass: Set[str] = set()


def _type_of_ctor(value) -> Optional[str]:
    if isinstance(value, ast.Call):
        return _suffix_match(_callee(value), BLOCKING_TYPES)
    return None


def _index_module(tree: ast.Module) -> _ModuleInfo:
    info = _ModuleInfo()

    def note_assign(targets, value, *, module_scope: bool):
        tag = _type_of_ctor(value)
        if tag is None:
            return
        for t in targets:
            if not isinstance(t, (ast.Name, ast.Attribute)):
                continue
            try:
                name = ast.unparse(t)
            except Exception:  # pragma: no cover
                continue
            # bare names are only trusted at MODULE scope — a local
            # `fut = Future()` in one function must not type every
            # other function's same-named variable; dotted (self.X)
            # attrs are process-lifetime state and index from anywhere
            if "." not in name and not module_scope:
                continue
            info.types[name] = tag
            if tag == "condition" and isinstance(value, ast.Call) \
                    and value.args:
                try:
                    info.cond_locks[name] = ast.unparse(value.args[0])
                except Exception:  # pragma: no cover
                    pass

    top = set(map(id, tree.body))
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            note_assign(node.targets, node.value,
                        module_scope=id(node) in top)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            note_assign([node.target], node.value,
                        module_scope=id(node) in top)
        elif isinstance(node, ast.ClassDef):
            bases = set()
            for b in node.bases:
                try:
                    bases.add(_last(ast.unparse(b)))
                except Exception:  # pragma: no cover
                    pass
            if "Thread" in bases:
                info.thread_subclass.add(node.name)
                info.thread_methods.setdefault(node.name, set()).add("run")
            # Thread(target=self.X) anywhere inside the class body
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        _last(_callee(sub)) == "Thread":
                    for kw in sub.keywords:
                        if kw.arg == "target" and isinstance(
                                kw.value, ast.Attribute) and isinstance(
                                kw.value.value, ast.Name) and \
                                kw.value.value.id == "self":
                            info.thread_methods.setdefault(
                                node.name, set()).add(kw.value.attr)
    return info


def _walk_own(fn):
    """Walk a function's OWN body: nested function/async-function
    subtrees are excluded entirely (ast.walk would descend into them;
    `continue`-ing on the def node alone still yields its children).
    Nested defs are judged as their own functions."""
    stack = [fn]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield child
            stack.append(child)


def _called_names(fn) -> Set[str]:
    """Names this function calls: bare names and `self.X` methods."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name) and f.value.id == "self":
                out.add(f.attr)
    return out


# ----------------------------------------------------------------------
# the checker
# ----------------------------------------------------------------------

class _Checker:
    def __init__(self, tree: ast.Module, path: str, src_lines: List[str]):
        self.tree = tree
        self.path = path
        self.src_lines = src_lines
        self.info = _index_module(tree)
        self.findings: List[Finding] = []
        self._flagged: Set[Tuple[str, int]] = set()
        # every (fn node, enclosing class name or None, ancestors chain)
        self.functions: List[Tuple[ast.AST, Optional[str], List[ast.AST]]] \
            = []
        self._collect_functions()
        self.local_types = {}  # per-function, rebuilt in _scan_fn
        self.blocking_fns = self._blocking_closure()

    # -- plumbing ------------------------------------------------------

    def _collect_functions(self):
        stack: List[Tuple[ast.AST, Optional[str], List[ast.AST]]] = [
            (self.tree, None, [])]
        while stack:
            node, cls, anc = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    self.functions.append((child, cls, anc))
                    stack.append((child, cls, anc + [child]))
                elif isinstance(child, ast.ClassDef):
                    stack.append((child, child.name, anc))
                else:
                    stack.append((child, cls, anc))

    def _annotated(self, line: int) -> bool:
        if 0 < line <= len(self.src_lines):
            return _ANNOTATION in self.src_lines[line - 1]
        return False

    def _flag(self, rule: str, node, message: str):
        line = getattr(node, "lineno", 0)
        if (rule, line) in self._flagged or self._annotated(line):
            return
        self._flagged.add((rule, line))
        snippet = ""
        if 0 < line <= len(self.src_lines):
            snippet = self.src_lines[line - 1].strip()
        self.findings.append(Finding(rule=rule, path=self.path, line=line,
                                     message=message, snippet=snippet))

    def _recv_type(self, call: ast.Call, fn_types: Dict[str, str]
                   ) -> Optional[str]:
        """Type tag of a method call's receiver, from module/class/local
        constructor tracking."""
        if not isinstance(call.func, ast.Attribute):
            return None
        try:
            recv = ast.unparse(call.func.value)
        except Exception:  # pragma: no cover
            return None
        return fn_types.get(recv) or self.info.types.get(recv)

    def _is_blocking_call(self, call: ast.Call,
                          fn_types: Dict[str, str]) -> Optional[str]:
        """Reason string when this call blocks the calling thread."""
        callee = _callee(call)
        hit = _suffix_match(callee, _BLOCKING_CALLS)
        if hit is not None:
            return f"`{hit}` blocks the calling thread"
        name = _last(callee)
        if isinstance(call.func, ast.Attribute):
            tag = self._recv_type(call, fn_types)
            if tag is not None and name in _BLOCKING_METHODS.get(tag, ()):
                # Lock.acquire(blocking=False) / q.get_nowait-style
                # non-blocking forms are fine
                for kw in call.keywords:
                    if kw.arg == "blocking" and isinstance(
                            kw.value, ast.Constant) and \
                            kw.value.value is False:
                        return None
                    if kw.arg == "block" and isinstance(
                            kw.value, ast.Constant) and \
                            kw.value.value is False:
                        return None
                return (f"`.{name}()` on a {tag} blocks the calling "
                        "thread")
        if name in SLOW_HELPERS:
            return (f"`{name}` is a registered slow-path helper "
                    "(analysis/concurrency.SLOW_HELPERS)")
        return None

    def _fn_local_types(self, fn) -> Dict[str, str]:
        types: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                tag = _type_of_ctor(node.value)
                if tag is None:
                    continue
                for t in node.targets:
                    if isinstance(t, (ast.Name, ast.Attribute)):
                        try:
                            types[ast.unparse(t)] = tag
                        except Exception:  # pragma: no cover
                            pass
        return types

    def _direct_blocking(self, fn) -> bool:
        types = self._fn_local_types(fn)
        for node in _walk_own(fn):  # nested defs judged separately
            if isinstance(node, ast.Call) and \
                    self._is_blocking_call(node, types):
                return True
        return False

    def _blocking_closure(self) -> Set[str]:
        """Names of SYNC module functions/methods whose bodies reach a
        blocking call (direct, or through same-module sync calls)."""
        sync_fns = {fn.name: fn for fn, _cls, _anc in self.functions
                    if isinstance(fn, ast.FunctionDef)}
        blocking = {name for name, fn in sync_fns.items()
                    if self._direct_blocking(fn)}
        changed = True
        while changed:
            changed = False
            for name, fn in sync_fns.items():
                if name in blocking:
                    continue
                if _called_names(fn) & blocking:
                    blocking.add(name)
                    changed = True
        return blocking

    # -- driver --------------------------------------------------------

    def run(self) -> List[Finding]:
        for fn, cls, ancestors in self.functions:
            self._scan_fn(fn, cls, ancestors)
        self._check_lock_order()
        self._check_cross_context_writes()
        return self.findings

    def _scan_fn(self, fn, cls, ancestors):
        # only an async def's OWN body is loop context. A sync def
        # nested inside one is usually exactly the sanctioned fix (a
        # closure handed to asyncio.to_thread / a worker-thread
        # callback) and must not flag; if the async body CALLS it
        # directly, the blocking-closure propagation flags that call
        # site instead.
        in_async = isinstance(fn, ast.AsyncFunctionDef)
        fn_types = self._fn_local_types(fn)
        awaited: Set[int] = set()
        for node in _walk_own(fn):
            if isinstance(node, ast.Await) and isinstance(node.value,
                                                          ast.Call):
                awaited.add(id(node.value))
        for node in _walk_own(fn):  # nested defs get their own pass
            if not isinstance(node, ast.Call):
                continue
            callee = _callee(node)
            name = _last(callee)
            # CON001: blocking call reachable from an async body
            if in_async and id(node) not in awaited \
                    and not callee.startswith("asyncio."):
                reason = self._is_blocking_call(node, fn_types)
                if reason is None and name in self.blocking_fns and (
                        isinstance(node.func, ast.Name)
                        or (isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self")):
                    reason = (f"`{name}` reaches a blocking primitive "
                              "(same-module call chain)")
                if reason is not None:
                    self._flag(
                        "CON001", node,
                        f"{reason} on the event loop — every in-flight "
                        "RPC on this loop stalls behind it (the PR 7 "
                        "ShmRing.write deadlock shape); await an async "
                        "form or run it via asyncio.to_thread")
            # CON002: unguarded Future settle
            if name in ("set_result", "set_exception") and \
                    isinstance(node.func, ast.Attribute):
                if not self._settle_guarded(node, fn):
                    self._flag(
                        "CON002", node,
                        f"`{name}` without a done()/cancelled() guard or "
                        "enclosing try/except — settling a future its "
                        "caller already cancelled raises "
                        "InvalidStateError and kills the settling "
                        "thread (the PR 4 batcher-worker killer)")
            # CON003: acquire without finally-release
            self._check_resource_pair(node, fn, ancestors)
            # CON006a: notify outside its lock
            if name in ("notify", "notify_all") and \
                    isinstance(node.func, ast.Attribute):
                tag = self._recv_type(node, fn_types)
                if tag == "condition" and not self._inside_with(node, fn):
                    self._flag(
                        "CON006", node,
                        f"`.{name}()` on a Condition outside any `with` "
                        "block — notify without holding the lock races "
                        "the waiter's predicate check (RuntimeError at "
                        "best, a lost wakeup at worst)")
            # CON006b: non-daemon thread without a join path
            if name == "Thread" and callee.split(".")[0] in (
                    "threading", "Thread"):
                self._check_thread_lifecycle(node, fn)

    # -- CON002 helpers ------------------------------------------------

    def _settle_guarded(self, call: ast.Call, fn) -> bool:
        """Guarded when an ancestor `if` tests done()/cancelled() on the
        same receiver, or the call sits in the BODY of a `try` whose
        handlers are broad enough to catch InvalidStateError. A settle
        inside an except handler / else / finally of that try is NOT
        guarded by it — a handler does not catch exceptions raised in
        its own body (exactly where cleanup-path settles live)."""
        try:
            recv = ast.unparse(call.func.value)
        except Exception:  # pragma: no cover
            recv = ""
        for anc in self._ancestors_of(call, fn):
            if isinstance(anc, ast.If):
                try:
                    test = ast.unparse(anc.test)
                except Exception:  # pragma: no cover
                    test = ""
                if (".done()" in test or ".cancelled()" in test) and \
                        (not recv or recv in test):
                    return True
            if isinstance(anc, ast.Try) and \
                    self._in_stmt_list(anc.body, call):
                for h in anc.handlers:
                    if h.type is None:
                        return True
                    try:
                        ht = ast.unparse(h.type)
                    except Exception:  # pragma: no cover
                        continue
                    if any(t in ht for t in (
                            "Exception", "BaseException",
                            "InvalidStateError")):
                        return True
        return False

    @staticmethod
    def _in_stmt_list(stmts, target) -> bool:
        for s in stmts:
            for node in ast.walk(s):
                if node is target:
                    return True
        return False

    def _ancestors_of(self, target, fn) -> List[ast.AST]:
        """Statement ancestors of `target` within `fn` (linear walk —
        functions are small)."""
        chain: List[ast.AST] = []

        def visit(node, path):
            if node is target:
                chain.extend(path)
                return True
            for child in ast.iter_child_nodes(node):
                if visit(child, path + [node]):
                    return True
            return False

        visit(fn, [])
        return chain

    # -- CON003 helpers ------------------------------------------------

    def _check_resource_pair(self, call: ast.Call, fn, ancestors):
        if not isinstance(call.func, ast.Attribute):
            return
        name = call.func.attr
        if name not in _ACQUIRE_NAMES:
            return
        # the acquire method's own implementation is not a call site
        if fn.name in _ACQUIRE_NAMES:
            return
        try:
            recv = ast.unparse(call.func.value)
        except Exception:  # pragma: no cover
            return
        for acq, hint, releases, what in RESOURCE_PAIRS:
            if name != acq:
                continue
            if hint is not None and hint.lower() not in recv.lower():
                continue
            # non-blocking acquire probes (lock.acquire(blocking=False))
            # are usually paired with an early return; still require the
            # finally — the rule is about the RELEASE path
            if self._released_in_finally(fn, ancestors, releases):
                return
            self._flag(
                "CON003", call,
                f"`{recv}.{name}()` acquires a {what} but no "
                f"{'/'.join(sorted(releases))} call appears in a "
                "`finally` of this function or an enclosing one — a "
                "cancelled or raising path leaks the resource (the "
                "PR 7 ticket-slot leak: 4 cancellations wedged the "
                "ring)")
            return

    def _released_in_finally(self, fn, ancestors, releases: Set[str]
                             ) -> bool:
        for scope in [fn] + list(ancestors):
            for node in ast.walk(scope):
                if not isinstance(node, ast.Try) or not node.finalbody:
                    continue
                for sub in ast.walk(ast.Module(body=list(node.finalbody),
                                               type_ignores=[])):
                    if isinstance(sub, ast.Call) and isinstance(
                            sub.func, ast.Attribute) and \
                            sub.func.attr in releases:
                        return True
                    if isinstance(sub, ast.Call) and isinstance(
                            sub.func, ast.Name) and \
                            sub.func.id in releases:
                        return True
        # `with` statements release on exit by construction
        for scope in [fn] + list(ancestors):
            for node in ast.walk(scope):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        try:
                            ctx = ast.unparse(item.context_expr)
                        except Exception:  # pragma: no cover
                            continue
                        if any(r in ctx for r in releases):
                            return True
        return False

    # -- CON004: lock-order cycles --------------------------------------

    def _lock_name(self, expr, cls: Optional[str]) -> Optional[str]:
        """Normalized lock identity for a `with X:` context, or None
        when X is not lock-like. Class-scoped for self attrs so two
        classes' `self._lock` never alias."""
        try:
            name = ast.unparse(expr)
        except Exception:  # pragma: no cover
            return None
        tag = self.info.types.get(name)
        if tag not in ("lock", "condition"):
            lowered = name.lower()
            if not any(k in lowered for k in ("lock", "cond", "_free",
                                              "mutex")):
                return None
        if name.startswith("self."):
            return f"{cls or '?'}.{name[5:]}"
        return name

    def _check_lock_order(self):
        edges: Dict[Tuple[str, str], ast.AST] = {}
        for fn, cls, _anc in self.functions:
            stack: List[Tuple[ast.AST, List[str]]] = [(fn, [])]
            while stack:
                node, held = stack.pop()
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) and \
                            child is not fn:
                        continue
                    child_held = held
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        names = [self._lock_name(i.context_expr, cls)
                                 for i in child.items]
                        names = [n for n in names if n is not None]
                        for outer in held:
                            for inner in names:
                                if outer != inner:
                                    edges.setdefault((outer, inner), child)
                        child_held = held + names
                    stack.append((child, child_held))
        # cycle detection over the module's lock graph
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        seen: Set[Tuple[str, str]] = set()
        for a, b in list(edges):
            if (b, a) in seen:
                continue
            # path b ->* a closes a cycle through edge a -> b
            stack, visited = [b], set()
            while stack:
                cur = stack.pop()
                if cur == a:
                    node = edges[(a, b)]
                    self._flag(
                        "CON004", node,
                        f"lock-order cycle: `{a}` is taken before "
                        f"`{b}` here, but `{b}` is (transitively) taken "
                        f"before `{a}` elsewhere in this module — two "
                        "threads interleaving these paths deadlock")
                    seen.add((a, b))
                    break
                if cur in visited:
                    continue
                visited.add(cur)
                stack.extend(graph.get(cur, ()))

    # -- CON005: cross-context unlocked writes --------------------------

    def _check_cross_context_writes(self):
        by_class: Dict[str, List[Tuple[ast.AST, List[ast.AST]]]] = {}
        for fn, cls, anc in self.functions:
            if cls is not None:
                by_class.setdefault(cls, []).append((fn, anc))
        for cls, fns in by_class.items():
            methods = {fn.name: fn for fn, _a in fns}
            thread_seed = set(self.info.thread_methods.get(cls, ()))
            if not thread_seed:
                continue
            loop_seed = {fn.name for fn, _a in fns
                         if isinstance(fn, ast.AsyncFunctionDef)}
            if not loop_seed:
                continue

            def closure(seed: Set[str]) -> Set[str]:
                out = set(seed)
                changed = True
                while changed:
                    changed = False
                    for name in list(out):
                        fn = methods.get(name)
                        if fn is None:
                            continue
                        for called in _called_names(fn) & set(methods):
                            if called not in out:
                                out.add(called)
                                changed = True
                return out

            thread_ctx = closure(thread_seed)
            loop_ctx = closure(loop_seed) - thread_ctx
            t_writes = self._attr_writes(methods, thread_ctx, cls)
            l_writes = self._attr_writes(methods, loop_ctx, cls)
            for attr in set(t_writes) & set(l_writes):
                node = t_writes[attr]
                self._flag(
                    "CON005", node,
                    f"`self.{attr}` is written from a Thread context "
                    f"({'/'.join(sorted(n for n in thread_ctx if n in methods))})"
                    " AND from event-loop-reachable code without a lock"
                    " — torn/stale writes race; lock both sides or "
                    "annotate the line `# conc: single-writer` with the"
                    " single-writer argument")

    def _attr_writes(self, methods, ctx: Set[str], cls: str
                     ) -> Dict[str, ast.AST]:
        """Unlocked `self.X = ...` write sites in the given methods."""
        out: Dict[str, ast.AST] = {}
        for name in ctx:
            fn = methods.get(name)
            if fn is None:
                continue
            stack: List[Tuple[ast.AST, bool]] = [(fn, False)]
            while stack:
                node, locked = stack.pop()
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) and \
                            child is not fn:
                        continue
                    child_locked = locked
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        if any(self._lock_name(i.context_expr, cls)
                               for i in child.items):
                            child_locked = True
                    if isinstance(child, (ast.Assign, ast.AugAssign)) \
                            and not child_locked:
                        targets = child.targets if isinstance(
                            child, ast.Assign) else [child.target]
                        for t in targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self" and \
                                    not self._annotated(child.lineno):
                                out.setdefault(t.attr, child)
                    stack.append((child, child_locked))
        return out

    # -- CON006b helper -------------------------------------------------

    def _check_thread_lifecycle(self, call: ast.Call, fn):
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return
        # a join() call or `.daemon = True` anywhere in the function is
        # a lifecycle path; otherwise the thread outlives shutdown
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "join":
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon":
                        return
        self._flag(
            "CON006", call,
            "non-daemon Thread started without a join path in this "
            "function — it outlives shutdown and strands interpreter "
            "exit; pass daemon=True or join it")

    def _inside_with(self, target, fn) -> bool:
        for anc in self._ancestors_of(target, fn):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                return True
        return False


# ----------------------------------------------------------------------
# entry point (merged into lint_source by analysis/lint.py)
# ----------------------------------------------------------------------

def check_source(src: str, path: str = "<string>") -> List[Finding]:
    """CON001-CON006 findings for one module's source. Occurrence
    numbering is the CALLER's job (lint.lint_source merges these with
    the TPU findings before assign_occurrences)."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []  # lint.py already reports TPU000 for syntax errors
    return _Checker(tree, path, src.splitlines()).run()
