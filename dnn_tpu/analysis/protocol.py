"""Protocol pass: serving state machines as checked transition tables.

The serving stack carries four small state machines whose bugs have all
historically been REVIEW-round finds, not CI finds: the client circuit
breaker (closed -> open -> half-open; PR 8's review round caught a
half-open probe slot that, once consumed-then-delegated, left the
breaker shedding 100% of traffic forever), connection draining
(serving -> draining -> drained), the chaos supervisor's child
lifecycle (init/up/restarting/crashloop/stopped), and the streamed
relay's per-microbatch ACCEPT_WINDOW protocol (sent -> accepted ->
answered).

This module declares each machine as a literal transition table and
model-checks it, then cross-checks the table against the CODE:

  PRO001  every state reachable from the initial state;
  PRO002  no absorbing non-terminal state (the "sheds traffic forever"
          bug class: a state you can enter but never leave);
  PRO003  every code transition SITE maps to a declared edge — sites
          are state-attr assignments (`self._state = "open"`),
          flight-event records (`flight.record("supervisor_restart")`)
          and protocol status constructors (`ack_status(...)`), found
          by a pure-AST scan of the machine's module;
  PRO004  every declared edge has at least one code site (a stale edge
          promises behavior the implementation no longer has).

Findings ride the same fingerprint/baseline/gate machinery as the lint
and program passes. Pure stdlib + ast — no jax, no imports of the code
under check.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dnn_tpu.analysis.findings import Finding

__all__ = ["Edge", "Machine", "MACHINES", "REPLICA", "ROUTER",
           "KVLEASE", "check_machine", "check_machine_sites",
           "run_protocol_audit"]


@dataclasses.dataclass(frozen=True)
class Edge:
    src: str
    event: str
    dst: str


@dataclasses.dataclass(frozen=True)
class Machine:
    """One protocol state machine plus where its code transitions live.

    Site sources (all optional; a machine may use several):
      * `state_attr` + `cls`: assignments `self.<state_attr> = "lit"`
        inside class `cls` — each literal must be the initial state or
        the dst of a declared edge;
      * `event_kinds`: flight-recorder kinds treated as transition
        events — each `record("<kind>")` call in `module` must map to a
        declared edge's event;
      * `call_events`: function-name -> event map for protocol status
        constructors (`ack_status` -> "ack").
    """

    name: str
    states: Tuple[str, ...]
    initial: str
    edges: Tuple[Edge, ...]
    terminal: Tuple[str, ...] = ()
    module: str = ""  # repo-relative path scanned for sites
    cls: str = ""
    state_attr: str = ""
    event_kinds: Tuple[str, ...] = ()
    call_events: Tuple[Tuple[str, str], ...] = ()


# ----------------------------------------------------------------------
# the declared machines — these tables ARE the protocol documentation;
# edit them together with the code they describe (PRO003/PRO004 enforce
# the correspondence in both directions)
# ----------------------------------------------------------------------

BREAKER = Machine(
    name="circuit_breaker",
    states=("closed", "open", "half_open"),
    initial="closed",
    edges=(
        # threshold consecutive terminal failures trip the breaker
        Edge("closed", "circuit_open", "open"),
        # cooldown elapsed: exactly one probe may proceed
        Edge("open", "circuit_half_open", "half_open"),
        # the probe succeeded (record(True) from any non-closed state
        # closes — success is the universal reset)
        Edge("half_open", "circuit_close", "closed"),
        Edge("open", "circuit_close", "closed"),
        # the probe failed: reopen with a doubled cooldown
        Edge("half_open", "circuit_reopen", "open"),
        # the probe slot was consumed but the call DELEGATED elsewhere:
        # give the slot back un-judged, cooldown pre-elapsed. THIS edge
        # is the PR 8 review fix — without it half_open had no exit
        # when the delegate ran its own allow/record cycle, and the
        # breaker shed 100% of traffic forever (PRO002 on the table
        # minus this edge reproduces the bug as a model-check failure)
        Edge("half_open", "release", "open"),
    ),
    module="dnn_tpu/comm/client.py",
    cls="CircuitBreaker",
    state_attr="_state",
    event_kinds=("circuit_open", "circuit_half_open", "circuit_close",
                 "circuit_reopen"),
)

SUPERVISOR = Machine(
    name="supervisor",
    states=("init", "up", "restarting", "crashloop", "stopped"),
    initial="init",
    terminal=("stopped",),
    edges=(
        Edge("init", "launch", "up"),
        # child exited / was condemned wedged -> the restart path
        Edge("up", "stage_down", "restarting"),
        Edge("up", "stage_wedged", "restarting"),
        # backoff ladder steps stay inside restarting
        Edge("restarting", "supervisor_backoff", "restarting"),
        Edge("restarting", "supervisor_restart", "up"),
        Edge("restarting", "crash_loop", "crashloop"),
        # stop() is legal from every live state
        Edge("init", "stop", "stopped"),
        Edge("up", "stop", "stopped"),
        Edge("restarting", "stop", "stopped"),
        Edge("crashloop", "stop", "stopped"),
    ),
    module="dnn_tpu/chaos/supervisor.py",
    cls="Supervisor",
    state_attr="state",
    event_kinds=("stage_down", "stage_wedged", "supervisor_backoff",
                 "supervisor_restart", "crash_loop"),
)

DRAIN = Machine(
    name="drain",
    states=("serving", "draining", "drained"),
    initial="serving",
    terminal=("drained",),
    edges=(
        # three entry doors, one state: POST /drainz (and the wedged
        # drain policy), SIGTERM, and the worker-level begin
        Edge("serving", "drainz", "draining"),
        Edge("serving", "sigterm_drain", "draining"),
        Edge("serving", "drain_begin", "draining"),
        # queued-but-unadmitted work hands back retriable (stays
        # draining while in-flight decodes finish)
        Edge("draining", "drain_handback", "draining"),
        # the worker finished its pool and exited clean
        Edge("draining", "drain_done", "drained"),
        # the blocking drain() observed the exit (clean or grace-out)
        Edge("draining", "drain_exit", "drained"),
    ),
    module="dnn_tpu/runtime/lm_server.py",
    event_kinds=("drainz", "sigterm_drain", "drain_begin",
                 "drain_handback", "drain_done", "drain_exit"),
)

RELAY_WINDOW = Machine(
    name="relay_accept_window",
    states=("sent", "accepted", "answered"),
    initial="sent",
    terminal=("answered",),
    edges=(
        # eager ack: the frame was decoded into the bounded accept
        # queue — the upstream sender's window advances NOW (and its
        # payload slot frees); compute happens later
        Edge("sent", "ack", "accepted"),
        # the microbatch's result (or its per-item error status, or the
        # stream-level -1 error) rides back tagged res:<seq>
        Edge("accepted", "result", "answered"),
    ),
    module="dnn_tpu/comm/service.py",
    call_events=(("ack_status", "ack"), ("result_status", "result")),
)

REPLICA = Machine(
    name="replica_lifecycle",
    states=("idle", "warming", "serving", "draining", "dead"),
    initial="idle",
    edges=(
        # ReplicaSet.start() launches the supervised child (attach
        # mode enters warming too — the probe loop promotes it)
        Edge("idle", "replica_spawn", "warming"),
        # first healthy probe: the replica takes traffic
        Edge("warming", "replica_ready", "serving"),
        # a child that exits during boot never served
        Edge("warming", "replica_dead", "dead"),
        # drain: admission closed via /drainz; in-flight work finishes,
        # the router's retry-on-sibling picks up the hand-backs
        Edge("serving", "replica_drain", "draining"),
        # exit / kill / consecutive health failures
        Edge("serving", "replica_dead", "dead"),
        Edge("draining", "replica_dead", "dead"),
        # the Supervisor relaunched the child (or an attached endpoint
        # came back): dead is NOT absorbing — without this edge a
        # one-kill fleet would shrink forever (PRO002 on the table
        # minus this edge reproduces exactly that as a model failure)
        Edge("dead", "replica_respawn", "warming"),
    ),
    module="dnn_tpu/control/replicaset.py",
    cls="ReplicaHandle",
    state_attr="state",
    event_kinds=("replica_spawn", "replica_ready", "replica_dead",
                 "replica_drain", "replica_respawn"),
)

ROUTER = Machine(
    name="router",
    states=("init", "serving", "shedding", "draining", "stopped"),
    initial="init",
    terminal=("stopped",),
    edges=(
        Edge("init", "router_start", "serving"),
        # SLO-driven admission turned arrivals away (saturated /
        # burn-rate): an EPISODE state, latched once per episode like
        # pool_exhausted — not per shed request
        Edge("serving", "router_shed", "shedding"),
        Edge("shedding", "router_unshed", "serving"),
        # SIGTERM / drain(): admission closes UNAVAILABLE, in-flight
        # forwards finish on their replicas
        Edge("serving", "router_drain", "draining"),
        Edge("shedding", "router_drain", "draining"),
        Edge("serving", "router_stop", "stopped"),
        Edge("shedding", "router_stop", "stopped"),
        Edge("draining", "router_stop", "stopped"),
        Edge("init", "router_stop", "stopped"),
    ),
    module="dnn_tpu/control/router.py",
    cls="Router",
    state_attr="_state",
    event_kinds=("router_start", "router_shed", "router_unshed",
                 "router_drain", "router_stop"),
)

KVLEASE = Machine(
    name="kvtier_lease",
    states=("offered", "pulling", "adopted", "released", "expired"),
    initial="offered",
    terminal=("released",),
    edges=(
        # the adopter started a grpc fetch of the staged bytes
        Edge("offered", "lease_pull", "pulling"),
        # ingest confirmed (kvack). From `offered` directly too: the
        # shm rung memcpys out of the published segment without ever
        # calling kvfetch, so the first thing the donor hears is the ack
        Edge("offered", "lease_adopt", "adopted"),
        Edge("pulling", "lease_adopt", "adopted"),
        # the donor frees the staging (bytes + shm segment)
        Edge("adopted", "lease_release", "released"),
        # TTL: the adopter died / went quiet — mark expired...
        Edge("offered", "lease_expire", "expired"),
        Edge("pulling", "lease_expire", "expired"),
        # ...and RECLAIM the staged payload. expired is deliberately
        # NON-terminal with this single exit: delete it and every
        # abandoned migration pins its staged blocks (and shm segment)
        # forever — "blocks leak forever" as a PRO002 model failure,
        # pinned both directions by tests/test_kvtier.py
        Edge("expired", "lease_reclaim", "released"),
    ),
    module="dnn_tpu/kvtier/migrate.py",
    cls="Lease",
    state_attr="state",
    event_kinds=("lease_pull", "lease_adopt", "lease_release",
                 "lease_expire", "lease_reclaim"),
)

MACHINES: Tuple[Machine, ...] = (BREAKER, SUPERVISOR, DRAIN,
                                 RELAY_WINDOW, REPLICA, ROUTER, KVLEASE)


# ----------------------------------------------------------------------
# model checks (PRO001 / PRO002)
# ----------------------------------------------------------------------

def check_machine(m: Machine) -> List[Finding]:
    """Table-only checks: declared-state hygiene, reachability from the
    initial state, no absorbing non-terminal state."""
    out: List[Finding] = []
    path = m.module or f"<machine:{m.name}>"

    def finding(rule, message, snippet):
        return Finding(rule=rule, path=path, line=0, message=message,
                       snippet=snippet)

    states = set(m.states)
    if m.initial not in states:
        out.append(finding(
            "PRO001", f"machine `{m.name}`: initial state "
            f"{m.initial!r} is not a declared state", m.initial))
    for e in m.edges:
        for s in (e.src, e.dst):
            if s not in states:
                out.append(finding(
                    "PRO001", f"machine `{m.name}`: edge "
                    f"{e.src}--{e.event}-->{e.dst} names undeclared "
                    f"state {s!r}", f"{e.src}:{e.event}:{e.dst}"))
    # reachability
    adj: Dict[str, Set[str]] = {}
    for e in m.edges:
        adj.setdefault(e.src, set()).add(e.dst)
    seen = {m.initial}
    stack = [m.initial]
    while stack:
        for nxt in adj.get(stack.pop(), ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    for s in m.states:
        if s not in seen:
            out.append(finding(
                "PRO001", f"machine `{m.name}`: state {s!r} is "
                "unreachable from the initial state over the declared "
                "edges", s))
    # absorbing non-terminal
    for s in m.states:
        if s in m.terminal:
            continue
        if not adj.get(s):
            out.append(finding(
                "PRO002", f"machine `{m.name}`: non-terminal state "
                f"{s!r} has no outgoing edge — once entered, the "
                "machine is stuck there forever", s))
    return out


# ----------------------------------------------------------------------
# code-site cross-check (PRO003 / PRO004)
# ----------------------------------------------------------------------

def _callee(call: ast.Call) -> str:
    try:
        return ast.unparse(call.func)
    except Exception:  # pragma: no cover
        return ""


def _collect_sites(m: Machine, tree: ast.Module
                   ) -> List[Tuple[str, str, int, str]]:
    """-> [(site_kind, token, line, snippet_key)] where site_kind is
    'state' (assigned state literal), 'event' (flight kind) or 'call'
    (protocol status constructor's mapped event)."""
    sites: List[Tuple[str, str, int, str]] = []
    call_map = dict(m.call_events)

    # locate the class body for state-attr scoping
    cls_node: Optional[ast.ClassDef] = None
    if m.cls:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == m.cls:
                cls_node = node
                break
    if m.state_attr and cls_node is not None:
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == m.state_attr and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        sites.append(("state", node.value.value,
                                      node.lineno,
                                      f"{m.state_attr}={node.value.value}"))
    if m.event_kinds:
        kinds = set(m.event_kinds)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    _callee(node).endswith("record") and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value in kinds:
                sites.append(("event", node.args[0].value, node.lineno,
                              f"record:{node.args[0].value}"))
    if call_map:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = _callee(node).rsplit(".", 1)[-1]
                if name in call_map:
                    sites.append(("call", call_map[name], node.lineno,
                                  f"{name}()"))
    return sites


def check_machine_sites(m: Machine, repo_root: str,
                        src: Optional[str] = None) -> List[Finding]:
    """Cross-check the machine against its module's code sites. `src`
    overrides reading `m.module` from disk (tests inject fixtures)."""
    if not m.module:
        return []
    if src is None:
        path = os.path.join(repo_root, m.module)
        if not os.path.exists(path):
            return [Finding(
                rule="PRO003", path=m.module, line=0,
                message=f"machine `{m.name}`: module {m.module} not "
                "found — the table points at code that moved",
                snippet=m.module)]
        with open(path, encoding="utf-8") as f:
            src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []  # the lint pass reports TPU000 for this module
    out: List[Finding] = []
    sites = _collect_sites(m, tree)
    dsts = {e.dst for e in m.edges}
    events = {e.event for e in m.edges}
    for kind, token, line, key in sites:
        if kind == "state":
            if token not in m.states:
                out.append(Finding(
                    rule="PRO003", path=m.module, line=line,
                    message=f"machine `{m.name}`: code assigns "
                    f"undeclared state {token!r} to "
                    f"self.{m.state_attr}", snippet=key))
            elif token != m.initial and token not in dsts:
                out.append(Finding(
                    rule="PRO003", path=m.module, line=line,
                    message=f"machine `{m.name}`: code transitions "
                    f"into {token!r} but no declared edge lands there",
                    snippet=key))
        else:  # event / call sites carry the edge's event token
            if token not in events:
                out.append(Finding(
                    rule="PRO003", path=m.module, line=line,
                    message=f"machine `{m.name}`: transition site "
                    f"`{key}` maps to no declared edge event",
                    snippet=key))
    # PRO004: declared edges with no site. State-attr machines witness
    # an edge by its dst assignment; event/call machines by the event.
    seen_states = {t for k, t, _l, _s in sites if k == "state"}
    seen_events = {t for k, t, _l, _s in sites if k in ("event", "call")}
    for e in m.edges:
        witnessed = e.event in seen_events or (
            m.state_attr and e.dst in seen_states)
        if not witnessed:
            out.append(Finding(
                rule="PRO004", path=m.module, line=0,
                message=f"machine `{m.name}`: declared edge "
                f"{e.src}--{e.event}-->{e.dst} has no code transition "
                "site — stale table entry or removed behavior",
                snippet=f"{e.src}:{e.event}:{e.dst}"))
    return out


def run_protocol_audit(repo_root: str, machines: Sequence[Machine] = MACHINES
                       ) -> Tuple[dict, List[Finding]]:
    """The full protocol pass: model-check every declared machine and
    cross-check it against its module. Returns (report, findings) —
    occurrence assignment is the caller's job (the CLI merges these
    with the lint/program findings)."""
    findings: List[Finding] = []
    report = {"machines": []}
    for m in machines:
        f_model = check_machine(m)
        f_sites = check_machine_sites(m, repo_root)
        findings.extend(f_model + f_sites)
        report["machines"].append({
            "name": m.name, "states": len(m.states),
            "edges": len(m.edges), "module": m.module,
            "clean": not (f_model or f_sites)})
    return report, findings
