"""CLI + CI gate: `python -m dnn_tpu.analysis`.

Runs the AST lint over the package (plus any extra paths) and the
device-free program pass over the real entrypoints, diffs everything
against analysis/baseline.json, and exits nonzero on any NEW finding.
Baselined findings are printed (enumerated, not hidden) with their
justification; baseline entries that no longer fire are reported stale.

The pass is CPU-only by design: before jax loads we force the cpu
platform with 8 virtual host devices (the same harness tests/conftest.py
uses), so the program pass traces the mesh entrypoints on any host —
including CI runners and hosts whose TPU tunnel is wedged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    if "jax" in sys.modules:
        # env alone is too late once jax is imported; backend init is
        # lazy though, so the config route still lands (conftest.py's
        # trick, reused here for in-process callers like the test suite)
        import jax

        jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    import dnn_tpu
    from dnn_tpu.analysis.findings import (
        RULES,
        assign_occurrences,
        diff_against_baseline,
        load_baseline,
        render_finding,
    )
    from dnn_tpu.analysis.lint import lint_paths

    pkg_dir = os.path.dirname(os.path.abspath(dnn_tpu.__file__))
    repo_root = os.path.dirname(pkg_dir)
    default_baseline = os.path.join(pkg_dir, "analysis", "baseline.json")

    ap = argparse.ArgumentParser(
        prog="python -m dnn_tpu.analysis",
        description="trace/shard-safety static analyzer (AST lint + "
                    "device-free jaxpr program checks)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the dnn_tpu "
                         "package)")
    ap.add_argument("--baseline", default=default_baseline,
                    help="suppression file (default: "
                         "dnn_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything as new)")
    ap.add_argument("--no-program", action="store_true",
                    help="skip the jaxpr program pass (pure AST lint — "
                         "no jax import)")
    ap.add_argument("--max-len", type=int, default=128,
                    help="cache allocation the decode census sweeps to "
                         "(default 128; benchmarks/STUDIES.md §7 records "
                         "1024)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(justifications of kept entries are preserved; "
                         "new entries get a fill-me-in marker)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, (title, desc) in RULES.items():
            print(f"{rule}  {title}\n    {desc}")
        return 0

    lint_targets = args.paths or [pkg_dir]
    findings = list(lint_paths(lint_targets, repo_root=repo_root))

    program_report = None
    if not args.no_program:
        _force_cpu()
        from dnn_tpu.analysis.program import run_program_audit

        program_report, prog_findings = run_program_audit(
            max_len=args.max_len)
        findings = assign_occurrences(findings + list(prog_findings))

    entries = []
    if not args.no_baseline and os.path.exists(args.baseline):
        entries = load_baseline(args.baseline)
    new, suppressed, stale = diff_against_baseline(findings, entries)

    if args.write_baseline:
        kept = {e["fingerprint"]: e for e in entries}
        out = {"suppressions": [
            kept.get(f.fingerprint, {
                "fingerprint": f.fingerprint,
                "rule": f.rule, "path": f.path, "snippet": f.snippet,
                "justification": "(unjustified — explain why this "
                                 "finding stays, or fix it)",
            }) for f in findings]}
        with open(args.baseline, "w") as fh:
            json.dump(out, fh, indent=2)
            fh.write("\n")
        print(f"wrote {len(findings)} entries to {args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "suppressed": [vars(f) | {"fingerprint": f.fingerprint}
                           for f in suppressed],
            "stale_baseline": stale,
            "program_report": program_report,
        }, indent=2, default=str))
        return 1 if new else 0

    if program_report is not None:
        dec = program_report.get("decode", {})
        print("program pass:")
        print(f"  decode donation: "
              f"{dec.get('donation', {}).get('aliased')}/"
              f"{dec.get('donation', {}).get('expected')} cache buffers "
              "aliased")
        sd = program_report.get("serving_decode", {}).get("variants", {})
        if sd:
            parts = [f"{k}={v['aliased']}/{v['expected']}"
                     for k, v in sd.items()]
            print("  serving decode donation (aliased/donated, zero "
                  "cache-sized copies asserted): " + ", ".join(parts))
        bc = dec.get("bucketed_census", {})
        nc = dec.get("naive_census", {})
        print(f"  bucketed decode census: {bc.get('programs')} programs "
              f"for {bc.get('calls')} steps (ladder bound "
              f"{bc.get('bound')}; naive exact-length dispatch: "
              f"{nc.get('programs')})")
        pipe = program_report.get("pipeline", {})
        print(f"  pipeline stage collective signature: "
              f"{pipe.get('collective_signature')}")
        tp = program_report.get("transport", {})
        print(f"  transport hop program ({tp.get('stages')} stages) "
              f"collective signature: {tp.get('collective_signature')}")
        eng = program_report.get("engine", {})
        print(f"  engine[{eng.get('runtime')}] batch census: "
              f"{eng.get('batch_census', {}).get('programs')} programs "
              f"/ {eng.get('batch_census', {}).get('calls')} batch "
              "shapes")
    if suppressed:
        just = {e["fingerprint"]: e.get("justification", "")
                for e in entries}
        print(f"\n{len(suppressed)} baseline-suppressed finding(s) "
              "(known, justified, NOT hidden):")
        for f in suppressed:
            print(f"  {f.path}:{f.line} {f.rule} — "
                  f"{just.get(f.fingerprint, '')}")
    if stale:
        print(f"\n{len(stale)} stale baseline entr(y/ies) — the finding "
              "no longer fires; delete from baseline.json:")
        for e in stale:
            print(f"  {e['fingerprint']} ({e.get('path', '?')})")
    if new:
        print(f"\n{len(new)} NEW finding(s):")
        for f in new:
            print(render_finding(f))
        print("\nFAIL: new findings above are not in the baseline. Fix "
              "them, or (with a written justification) add them to "
              f"{args.baseline}.")
        return 1
    print(f"\nOK: no new findings ({len(findings)} total, "
          f"{len(suppressed)} baselined).")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `... | head` closed stdout mid-report
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
