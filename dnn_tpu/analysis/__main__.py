"""CLI + CI gate: `python -m dnn_tpu.analysis`.

Runs the AST lint (trace/shard TPU rules + concurrency CON rules +
sharding SHD rules) over the package (plus any extra paths), the
protocol state-machine pass over the declared serving machines, the
device-free program pass over the real entrypoints, and the sharded-
program audit (shardcheck: memory bill, contract conformance,
allocation-sized collectives over the zero1/llama/pipeline/moe
programs), diffs everything against analysis/baseline.json, and exits
nonzero on any NEW finding.
Baselined findings are printed (enumerated, not hidden) with their
justification; baseline entries that no longer fire are reported stale.
`--diff REV` restricts the lint to package files changed since REV;
`--format sarif` emits SARIF 2.1.0 for CI annotation.

The pass is CPU-only by design: before jax loads we force the cpu
platform with 8 virtual host devices (the same harness tests/conftest.py
uses), so the program pass traces the mesh entrypoints on any host —
including CI runners and hosts whose TPU tunnel is wedged.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def changed_files(rev: str, repo_root: str):
    """Repo-relative .py files changed between `rev` and the working
    tree (committed + staged + unstaged; deleted files excluded) — the
    `--diff` CI-annotation mode's file set."""
    out = subprocess.run(
        ["git", "-C", repo_root, "diff", "--name-only", rev, "--",
         "*.py"],
        capture_output=True, text=True, check=True).stdout
    files = []
    for rel in out.splitlines():
        rel = rel.strip()
        if rel and os.path.exists(os.path.join(repo_root, rel)):
            files.append(rel)
    return files


def sarif_report(new, suppressed, entries) -> dict:
    """SARIF 2.1.0 document for CI annotation (--format sarif): new
    findings as `error` results, baseline-suppressed ones carried as
    `note`s with their justification as an external suppression —
    enumerated, not hidden, same policy as the text report."""
    from dnn_tpu.analysis.findings import RULES

    just = {e["fingerprint"]: e.get("justification", "") for e in entries}
    used = sorted({f.rule for f in list(new) + list(suppressed)})
    rules = [{
        "id": rule,
        "shortDescription": {"text": RULES.get(rule, (rule, ""))[0]},
        "fullDescription": {"text": RULES.get(rule, ("", ""))[1]},
    } for rule in used]
    rule_index = {r: i for i, r in enumerate(used)}

    def result(f, *, suppressed_by=None):
        res = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": "note" if suppressed_by is not None else "error",
            "message": {"text": f.message},
            "locations": [{"physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(f.line, 1)},
            }}],
            "partialFingerprints": {"dnnTpuAnalysis/v1": f.fingerprint},
        }
        if suppressed_by is not None:
            res["suppressions"] = [{"kind": "external",
                                    "justification": suppressed_by}]
        return res

    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "dnn_tpu.analysis",
                "informationUri": "dnn_tpu/analysis",
                "rules": rules,
            }},
            "results": [result(f) for f in new] + [
                result(f, suppressed_by=just.get(f.fingerprint, ""))
                for f in suppressed],
        }],
    }


def _force_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    if "jax" in sys.modules:
        # env alone is too late once jax is imported; backend init is
        # lazy though, so the config route still lands (conftest.py's
        # trick, reused here for in-process callers like the test suite)
        import jax

        jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    import dnn_tpu
    from dnn_tpu.analysis.findings import (
        RULES,
        assign_occurrences,
        diff_against_baseline,
        load_baseline,
        render_finding,
    )
    from dnn_tpu.analysis.lint import lint_paths

    pkg_dir = os.path.dirname(os.path.abspath(dnn_tpu.__file__))
    repo_root = os.path.dirname(pkg_dir)
    default_baseline = os.path.join(pkg_dir, "analysis", "baseline.json")

    ap = argparse.ArgumentParser(
        prog="python -m dnn_tpu.analysis",
        description="trace/shard-safety static analyzer (AST lint + "
                    "device-free jaxpr program checks)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the dnn_tpu "
                         "package)")
    ap.add_argument("--baseline", default=default_baseline,
                    help="suppression file (default: "
                         "dnn_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything as new)")
    ap.add_argument("--no-program", action="store_true",
                    help="skip the jaxpr program pass (pure AST lint — "
                         "no jax import)")
    ap.add_argument("--no-protocol", action="store_true",
                    help="skip the protocol state-machine pass "
                         "(analysis/protocol.py)")
    ap.add_argument("--diff", metavar="REV", default=None,
                    help="changed-files-only mode: lint only the "
                         "PACKAGE .py files that differ from REV (git "
                         "diff REV, filtered to dnn_tpu/ — the same "
                         "scope as the default gate; tests/benchmarks "
                         "plant hazard fixtures on purpose); implies "
                         "--no-program and skips stale-baseline "
                         "reporting (most entries legitimately don't "
                         "fire on a partial file set)")
    ap.add_argument("--format", choices=("text", "sarif"),
                    default="text",
                    help="report format; sarif emits a SARIF 2.1.0 "
                         "document on stdout for CI annotation")
    ap.add_argument("--max-len", type=int, default=128,
                    help="cache allocation the decode census sweeps to "
                         "(default 128; benchmarks/STUDIES.md §7 records "
                         "1024)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(justifications of kept entries are preserved; "
                         "new entries get a fill-me-in marker)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, (title, desc) in RULES.items():
            print(f"{rule}  {title}\n    {desc}")
        return 0

    if args.diff is not None:
        # changed-files-only (CI annotation on a PR diff): the AST +
        # concurrency lints are per-file-sound, so a partial file set
        # is exact for them; the whole-program jaxpr pass is not and
        # is skipped (run the full gate for it)
        pkg_rel = os.path.basename(pkg_dir)
        try:
            lint_targets = [
                os.path.join(repo_root, rel)
                for rel in changed_files(args.diff, repo_root)
                if rel == pkg_rel or rel.startswith(pkg_rel + "/")]
        except subprocess.CalledProcessError as e:
            print(f"--diff {args.diff}: git diff failed: "
                  f"{e.stderr or e}", file=sys.stderr)
            return 2
        args.no_program = True
    else:
        lint_targets = args.paths or [pkg_dir]
    findings = list(lint_paths(lint_targets, repo_root=repo_root))

    protocol_report = None
    if not args.no_protocol:
        # protocol pass: pure-AST over the declared machines' modules —
        # whole-repo-sound and cheap, so it runs even in --diff mode
        from dnn_tpu.analysis.protocol import run_protocol_audit

        protocol_report, proto_findings = run_protocol_audit(repo_root)
        findings = assign_occurrences(findings + list(proto_findings))

    program_report = None
    shard_report = None
    if not args.no_program:
        _force_cpu()
        from dnn_tpu.analysis.program import run_program_audit
        from dnn_tpu.analysis.shardcheck import run_shard_audit

        program_report, prog_findings = run_program_audit(
            max_len=args.max_len)
        shard_report, shard_findings = run_shard_audit()
        findings = assign_occurrences(
            findings + list(prog_findings) + list(shard_findings))

    entries = []
    if not args.no_baseline and os.path.exists(args.baseline):
        entries = load_baseline(args.baseline)
    new, suppressed, stale = diff_against_baseline(findings, entries)

    if args.write_baseline:
        kept = {e["fingerprint"]: e for e in entries}
        out = {"suppressions": [
            kept.get(f.fingerprint, {
                "fingerprint": f.fingerprint,
                "rule": f.rule, "path": f.path, "snippet": f.snippet,
                "justification": "(unjustified — explain why this "
                                 "finding stays, or fix it)",
            }) for f in findings]}
        with open(args.baseline, "w") as fh:
            json.dump(out, fh, indent=2)
            fh.write("\n")
        print(f"wrote {len(findings)} entries to {args.baseline}")
        return 0

    if args.diff is not None:
        stale = []  # partial file set: silence is expected, not stale

    if args.format == "sarif":
        print(json.dumps(sarif_report(new, suppressed, entries),
                         indent=2))
        return 1 if new else 0

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "suppressed": [vars(f) | {"fingerprint": f.fingerprint}
                           for f in suppressed],
            "stale_baseline": stale,
            "program_report": program_report,
            "shard_report": shard_report,
            "protocol_report": protocol_report,
        }, indent=2, default=str))
        return 1 if new else 0

    if protocol_report is not None:
        mk = protocol_report["machines"]
        print("protocol pass: "
              + ", ".join(f"{m['name']}({m['states']}s/{m['edges']}e"
                          f"{'' if m['clean'] else ' DRIFT'})"
                          for m in mk))
    if program_report is not None:
        dec = program_report.get("decode", {})
        print("program pass:")
        print(f"  decode donation: "
              f"{dec.get('donation', {}).get('aliased')}/"
              f"{dec.get('donation', {}).get('expected')} cache buffers "
              "aliased")
        sd = program_report.get("serving_decode", {}).get("variants", {})
        if sd:
            parts = [f"{k}={v['aliased']}/{v['expected']}"
                     for k, v in sd.items()]
            print("  serving decode donation (aliased/donated, zero "
                  "cache-sized copies asserted): " + ", ".join(parts))
        bc = dec.get("bucketed_census", {})
        nc = dec.get("naive_census", {})
        print(f"  bucketed decode census: {bc.get('programs')} programs "
              f"for {bc.get('calls')} steps (ladder bound "
              f"{bc.get('bound')}; naive exact-length dispatch: "
              f"{nc.get('programs')})")
        pipe = program_report.get("pipeline", {})
        print(f"  pipeline stage collective signature: "
              f"{pipe.get('collective_signature')}")
        tp = program_report.get("transport", {})
        print(f"  transport hop program ({tp.get('stages')} stages) "
              f"collective signature: {tp.get('collective_signature')}")
        eng = program_report.get("engine", {})
        print(f"  engine[{eng.get('runtime')}] batch census: "
              f"{eng.get('batch_census', {}).get('programs')} programs "
              f"/ {eng.get('batch_census', {}).get('calls')} batch "
              "shapes")
    if shard_report is not None:
        print("shard pass:")
        for name in ("zero1", "llama_dp_tp"):
            sec = shard_report.get(name, {})
            bill = sec.get("bill", {}).get("params", {})
            col = sec.get("collectives", {})
            line = (f"  {name}{sec.get('mesh')}: params bill "
                    f"{bill.get('actual_per_device_bytes')}/"
                    f"{bill.get('expected_per_device_bytes')} B/device "
                    f"({len(bill.get('mismatches', []))} mismatches), "
                    f"largest collective "
                    f"{col.get('largest_frac', 0):.2f}x of "
                    "tree-frac threshold "
                    f"{col.get('threshold_frac')}")
            print(line)
        z = shard_report.get("zero1", {})
        don = z.get("donation", {})
        print(f"  zero1 donation under NamedSharding: "
              f"{don.get('aliased')}/{don.get('expected')} sharded "
              "buffers aliased; sharding census "
              f"{z.get('sharding_census', {}).get('programs')} programs"
              f"/{z.get('sharding_census', {}).get('calls')} calls "
              f"(bound {z.get('sharding_census', {}).get('bound')})")
        pl = shard_report.get("pipeline_stacked", {})
        moe = shard_report.get("moe_ep", {})
        print(f"  stacked pipeline placement bill: "
              f"{pl.get('bill', {}).get('stacked', {}).get('mismatches')}"
              " mismatches; moe EP axis signature: "
              f"{moe.get('collective_signature')}")
    if suppressed:
        just = {e["fingerprint"]: e.get("justification", "")
                for e in entries}
        print(f"\n{len(suppressed)} baseline-suppressed finding(s) "
              "(known, justified, NOT hidden):")
        for f in suppressed:
            print(f"  {f.path}:{f.line} {f.rule} — "
                  f"{just.get(f.fingerprint, '')}")
    if stale:
        print(f"\n{len(stale)} stale baseline entr(y/ies) — the finding "
              "no longer fires; delete from baseline.json:")
        for e in stale:
            print(f"  {e['fingerprint']} ({e.get('path', '?')})")
    if new:
        print(f"\n{len(new)} NEW finding(s):")
        for f in new:
            print(render_finding(f))
        print("\nFAIL: new findings above are not in the baseline. Fix "
              "them, or (with a written justification) add them to "
              f"{args.baseline}.")
        return 1
    print(f"\nOK: no new findings ({len(findings)} total, "
          f"{len(suppressed)} baselined).")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `... | head` closed stdout mid-report
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
