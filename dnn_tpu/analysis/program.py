"""Device-free program pass: jaxpr/lowering checks on REAL entrypoints.

Where analysis/lint.py reads source, this module reads programs: it
traces the framework's actual entrypoints (engine predict, the solo and
bucketed decode steps, the SPMD pipeline from parallel/pipeline.py) with
abstract shapes — `jax.eval_shape` avals, `jax.make_jaxpr`,
`jax.jit(...).lower(...)` — so auditing a 1.1B-parameter decode step
costs no weights, no devices, and no compile. It extends
utils/hlo_audit.py (which answers "does the lowered step copy the
cache?") with four whole-program questions:

  PRG001  do cond/switch branches issue identical collective sequences?
          (the jaxpr-level SPMD-deadlock check — catches dynamically
          built branch lists, e.g. spmd_pipeline's per-stage
          `lax.switch`, that the AST pass cannot resolve)
  PRG002  are allocation-sized constants baked into the program?
          (a closed-over concrete array = a private copy per compile)
  PRG003  do decode steps donate their cache? (aliasing audit on the
          lowered StableHLO — an undonated cache is a full copy/step)
  PRG004  how many distinct programs does a shape sweep compile?
          (recompile census; the bucketed decode must stay within its
          ladder bound)

CPU-only by design: jit signatures are (avals + static args), identical
on every backend, and StableHLO aliasing annotations are emitted before
any backend pipeline runs — so every verdict here transfers to TPU.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.analysis.findings import Finding, assign_occurrences
from dnn_tpu.utils.hlo_audit import (
    count_aliased,
    count_cache_sized,
    gpt_decode_step,
    lowered_text,
)

__all__ = [
    "collective_signature", "axis_collective_signature",
    "check_branch_collectives", "baked_constants",
    "donation_report", "recompile_census", "audit_decode_paths",
    "audit_serving_decode", "audit_pipeline_programs", "audit_engine",
    "check_decode_program", "run_program_audit",
]

_COLLECTIVE_PRIMS = {
    "psum", "ppermute", "all_gather", "all_to_all", "psum_scatter",
    "pmin", "pmax", "reduce_scatter", "collective_permute", "pgather",
    "all_gather_invariant", "psum_invariant",
}
# branch-holding / body-holding primitive params to recurse into
_SUBJAXPR_PARAMS = ("branches", "jaxpr", "call_jaxpr", "cond_jaxpr",
                    "body_jaxpr", "fun_jaxpr")


def _sub_jaxprs(eqn):
    """(param_name, jaxpr) pairs for every sub-program of one equation."""
    out = []
    for name in _SUBJAXPR_PARAMS:
        v = eqn.params.get(name)
        if v is None:
            continue
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for sub in vs:
            j = getattr(sub, "jaxpr", sub)  # ClosedJaxpr -> Jaxpr
            if hasattr(j, "eqns"):
                out.append((name, j))
    return out


def collective_signature(jaxpr) -> Tuple[str, ...]:
    """Ordered tuple of collective primitive names in a jaxpr, recursing
    into scan/while/pjit/cond sub-programs in equation order. Two SPMD
    programs with different signatures cannot be deadlock-free on the
    same mesh step."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    out: List[str] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            out.append(eqn.primitive.name)
        for _, sub in _sub_jaxprs(eqn):
            out.extend(collective_signature(sub))
    return tuple(out)


def _eqn_axes(eqn) -> Tuple[str, ...]:
    """The mesh axes one collective equation operates over. psum-family
    primitives carry `axes`; gather/permute/scatter carry `axis_name`
    (either may be a bare name or a tuple)."""
    v = eqn.params.get("axes", eqn.params.get("axis_name"))
    if v is None:
        return ()
    if not isinstance(v, (tuple, list)):
        v = (v,)
    return tuple(str(a) for a in v)


def axis_collective_signature(jaxpr) -> Tuple[str, ...]:
    """collective_signature with the mesh axes each collective operates
    over: `psum@data`, `ppermute@stage`, ... Two branches can agree on
    primitive NAMES while reducing over different axes — that still
    deadlocks a real mesh, so PRG001 compares THIS signature."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    out: List[str] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            axes = ",".join(_eqn_axes(eqn))
            out.append(f"{eqn.primitive.name}@{axes}" if axes
                       else eqn.primitive.name)
        for _, sub in _sub_jaxprs(eqn):
            out.extend(axis_collective_signature(sub))
    return tuple(out)


def check_branch_collectives(jaxpr, where: str = "<program>"
                             ) -> List[Finding]:
    """PRG001: walk a jaxpr; at every cond/switch equation, compare the
    MESH-AXIS-AWARE collective signature of each branch. The stage
    programs of spmd_pipeline ARE these branches (lax.switch on the
    stage coord), so this is the 'collective sequences identical across
    pipeline stage programs' check of the paper-scale SPMD contract —
    and since ISSUE 17 it also fails two branches that agree on
    primitive names but reduce over DIFFERENT mesh axes (a dropped or
    re-axed psum deadlocks ranks just the same)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    findings: List[Finding] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "cond":
            sigs = [axis_collective_signature(b)
                    for b in eqn.params.get("branches", ())]
            if len(set(sigs)) > 1:
                detail = " vs ".join(
                    "(" + (", ".join(s) or "none") + ")" for s in sigs)
                findings.append(Finding(
                    rule="PRG001", path=where, line=0,
                    message=f"cond/switch branches have different "
                            f"collective sequences: {detail}",
                    snippet=f"branches={len(sigs)}"))
        for _, sub in _sub_jaxprs(eqn):
            findings.extend(check_branch_collectives(sub, where))
    return findings


def baked_constants(closed_jaxpr, *, min_bytes: int = 1 << 20,
                    where: str = "<program>") -> List[Finding]:
    """PRG002: constants (closed-over concrete arrays) at allocation
    scale. Weights and caches must arrive as ARGUMENTS — a baked const
    is copied into every compiled executable that closes over it."""
    findings = []
    for c in getattr(closed_jaxpr, "consts", ()):
        nbytes = getattr(c, "nbytes", None)
        if nbytes is None and hasattr(c, "size"):
            nbytes = int(np.asarray(c).nbytes)
        if nbytes and nbytes >= min_bytes:
            findings.append(Finding(
                rule="PRG002", path=where, line=0,
                message=f"program bakes a {nbytes/1e6:.1f} MB constant "
                        f"(shape {getattr(c, 'shape', '?')}); pass it as "
                        "an argument instead of closing over it",
                snippet=f"const{tuple(getattr(c, 'shape', ()))}"))
    return findings


def donation_report(fn, args, donate_argnums: Sequence[int],
                    *, where: str = "<program>",
                    expect_aliased: Optional[int] = None) -> dict:
    """PRG003: lower jit(fn, donate_argnums=...) at `args` (arrays or
    ShapeDtypeStructs) and count aliased inputs in the StableHLO
    (`tf.aliasing_output` annotations). Returns
    {aliased, expected, findings}; a gap means the runtime pays a full
    copy of every un-aliased donated buffer per step."""
    text = lowered_text(fn, *args, donate_argnums=tuple(donate_argnums))
    aliased = count_aliased(text)
    if expect_aliased is None:
        expect_aliased = sum(
            len(jax.tree.leaves(args[i])) for i in donate_argnums)
    findings = []
    if aliased < expect_aliased:
        findings.append(Finding(
            rule="PRG003", path=where, line=0,
            message=f"only {aliased}/{expect_aliased} donated buffers "
                    "are aliased to outputs in the lowered program — "
                    "un-aliased donations copy every step",
            snippet=f"aliased={aliased} expected={expect_aliased}"))
    return {"aliased": aliased, "expected": expect_aliased,
            "findings": findings}


# ----------------------------------------------------------------------
# recompile census
# ----------------------------------------------------------------------

def _aval_signature(args) -> Tuple:
    """What jit keys its program cache on (per arg: shape+dtype, plus
    the declared sharding when the aval carries one — identical avals
    under DIFFERENT shardings compile different partitioned programs,
    so the sharded-program census must count them separately)."""
    leaves = jax.tree.leaves(
        jax.tree.map(lambda l: jax.ShapeDtypeStruct(
            jnp.shape(l), getattr(l, "dtype", jnp.result_type(l)),
            sharding=getattr(l, "sharding", None)), args),
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
    return tuple((tuple(l.shape), str(l.dtype),
                  str(l.sharding) if l.sharding is not None else None)
                 for l in leaves)


def recompile_census(arg_sets: Sequence[Tuple], *, bound: Optional[int]
                     = None, where: str = "<program>") -> dict:
    """PRG004: distinct jit program signatures across a shape sweep.
    `arg_sets` is a sequence of argument tuples (arrays or
    ShapeDtypeStructs); the census counts unique aval signatures — one
    compile each. `bound` asserts the documented program-count ceiling
    (e.g. the bucket ladder length)."""
    sigs = {}
    for args in arg_sets:
        sigs.setdefault(_aval_signature(args), []).append(args)
    report = {"calls": len(arg_sets), "programs": len(sigs),
              "bound": bound, "findings": []}
    if bound is not None and len(sigs) > bound:
        report["findings"].append(Finding(
            rule="PRG004", path=where, line=0,
            message=f"shape sweep compiles {len(sigs)} distinct programs,"
                    f" over the documented bound {bound}",
            snippet=f"programs={len(sigs)} bound={bound}"))
    return report


# ----------------------------------------------------------------------
# entrypoint audits
# ----------------------------------------------------------------------

def _tiny_gpt_cfg():
    from dnn_tpu.models.gpt import GPTConfig

    return GPTConfig(vocab_size=64, block_size=128, n_layer=2, n_head=2,
                     n_embd=32)


def audit_decode_paths(cfg=None, *, batch: int = 2,
                       max_len: int = 128) -> dict:
    """Solo + bucketed decode steps (runtime/generate.py,
    runtime/decode_buckets.py): donation coverage, baked constants, and
    the recompile census that certifies the PR-1 bucketing contract —
    decode programs bounded by the LADDER length, vs one program per
    live length for exact-shape dispatch.
    """
    from dnn_tpu.runtime.decode_buckets import bucket_for, bucket_ladder

    cfg = cfg or _tiny_gpt_cfg()
    findings: List[Finding] = []

    step, args, layer_elems = gpt_decode_step(
        cfg, batch=batch, s_max=max_len)

    # PRG003: the decode step must alias its donated cache leaves
    don = donation_report(step, args, (1,),
                          where="runtime/generate.decode_step")
    findings += don["findings"]

    # PRG002: nothing cache- or weight-scale may be baked in
    closed = jax.make_jaxpr(step)(*args)
    findings += baked_constants(
        closed, min_bytes=max(layer_elems * 4, 1 << 20),
        where="runtime/generate.decode_step")

    # hlo_audit extension: the StableHLO must not transpose/copy the
    # cache outside the donated in-place update (PR-1 regression, now
    # part of the standing audit)
    text = lowered_text(step, *args, donate_argnums=(1,))
    copies = count_cache_sized(text, layer_elems)
    if copies:
        # hardened from transpose-only (ISSUE 6): with the cache donated,
        # the StableHLO must carry ZERO cache-sized copies too — a copy
        # here is a program-demanded materialization no backend can elide
        findings.append(Finding(
            rule="PRG002", path="runtime/generate.decode_step", line=0,
            message=f"decode step materializes cache-sized op(s) in "
                    f"StableHLO beyond the donated in-place update: "
                    f"{copies}",
            snippet=str(copies)))

    # PRG004: bucketed decode — simulate a generate() from prompt 8 to
    # max_len and count the step programs the bucket dispatch compiles.
    # Cache avals for each live length derive from the max_len template
    # (position axis 3, the codec layout contract) — one eval_shape
    # total instead of one per swept length.
    def at_len(n):
        prepared_s, cache_s, tok_s, pos_s = args

        def resize(l):
            s = list(l.shape)
            s[3] = n
            return jax.ShapeDtypeStruct(tuple(s), l.dtype)

        return (prepared_s, jax.tree.map(resize, cache_s), tok_s, pos_s)

    ladder = bucket_ladder(max_len)
    prompt = 8
    sweep = range(prompt, max_len - 1)
    census = recompile_census(
        [at_len(bucket_for(ladder, pos + 1)) for pos in sweep],
        bound=len(ladder),
        where="runtime/decode_buckets.make_bucketed_generate")
    findings += census["findings"]

    naive = recompile_census(
        [at_len(pos + 1) for pos in sweep],
        where="naive exact-length dispatch (counterfactual)")

    return {
        "donation": {k: don[k] for k in ("aliased", "expected")},
        "stablehlo_cache_ops": copies,
        "bucketed_census": {k: census[k]
                            for k in ("calls", "programs", "bound")},
        "naive_census": {k: naive[k] for k in ("calls", "programs")},
        "ladder": list(ladder),
        "findings": findings,
    }


def check_decode_program(name, jit_fn, args, donate_idx, layer_elems,
                         *, where_prefix: str = "runtime/serving.decode"
                         ) -> Tuple[dict, List[Finding]]:
    """Lower ONE serving decode-family program at `args` and apply the
    ISSUE 6 gate to it: (a) every leaf of every donated arg must be
    aliased to an output in the StableHLO (an un-aliased donation is a
    silent full copy per step), and (b) zero cache-sized copies/
    transposes beyond the aliased in-place update. Module-level so the
    gate itself is testable: tests/test_overlap.py lowers a
    deliberately un-aliased mixed-step variant through this helper and
    asserts the findings fire."""
    avals = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype), args)
    text = jit_fn.lower(*avals).as_text()
    aliased = count_aliased(text)
    expected = sum(len(jax.tree.leaves(args[i])) for i in donate_idx)
    where = f"{where_prefix}[{name}]"
    findings: List[Finding] = []
    if aliased < expected:
        findings.append(Finding(
            rule="PRG003", path=where, line=0,
            message=f"only {aliased}/{expected} donated buffers are "
                    "aliased to outputs — un-aliased donations copy "
                    "every decode step",
            snippet=f"{name}: aliased={aliased} expected={expected}"))
    copies = count_cache_sized(text, layer_elems)
    if copies:
        findings.append(Finding(
            rule="PRG003", path=where, line=0,
            message=f"decode step materializes cache-sized op(s) "
                    f"beyond the donated in-place update: {copies}",
            snippet=f"{name}: {copies}"))
    return ({"aliased": aliased, "expected": expected,
             "cache_sized_ops": copies}, findings)


def audit_serving_decode(cfg=None, *, slots: int = 2,
                         max_len: int = 128) -> dict:
    """ISSUE 6 donation-coverage GATE over the SERVING decode programs:
    every cache layout the batcher ships (dense f32 / int8 / int4,
    bucketed, paged) plus the speculative step, each lowered at its live
    donate_argnums and checked for (a) FULL aliasing of every donated
    leaf — an un-aliased donation is a silent full copy per step
    (hlo_audit.count_aliased; PRG003) — and (b) ZERO cache-sized
    copies/transposes in the StableHLO beyond the aliased in-place
    update (the PR-1 three-copies-per-step diagnosis, now failed-on
    rather than documented). Exceptions go through the justified
    baseline like every other finding — there are none today.

    Constructor-only cost: the batchers are built at test-preset size
    and their step programs LOWERED (traced), never compiled or run."""
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = cfg or _tiny_gpt_cfg()
    prepared = gpt.prepare_stacked(
        gpt.init(jax.random.PRNGKey(0), cfg), cfg)
    findings: List[Finding] = []
    report: Dict[str, dict] = {}

    def lower_and_check(name, jit_fn, args, donate_idx, layer_elems):
        entry, f = check_decode_program(name, jit_fn, args, donate_idx,
                                        layer_elems)
        findings.extend(f)
        report[name] = entry

    def batcher_args(b):
        return (b._decode_view, b.cache, b.pos, b.tok, b.active, b.keys,
                b._temp, b._topk, b._topp, b._minp, b._rep, b._seen,
                b._bias, b._crow, b._ctable, b._ctrans)

    variants = {
        "dense_f32": {},
        "dense_int8": {"kv_dtype": "int8"},
        "dense_int4": {"kv_dtype": "int4"},
        "bucketed": {"decode_buckets": True},
        "paged": {"kv": "paged"},
        # constrained decoding (ISSUE 16): the grammar DFA walk is
        # carried device state — crow joins the donate set, and the
        # gate must see it aliased (an un-aliased crow would copy per
        # step; the (S, V) ctable/ctrans pools are read-only gathers
        # and must NOT appear as cache-sized copies)
        "dense_constrained": {"allow_constraints": True,
                              "constraint_rows": 8},
        "paged_constrained": {"kv": "paged", "allow_constraints": True,
                              "constraint_rows": 8},
    }
    hd = cfg.n_embd // cfg.n_head
    for name, kw in variants.items():
        b = ContinuousBatcher(cfg, prepared, slots=slots, max_len=max_len,
                              prompt_pad=16, **kw)
        if b._paged:
            layer_elems = (b._allocator.n_blocks * cfg.n_head
                           * b._block_len * hd)
        else:
            layer_elems = slots * cfg.n_head * b._cache_len * hd
        # donated argnums mirror serving.py's jit construction
        # (cache, pos, tok, keys, seen — plus crow when constrained)
        lower_and_check(name, b._decode, batcher_args(b),
                        b._decode_donate, layer_elems)

    # the speculative step (serving_spec.py): both caches + the per-slot
    # vectors it returns must all alias
    from dnn_tpu.runtime.serving_spec import SpeculativeBatcher

    sb = SpeculativeBatcher(cfg, prepared, cfg, prepared, spec_k=2,
                            slots=slots, max_len=max_len, prompt_pad=16)
    sp_args = (sb.prepared, sb.draft_prepared, sb.cache, sb.d_cache,
               sb.tok, sb.pos, sb.active, sb.keys, sb.prev_chunk,
               sb.prev_pos)
    lower_and_check("speculative", sb._spec_step, sp_args,
                    (2, 3, 4, 5, 7, 8, 9),
                    slots * cfg.n_head * max_len * hd)

    # ISSUE 12 — the mixed-step programs: interleaved chunked prefill
    # folds a prompt chunk into the decode program, and the fused
    # admission finish installs + samples + scatters slot state on
    # device. Same gate as every other decode program: FULL aliasing of
    # every donated leaf, zero cache-sized copies.
    p_c = 16

    def ilv_args(b):
        row = b._ilv_new_row()
        chunk = jnp.zeros((1, p_c), jnp.int32)
        base = batcher_args(b)
        m_args = (base[0], base[0]) + base[1:] + (row, chunk,
                                                  jnp.int32(0))
        v = cfg.vocab_size
        nb_max = (b.cache["tables"].shape[-1] if b._paged else 0)
        f_args = (b.cache, row,
                  jnp.zeros((1, p_c, v), jnp.float32),
                  jnp.int32(0), jnp.int32(0),
                  jnp.zeros((2,), jnp.uint32), jnp.zeros((2,), jnp.uint32),
                  b.pos, b.tok, b.active, b.keys, b._temp, b._topk,
                  b._topp, b._minp, b._rep, b._seen, b._bias,
                  jnp.float32(0), jnp.int32(0), jnp.float32(0),
                  jnp.float32(0), jnp.float32(1),
                  jnp.zeros((v,), jnp.bool_),
                  jnp.zeros((v if b._allow_bias else 0,), jnp.float32),
                  jnp.int32(8),
                  jnp.zeros((nb_max,), jnp.int32),
                  b._crow, jnp.int32(0), b._ctable, b._ctrans)
        return m_args, f_args

    for name, kw in {"mixed_dense": {},
                     "mixed_paged": {"kv": "paged"},
                     "mixed_bucketed": {"decode_buckets": True},
                     # ISSUE 16: constrained requests ride the mixed/
                     # overlap hot path — both the mixed step (carried
                     # crow donated+aliased) and the fused finish (crow
                     # scatter-seeded on device) pass the same gate
                     "mixed_constrained": {"allow_constraints": True,
                                           "constraint_rows": 8}}.items():
        b = ContinuousBatcher(cfg, prepared, slots=slots, max_len=max_len,
                              prompt_pad=16, prefill_chunk_tokens=p_c,
                              **kw)
        if b._paged:
            layer_elems = (b._allocator.n_blocks * cfg.n_head
                           * b._block_len * hd)
        else:
            layer_elems = slots * cfg.n_head * b._cache_len * hd
        m_args, f_args = ilv_args(b)
        lower_and_check(name, b._mixed, m_args, b._mixed_donate,
                        layer_elems)
        lower_and_check(name + "_finish", b._ilv_finish, f_args,
                        b._ilv_finish_donate, layer_elems)

    sbm = SpeculativeBatcher(cfg, prepared, cfg, prepared, spec_k=2,
                             slots=slots, max_len=max_len, prompt_pad=16,
                             prefill_chunk_tokens=p_c)
    row = sbm._ilv_new_row()
    d_row = sbm._d_family.init_cache(1, sbm._ilv_row_len,
                                     sbm.d_cache["k"].dtype)
    chunk = jnp.zeros((1, p_c), jnp.int32)
    spm_args = (sbm.prepared, sbm.draft_prepared, sbm.cache, sbm.d_cache,
                sbm.tok, sbm.pos, sbm.active, sbm.keys, sbm.prev_chunk,
                sbm.prev_pos, row, d_row, chunk, jnp.int32(0))
    spec_elems = slots * cfg.n_head * max_len * hd
    lower_and_check("mixed_speculative", sbm._spec_mixed, spm_args,
                    sbm._spec_mixed_donate, spec_elems)
    v = cfg.vocab_size
    spf_args = (sbm.cache, sbm.d_cache, row, d_row,
                jnp.zeros((1, p_c, v), jnp.float32),
                jnp.int32(0), jnp.int32(0),
                jnp.zeros((2,), jnp.uint32), jnp.zeros((2,), jnp.uint32),
                sbm.pos, sbm.tok, sbm.active, sbm.keys, sbm._temp,
                sbm._topk, sbm._topp, sbm._minp, sbm._rep, sbm._seen,
                sbm._bias,
                jnp.float32(0), jnp.int32(0), jnp.float32(0),
                jnp.float32(0), jnp.float32(1),
                jnp.zeros((v,), jnp.bool_),
                jnp.zeros((v if sbm._allow_bias else 0,), jnp.float32),
                jnp.int32(8), jnp.zeros((0,), jnp.int32),
                sbm._crow, jnp.int32(0), sbm._ctable, sbm._ctrans,
                jnp.zeros((sbm.spec_k + 1,), jnp.int32),
                sbm.prev_chunk, sbm.prev_pos)
    lower_and_check("mixed_speculative_finish", sbm._spec_ilv_finish,
                    spf_args, sbm._spec_ilv_finish_donate, spec_elems)

    return {"variants": report, "findings": findings}


def audit_pipeline_programs(num_stages: int = 2, *, feature: int = 8,
                            batch: int = 4) -> dict:
    """spmd_pipeline stage programs (parallel/pipeline.py): trace the
    heterogeneous-stage pipeline on a real mesh and verify every
    lax.switch branch (= every stage program) issues the same collective
    sequence, with no allocation-sized baked constants. Uses abstract
    tracing only — no compile, no execution."""
    from jax.sharding import Mesh

    from dnn_tpu.parallel.mesh import STAGE_AXIS
    from dnn_tpu.parallel.pipeline import spmd_pipeline

    devs = jax.devices()
    if len(devs) < num_stages:
        return {"skipped": f"need {num_stages} devices, have {len(devs)}",
                "findings": []}
    mesh = Mesh(np.array(devs[:num_stages]), (STAGE_AXIS,))

    # two deliberately heterogeneous stages (different widths/params) so
    # the switch branches are non-trivial
    def stage_a(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def stage_b(p, x):
        return x @ p["w"]

    params = [
        {"w": jnp.zeros((feature, feature * 2)),
         "b": jnp.zeros((feature * 2,))},
        {"w": jnp.zeros((feature * 2, feature))},
    ]
    stage_fns = [stage_a, stage_b][:num_stages]
    params = params[:num_stages]

    def run(sp, x):
        return spmd_pipeline(stage_fns, sp, x, mesh=mesh,
                             num_microbatches=2,
                             param_placement="replicated")

    x = jnp.zeros((batch, feature))
    closed = jax.make_jaxpr(run)(tuple(params), x)
    findings = check_branch_collectives(
        closed, "parallel/pipeline.spmd_pipeline")
    findings += baked_constants(
        closed, where="parallel/pipeline.spmd_pipeline")
    sig = collective_signature(closed)

    # PRG004 (ISSUE 17): the pipeline program count. Steps at the same
    # batch shape are ONE program — the stage coordinate and microbatch
    # index are traced, not static — so a repeated-call sweep must stay
    # at exactly one compile. The sharded serving PR cannot silently
    # start multiplying compilations per rung without tripping this.
    x_aval = jax.ShapeDtypeStruct(x.shape, x.dtype)
    p_avals = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), tuple(params))
    census = recompile_census(
        [(p_avals, x_aval)] * 4, bound=1,
        where="parallel/pipeline.spmd_pipeline")
    findings += census["findings"]
    return {"collective_signature": list(sig),
            "stages": num_stages,
            "step_census": {k: census[k]
                            for k in ("calls", "programs", "bound")},
            "findings": findings}


def audit_transport_programs(num_stages: int = 4, *, feature: int = 8,
                             batch: int = 2) -> dict:
    """Device-transport send/recv programs (comm/transport.py
    make_hop_program): the compiled ppermute shuttle that moves a
    mesh-resident activation from stage i to stage i+1 is ONE program
    switching over the hop index — every switch branch must issue the
    IDENTICAL collective sequence (one ppermute) or ranks deadlock on a
    real pod, the same SPMD contract PRG001 enforces on the pipeline's
    stage switch. Traced abstractly on a real mesh — no compile, no
    execution."""
    from jax.sharding import Mesh

    from dnn_tpu.comm.transport import make_hop_program
    from dnn_tpu.parallel.mesh import STAGE_AXIS

    devs = jax.devices()
    if len(devs) < num_stages:
        return {"skipped": f"need {num_stages} devices, have {len(devs)}",
                "findings": []}
    mesh = Mesh(np.array(devs[:num_stages]), (STAGE_AXIS,))
    hop = make_hop_program(mesh, STAGE_AXIS)
    buf = jnp.zeros((num_stages, batch, feature))
    closed = jax.make_jaxpr(lambda h, b: hop(h, b))(jnp.int32(0), buf)
    findings = check_branch_collectives(
        closed, "comm/transport.make_hop_program")
    findings += baked_constants(
        closed, where="comm/transport.make_hop_program")
    # the traced signature concatenates over the switch's branches (one
    # branch per hop): it must be exactly one ppermute PER BRANCH — a
    # branch growing a second collective (or losing its ppermute) is a
    # deadlock on a real mesh even when the branches still AGREE with
    # each other (which check_branch_collectives pins above)
    sig = collective_signature(closed)
    if tuple(sig) != ("ppermute",) * (num_stages - 1):
        findings.append(Finding(
            rule="PRG001", path="comm/transport.make_hop_program", line=0,
            message=f"transport hop program must issue exactly one "
                    f"ppermute per hop branch ({num_stages - 1} hops), "
                    f"traced {list(sig) or 'none'}",
            snippet=f"stages={num_stages}"))

    # PRG004 (ISSUE 17): the hop INDEX is a traced int32 — all
    # num_stages-1 hops of a relay dispatch through ONE switch program.
    # Pin that a full hop sweep compiles exactly one program; a hop
    # index leaking into a static arg would show up here as n-1.
    hop_aval = jax.ShapeDtypeStruct((), jnp.int32)
    buf_aval = jax.ShapeDtypeStruct(buf.shape, buf.dtype)
    census = recompile_census(
        [(hop_aval, buf_aval) for _ in range(num_stages - 1)],
        bound=1, where="comm/transport.make_hop_program")
    findings += census["findings"]
    return {"collective_signature": list(sig),
            "stages": num_stages,
            "hop_census": {k: census[k]
                           for k in ("calls", "programs", "bound")},
            "findings": findings}


def audit_engine(*, batch_sweep: Sequence[int] = (1, 2, 4, 8)) -> dict:
    """PipelineEngine predict (runtime/engine.py): build the smallest
    registered pipeline model end to end, jaxpr-check its compiled
    pipeline callable (collective consistency + baked constants at
    activation scale), and run the recompile census over a batch sweep
    — the serving-shape question ('how many programs does this engine
    hold at steady state?') answered on paper."""
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    config = TopologyConfig.from_dict({
        "nodes": [{"id": "a", "part_index": 0},
                  {"id": "b", "part_index": 1}],
        "num_parts": 2, "model": "mlp", "device_type": "cpu",
        "runtime": "spmd" if len(jax.devices()) >= 2 else "relay",
    })
    engine = PipelineEngine(config)
    findings: List[Finding] = []
    x = engine.spec.example_input()
    sig: List[str] = []
    if engine.runtime == "spmd":
        closed = jax.make_jaxpr(engine._pipeline_fn)(jnp.asarray(x))
        findings += check_branch_collectives(
            closed, "runtime/engine.PipelineEngine.run")
        # engine weights legitimately ride the wrapper closure (packed
        # once at load, passed as jit ARGS inside); only flag consts
        # beyond total weight size — a duplicate would exceed it
        weight_bytes = sum(
            l.size * jnp.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(engine._stage_params))
        findings += baked_constants(
            closed, min_bytes=max(2 * weight_bytes, 1 << 20),
            where="runtime/engine.PipelineEngine.run")
        sig = list(collective_signature(closed))

    x0 = np.asarray(x)
    sweep = []
    for b in batch_sweep:
        xb = np.broadcast_to(x0[:1], (b, *x0.shape[1:]))
        mb = engine._effective_microbatches(b)
        sweep.append((jax.ShapeDtypeStruct(xb.shape, xb.dtype),
                      jax.ShapeDtypeStruct((), jnp.dtype(np.int32)) if mb
                      else None))
    # REPORT-ONLY (bound=None): one program per distinct batch shape is
    # the engine's designed steady state, and an aval-level census can
    # never exceed the sweep size — a bound here would be a gate that
    # cannot fail. The enforced ceiling lives on the decode path, where
    # the ladder gives a real bound below the call count.
    census = recompile_census(
        sweep, where="runtime/engine.PipelineEngine.predict")
    return {"runtime": engine.runtime,
            "collective_signature": sig,
            "batch_census": {k: census[k]
                             for k in ("calls", "programs", "bound")},
            "findings": findings}


def run_program_audit(*, max_len: int = 128) -> Tuple[dict, List[Finding]]:
    """The full device-free program audit. Returns (report, findings)."""
    report: Dict[str, dict] = {}
    findings: List[Finding] = []
    report["decode"] = audit_decode_paths(max_len=max_len)
    report["serving_decode"] = audit_serving_decode(max_len=max_len)
    report["pipeline"] = audit_pipeline_programs()
    report["transport"] = audit_transport_programs()
    report["engine"] = audit_engine()
    for section in report.values():
        findings.extend(section.pop("findings", []))
    return report, assign_occurrences(findings)
