"""AST lint pass: trace/shard-safety rules TPU001-TPU006.

Pure `ast` — no jax import, no tracing, no devices — so the whole ~22k-LoC
package lints in well under a second and the pass can run on user model
code that does not even import cleanly on this host.

What counts as a *traced function* (the scope of TPU001/TPU002/TPU006):

  * a def decorated with jit/pjit/pmap/vmap/grad/shard_map (bare or via
    functools.partial(jax.jit, ...));
  * a def whose NAME is passed to a jax transform / control-flow combinator
    (jax.jit(f), lax.scan(step, ...), jax.shard_map(body, ...), ...);
  * any def lexically nested inside a traced function (closures traced
    with their parent).

Helpers called by traced code but neither decorated, passed, nor nested
(ordinary module-level functions) are NOT treated as traced: whole-package
interprocedural analysis would drown the signal in false positives. The
jaxpr-level program pass (analysis/program.py) covers the composed
programs those helpers end up in.

Taint discipline: a traced function's parameters are traced values
(minus static_argnums/static_argnames); `.shape/.ndim/.dtype/.size`,
`len()`, `isinstance()` and `is`-comparisons launder taint (they are
Python-static under jit). Statements are processed in source order, and
loop bodies are processed TWICE so second-iteration hazards (key reuse,
use-after-donation of a buffer donated in iteration one) surface without
a fixpoint engine.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dnn_tpu.analysis.findings import Finding, assign_occurrences

__all__ = ["lint_source", "lint_paths", "iter_python_files"]

# jax transforms / combinators whose function-valued args are traced
_TRACERS = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "jacfwd",
    "jacrev", "hessian", "scan", "cond", "switch", "while_loop",
    "fori_loop", "associative_scan", "checkpoint", "remat", "eval_shape",
    "make_jaxpr", "named_call", "pallas_call", "custom_jvp", "custom_vjp",
    "linearize", "vjp", "jvp",
}
_SPMD = {"shard_map", "pmap"}
# attributes/calls that read Python-static metadata off a traced value
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "weak_type",
                 "sharding", "aval", "nbytes"}
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "callable", "id",
                 "repr", "str", "format"}
_STATIC_CALL_ATTRS = {"shape", "ndim", "result_type", "issubdtype", "dtype",
                      "tree_structure"}
# device->host converters (TPU002)
_HOST_BUILTINS = {"float", "int", "bool", "complex"}
_HOST_NP_FNS = {"asarray", "array", "ascontiguousarray", "copy"}
_HOST_METHODS = {"item", "tolist", "__array__"}
# jax.random draws; split also CONSUMES its key (the entropy moves into
# the children — drawing from the parent afterwards correlates streams)
# but yields fresh keys. fold_in(key, data) is NON-consuming on purpose:
# deriving per-step keys from one base key with varying data is the
# documented idiom (fold_in(key, i) in a loop must not flag).
_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "clone",
               "wrap_key_data"}
_KEY_NONCONSUMING = {"PRNGKey", "key", "fold_in", "wrap_key_data",
                     "key_data", "key_impl", "default_prng_impl"}
_COLLECTIVES = {"psum", "ppermute", "all_gather", "all_to_all",
                "psum_scatter", "pmean", "pmin", "pmax", "pbroadcast",
                "all_gather_invariant", "axis_index_groups"}
# arg-wrapping namespaces that pin a committed dtype (TPU005 clean form)
_WRAP_PREFIXES = ("jnp.", "jax.", "np.", "numpy.")


def _callee(call: ast.Call) -> str:
    """Dotted name of a call target ('jax.random.split'); '' if dynamic."""
    try:
        return ast.unparse(call.func)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return ""


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _literal_indices(node) -> Tuple[int, ...]:
    """donate_argnums/static_argnums keyword literal -> tuple of ints."""
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(v, int):
        return (v,)
    if isinstance(v, (tuple, list)):
        return tuple(int(i) for i in v if isinstance(i, int))
    return ()


def _literal_names(node) -> Tuple[str, ...]:
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(v, str):
        return (v,)
    if isinstance(v, (tuple, list)):
        return tuple(s for s in v if isinstance(s, str))
    return ()


class _JitInfo:
    """What we know about one jitted callable (by its bound name)."""

    def __init__(self, donate=(), static=(), static_names=()):
        self.donate = tuple(donate)
        self.static = tuple(static)
        self.static_names = tuple(static_names)


def _jit_call_info(call: ast.Call) -> Optional[_JitInfo]:
    """_JitInfo for `jax.jit(f, ...)` / `functools.partial(jax.jit, ...)`
    call nodes; None if the call is not a jit wrapper."""
    name = _last(_callee(call))
    inner = None
    if name == "partial" and call.args:
        first = call.args[0]
        if isinstance(first, (ast.Name, ast.Attribute)) and \
                _last(ast.unparse(first)) in ("jit", "pjit"):
            inner = call
    if name in ("jit", "pjit"):
        inner = call
    if inner is None:
        return None
    donate = static = ()
    static_names = ()
    for kw in inner.keywords:
        if kw.arg == "donate_argnums":
            donate = _literal_indices(kw.value)
        elif kw.arg == "static_argnums":
            static = _literal_indices(kw.value)
        elif kw.arg == "static_argnames":
            static_names = _literal_names(kw.value)
    return _JitInfo(donate, static, static_names)


class _ModuleIndex(ast.NodeVisitor):
    """One walk over the module: traced/spmd function names, jitted
    callables (with donation/static info), and a parent chain for defs."""

    def __init__(self):
        self.traced_names: Set[str] = set()
        self.spmd_names: Set[str] = set()
        # decorated defs, tracked by NODE identity (name-based marking
        # would poison same-named siblings elsewhere in the module)
        self.traced_nodes: Dict[int, _JitInfo] = {}
        self.spmd_nodes: Set[int] = set()
        # bound-name (possibly dotted, e.g. 'self._decode') -> _JitInfo
        self.jitted: Dict[str, _JitInfo] = {}
        # traced function name -> _JitInfo (for static-param untainting)
        self.traced_info: Dict[str, _JitInfo] = {}

    def visit_Call(self, node: ast.Call):
        name = _last(_callee(node))
        if name in _TRACERS or name in _SPMD:
            info = _jit_call_info(node) or _JitInfo()
            for a in node.args:
                targets = a.elts if isinstance(a, (ast.List, ast.Tuple)) \
                    else [a]
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.traced_names.add(t.id)
                        self.traced_info.setdefault(t.id, info)
                        if name in _SPMD:
                            self.spmd_names.add(t.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if isinstance(node.value, ast.Call):
            info = _jit_call_info(node.value)
            # `f = jax.jit(g, ...)`: f is a jitted callable (donation and
            # static args apply at f's call sites); partial(...) alone
            # (no wrapped fn yet) binds at decoration, not here
            if info is not None and _last(_callee(node.value)) != "partial":
                for t in node.targets:
                    if isinstance(t, (ast.Name, ast.Attribute)):
                        self.jitted[ast.unparse(t)] = info
        self.generic_visit(node)

    def _visit_def(self, node):
        for dec in node.decorator_list:
            if isinstance(dec, (ast.Name, ast.Attribute)) and \
                    _last(ast.unparse(dec)) in (_TRACERS | _SPMD):
                info = _JitInfo()
            elif isinstance(dec, ast.Call):
                info = _jit_call_info(dec)
                if info is None and \
                        _last(_callee(dec)) not in (_TRACERS | _SPMD):
                    continue
                info = info or _JitInfo()
            else:
                continue
            self.traced_nodes[id(node)] = info
            self.jitted.setdefault(node.name, info)
            dec_name = _last(ast.unparse(dec)) if isinstance(
                dec, (ast.Name, ast.Attribute)) else _last(_callee(dec))
            if dec_name in _SPMD:
                self.spmd_nodes.add(id(node))
        self.generic_visit(node)

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def


def _walk_functions(tree):
    """Yield (funcdef, ancestors) for every def, outermost first."""
    stack = [(tree, [])]
    while stack:
        node, anc = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, anc
                stack.append((child, anc + [child]))
            else:
                stack.append((child, anc))


# ----------------------------------------------------------------------
# expression taint
# ----------------------------------------------------------------------

def _expr_tainted(node, tainted: Set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Subscript):
        return _expr_tainted(node.value, tainted) or \
            _expr_tainted(node.slice, tainted)
    if isinstance(node, ast.Call):
        callee = _callee(node)
        if _last(callee) in _STATIC_CALLS or \
                _last(callee) in _STATIC_CALL_ATTRS:
            return False
        if any(_expr_tainted(a, tainted) for a in node.args):
            return True
        if any(_expr_tainted(kw.value, tainted) for kw in node.keywords):
            return True
        # method call on a tainted object (x.astype(...), x.sum())
        if isinstance(node.func, ast.Attribute):
            return _expr_tainted(node.func.value, tainted)
        return False
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
        return _expr_tainted(node.left, tainted) or \
            any(_expr_tainted(c, tainted) for c in node.comparators)
    if isinstance(node, (ast.BinOp,)):
        return _expr_tainted(node.left, tainted) or \
            _expr_tainted(node.right, tainted)
    if isinstance(node, ast.UnaryOp):
        return _expr_tainted(node.operand, tainted)
    if isinstance(node, ast.BoolOp):
        return any(_expr_tainted(v, tainted) for v in node.values)
    if isinstance(node, ast.IfExp):
        return _expr_tainted(node.test, tainted) or \
            _expr_tainted(node.body, tainted) or \
            _expr_tainted(node.orelse, tainted)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(_expr_tainted(e, tainted) for e in node.elts)
    if isinstance(node, ast.Dict):
        return any(_expr_tainted(v, tainted)
                   for v in list(node.keys) + list(node.values) if v)
    if isinstance(node, ast.Starred):
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Slice):
        return any(_expr_tainted(p, tainted)
                   for p in (node.lower, node.upper, node.step) if p)
    return False


def _target_names(target) -> List[str]:
    """Flat bound names of an assignment target (dotted for attributes)."""
    if isinstance(target, (ast.Name, ast.Attribute)):
        try:
            return [ast.unparse(target)]
        except Exception:  # pragma: no cover
            return []
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def _collective_sequence(node) -> Tuple[str, ...]:
    """Ordered collective-call names in a subtree (source order)."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = _last(_callee(n))
            if name in _COLLECTIVES:
                out.append(name)
    return tuple(out)


# ----------------------------------------------------------------------
# per-function checker
# ----------------------------------------------------------------------

class _FunctionChecker:
    def __init__(self, fn, path: str, src_lines: List[str],
                 index: _ModuleIndex, *, traced: bool, spmd: bool,
                 local_defs: Dict[str, ast.AST]):
        self.fn = fn
        self.path = path
        self.src_lines = src_lines
        self.index = index
        self.traced = traced
        self.spmd = spmd
        self.local_defs = local_defs
        self.findings: List[Finding] = []
        self._flagged: Set[Tuple[str, int]] = set()

        self.tainted: Set[str] = set()
        if traced:
            info = index.traced_nodes.get(id(fn)) or \
                index.traced_info.get(fn.name) or _JitInfo()
            args = fn.args
            pos = list(args.posonlyargs) + list(args.args)
            for i, a in enumerate(pos):
                if i in info.static or a.arg in info.static_names:
                    continue
                self.tainted.add(a.arg)
            for a in args.kwonlyargs:
                if a.arg not in info.static_names:
                    self.tainted.add(a.arg)
            if args.vararg:
                self.tainted.add(args.vararg.arg)
        self.loopd: Set[str] = set()   # loop-derived Python values
        self.keys_live: Set[str] = set()
        self.keys_consumed: Dict[str, int] = {}
        self.donated: Dict[str, int] = {}  # expr string -> donation line

    # -- emission ------------------------------------------------------

    def _flag(self, rule: str, node, message: str):
        line = getattr(node, "lineno", 0)
        if (rule, line) in self._flagged:
            return
        self._flagged.add((rule, line))
        snippet = ""
        if 0 < line <= len(self.src_lines):
            snippet = self.src_lines[line - 1].strip()
        self.findings.append(Finding(
            rule=rule, path=self.path, line=line, message=message,
            snippet=snippet))

    # -- driver --------------------------------------------------------

    def run(self):
        self._process_body(self.fn.body, in_loop=False)
        return self.findings

    def _process_body(self, body, *, in_loop: bool):
        for stmt in body:
            self._process_stmt(stmt, in_loop=in_loop)

    def _process_stmt(self, stmt, *, in_loop: bool):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are checked as their own functions
        if isinstance(stmt, (ast.If,)):
            if self.traced and _expr_tainted(stmt.test, self.tainted):
                self._flag(
                    "TPU001", stmt,
                    "Python `if` on a traced value inside a traced "
                    "function; use jnp.where / lax.cond")
            if self.spmd:
                self._check_python_branch_collectives(stmt)
            self._scan_exprs(stmt.test, in_loop)
            self._process_body(stmt.body, in_loop=in_loop)
            self._process_body(stmt.orelse, in_loop=in_loop)
            return
        if isinstance(stmt, ast.While):
            if self.traced and _expr_tainted(stmt.test, self.tainted):
                self._flag(
                    "TPU001", stmt,
                    "Python `while` on a traced value inside a traced "
                    "function; use lax.while_loop")
            self._scan_exprs(stmt.test, in_loop)
            for name in self._augassigned_names(stmt.body):
                self.loopd.add(name)
            for _ in range(2):
                self._process_body(stmt.body, in_loop=True)
            self._process_body(stmt.orelse, in_loop=in_loop)
            return
        if isinstance(stmt, ast.For):
            # iterating a tainted value is NOT flagged: statically a dict
            # of arrays (legal, common) and an array (unroll hazard) are
            # indistinguishable, and the dict form dominates real code
            self._scan_exprs(stmt.iter, in_loop)
            # loop-derived (TPU005) taint only for PYTHON-SCALAR
            # induction vars — range()/enumerate() counters; iterating
            # data yields arrays, whose dtypes are already committed
            loopd_targets = []
            if isinstance(stmt.iter, ast.Call):
                it_name = _last(_callee(stmt.iter))
                targets = _target_names(stmt.target)
                if it_name == "range":
                    loopd_targets = targets
                elif it_name == "enumerate" and targets:
                    loopd_targets = targets[:1]
            for name in _target_names(stmt.target):
                self.tainted.discard(name)
                self.loopd.discard(name)
            for name in loopd_targets:
                self.loopd.add(name)
            for _ in range(2):
                self._process_body(stmt.body, in_loop=True)
            self._process_body(stmt.orelse, in_loop=in_loop)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_exprs(item.context_expr, in_loop)
                if item.optional_vars is not None:
                    self._rebind(_target_names(item.optional_vars), None)
            self._process_body(stmt.body, in_loop=in_loop)
            return
        if isinstance(stmt, ast.Try):
            self._process_body(stmt.body, in_loop=in_loop)
            for h in stmt.handlers:
                self._process_body(h.body, in_loop=in_loop)
            self._process_body(stmt.orelse, in_loop=in_loop)
            self._process_body(stmt.finalbody, in_loop=in_loop)
            return

        # --- straight-line statements ---
        value = None
        targets: List[str] = []
        if isinstance(stmt, ast.Assign):
            value = stmt.value
            for t in stmt.targets:
                targets.extend(_target_names(t))
        elif isinstance(stmt, ast.AnnAssign):
            value = stmt.value
            targets = _target_names(stmt.target)
        elif isinstance(stmt, ast.AugAssign):
            value = stmt.value
            targets = _target_names(stmt.target)
            if in_loop:
                self.loopd.update(targets)
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            value = stmt.value
        elif isinstance(stmt, (ast.Assert, ast.Raise)):
            if isinstance(stmt, ast.Assert) and self.traced and \
                    _expr_tainted(stmt.test, self.tainted):
                self._flag(
                    "TPU001", stmt,
                    "assert on a traced value inside a traced function; "
                    "use checkify or a host-side check")
            for child in ast.iter_child_nodes(stmt):
                self._scan_exprs(child, in_loop)
            return
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_exprs(child, in_loop)
            return

        if value is not None:
            self._scan_exprs(value, in_loop)
        if targets:
            self._rebind(targets, value)

    # -- assignment bookkeeping ---------------------------------------

    def _rebind(self, targets: List[str], value):
        value_tainted = value is not None and \
            _expr_tainted(value, self.tainted)
        value_loopd = value is not None and self._loopd_tainted(value)
        is_key = value is not None and self._is_key_expr(value)
        for name in targets:
            self.donated.pop(name, None)
            if value_tainted:
                self.tainted.add(name)
            else:
                self.tainted.discard(name)
            if value_loopd:
                self.loopd.add(name)
            else:
                self.loopd.discard(name)
            if is_key and "." not in name:
                self.keys_live.add(name)
                self.keys_consumed.pop(name, None)
            else:
                self.keys_live.discard(name)
                self.keys_consumed.pop(name, None)

    def _loopd_tainted(self, value) -> bool:
        """Loop-derived HOST-scalar taint. Unlike traced-value taint,
        calls to jitted callables and dtype-pinning wrappers are
        barriers: their results are committed device arrays, not raw
        Python scalars, so they cannot churn weak types downstream."""
        if isinstance(value, ast.Call):
            callee = _callee(value)
            if callee in self.index.jitted or self._dtype_pinned(value):
                return False
        return _expr_tainted(value, self.loopd)

    def _is_key_expr(self, value) -> bool:
        if isinstance(value, ast.Call):
            callee = _callee(value)
            if "random" in callee and _last(callee) in _KEY_MAKERS:
                return True
        if isinstance(value, (ast.Tuple, ast.List)):
            return any(self._is_key_expr(e) for e in value.elts)
        if isinstance(value, ast.Subscript):
            return self._is_key_expr(value.value)
        return False

    def _augassigned_names(self, body) -> List[str]:
        out = []
        for n in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(n, ast.AugAssign):
                out.extend(_target_names(n.target))
        return out

    # -- expression scan (calls, uses) --------------------------------

    def _scan_exprs(self, node, in_loop: bool):
        if node is None:
            return
        # use-after-donation: check BEFORE this statement's own donations
        if self.donated:
            for n in ast.walk(node):
                if isinstance(n, (ast.Name, ast.Attribute)) and \
                        not isinstance(getattr(n, "ctx", None), ast.Store):
                    try:
                        key = ast.unparse(n)
                    except Exception:  # pragma: no cover
                        continue
                    if key in self.donated:
                        self._flag(
                            "TPU004", n,
                            f"`{key}` used after being donated at line "
                            f"{self.donated[key]} (donate_argnums "
                            "invalidates the buffer)")
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self._check_call(n, in_loop)
            elif isinstance(n, ast.IfExp) and self.traced and \
                    _expr_tainted(n.test, self.tainted):
                self._flag(
                    "TPU001", n,
                    "Python conditional expression on a traced value; "
                    "use jnp.where")

    def _check_call(self, call: ast.Call, in_loop: bool):
        callee = _callee(call)
        name = _last(callee)

        # TPU002: host transfers on traced values
        if self.traced:
            if name in _HOST_BUILTINS and callee == name and call.args and \
                    _expr_tainted(call.args[0], self.tainted):
                self._flag(
                    "TPU002", call,
                    f"{name}() on a traced value forces a host transfer "
                    "(ConcretizationTypeError under jit); keep it on "
                    "device")
            elif name in _HOST_NP_FNS and \
                    callee.split(".")[0] in ("np", "numpy") and call.args \
                    and _expr_tainted(call.args[0], self.tainted):
                self._flag(
                    "TPU002", call,
                    f"{callee}() on a traced value materializes on host; "
                    "use jnp.asarray or keep the jax array")
            elif name in _HOST_METHODS and \
                    isinstance(call.func, ast.Attribute) and \
                    _expr_tainted(call.func.value, self.tainted):
                self._flag(
                    "TPU002", call,
                    f".{name}() on a traced value forces a host sync")

        # TPU003: key reuse
        if "random" in callee and name not in _KEY_NONCONSUMING:
            for a in call.args:
                if isinstance(a, ast.Name) and a.id in self.keys_live:
                    if a.id in self.keys_consumed:
                        self._flag(
                            "TPU003", call,
                            f"PRNG key `{a.id}` reused (first consumed at "
                            f"line {self.keys_consumed[a.id]}) without "
                            "split/fold_in — draws are correlated")
                    else:
                        self.keys_consumed[a.id] = call.lineno

        # TPU004 donations + TPU005 recompile hazards at jitted call sites
        info = self.index.jitted.get(callee)
        if info is not None:
            for i in info.donate:
                if i < len(call.args) and \
                        isinstance(call.args[i], (ast.Name, ast.Attribute)):
                    try:
                        key = ast.unparse(call.args[i])
                    except Exception:  # pragma: no cover
                        continue
                    self.donated[key] = call.lineno
            if in_loop:
                for i, a in enumerate(call.args):
                    if not _expr_tainted(a, self.loopd):
                        continue
                    if i in info.static:
                        self._flag(
                            "TPU005", call,
                            f"loop-varying value at static_argnums "
                            f"position {i} of jitted `{callee}` — one "
                            "recompile per distinct value")
                    elif not self._dtype_pinned(a):
                        self._flag(
                            "TPU005", call,
                            f"raw Python scalar derived from a loop "
                            f"variable passed to jitted `{callee}` — "
                            "weak-type churn recompiles silently; pin "
                            "with jnp.int32(...)/jnp.asarray(...)")

        # TPU006: divergent collectives across lax.cond/lax.switch branches
        if self.spmd and name in ("cond", "switch"):
            self._check_branch_collectives(call, name)

    def _dtype_pinned(self, node) -> bool:
        """True when the arg is wrapped in a dtype-pinning constructor
        (jnp.int32(i), jnp.asarray(i), np.float32(x))."""
        if isinstance(node, ast.Call):
            callee = _callee(node)
            return any(callee.startswith(p) for p in _WRAP_PREFIXES)
        return False

    # -- TPU006 helpers ------------------------------------------------

    def _resolve_branch(self, node):
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            return self.local_defs.get(node.id)
        return None

    def _check_branch_collectives(self, call: ast.Call, kind: str):
        if kind == "cond" and len(call.args) >= 3:
            branch_nodes = [call.args[1], call.args[2]]
        elif kind == "switch" and len(call.args) >= 2 and \
                isinstance(call.args[1], (ast.List, ast.Tuple)):
            branch_nodes = list(call.args[1].elts)
        else:
            return
        resolved = [self._resolve_branch(b) for b in branch_nodes]
        if any(r is None for r in resolved) or len(resolved) < 2:
            return  # dynamically built branches: program pass covers these
        seqs = [_collective_sequence(r) for r in resolved]
        if len(set(seqs)) > 1:
            detail = " vs ".join(
                "(" + (", ".join(s) or "none") + ")" for s in seqs)
            self._flag(
                "TPU006", call,
                f"lax.{kind} branches inside an SPMD body issue different "
                f"collective sequences {detail} — ranks diverging on the "
                "predicate deadlock")

    def _check_python_branch_collectives(self, stmt: ast.If):
        body_seq = _collective_sequence(
            ast.Module(body=list(stmt.body), type_ignores=[]))
        else_seq = _collective_sequence(
            ast.Module(body=list(stmt.orelse), type_ignores=[]))
        if body_seq != else_seq and (body_seq or else_seq):
            self._flag(
                "TPU006", stmt,
                f"Python if/else inside an SPMD body traces different "
                f"collective sequences ({', '.join(body_seq) or 'none'}) "
                f"vs ({', '.join(else_seq) or 'none'}) — call sites "
                "specializing differently produce rank-divergent "
                "programs")


# ----------------------------------------------------------------------
# entry points
# ----------------------------------------------------------------------

def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    """Lint one module's source: the trace/shard rules (TPU001-006) plus
    the concurrency pass (CON001-006, analysis/concurrency.py). `path`
    is recorded on findings (repo-relative for real files)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(rule="TPU000", path=path, line=e.lineno or 0,
                        message=f"syntax error: {e.msg}", snippet="")]
    src_lines = src.splitlines()
    index = _ModuleIndex()
    index.visit(tree)

    findings: List[Finding] = []
    for fn, ancestors in _walk_functions(tree):
        chain = ancestors + [fn]

        def _is_traced(node):
            return id(node) in index.traced_nodes or \
                node.name in index.traced_names
        traced = any(_is_traced(n) for n in chain)
        spmd = any(id(n) in index.spmd_nodes or n.name in index.spmd_names
                   for n in chain)
        # sibling + ancestor-scope defs, for TPU006 branch resolution
        local_defs: Dict[str, ast.AST] = {}
        for scope in ancestors + [fn]:
            for child in ast.walk(scope):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    local_defs[child.name] = child
        checker = _FunctionChecker(
            fn, path, src_lines, index, traced=traced, spmd=spmd,
            local_defs=local_defs)
        findings.extend(checker.run())
    from dnn_tpu.analysis.concurrency import check_source
    from dnn_tpu.analysis.shardcheck import check_source as shard_check

    findings.extend(check_source(src, path))
    findings.extend(shard_check(src, path))
    return assign_occurrences(findings)


def iter_python_files(root: str):
    """Lintable .py files under `root` (skips caches and generated pb2)."""
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for f in sorted(filenames):
            if f.endswith(".py") and not f.endswith("_pb2.py"):
                yield os.path.join(dirpath, f)


def lint_paths(paths: Sequence[str], repo_root: Optional[str] = None
               ) -> List[Finding]:
    """Lint every .py file under `paths`; finding paths are relative to
    `repo_root` (default: cwd) so fingerprints are machine-independent."""
    repo_root = repo_root or os.getcwd()
    findings: List[Finding] = []
    for p in paths:
        for f in iter_python_files(p):
            rel = os.path.relpath(os.path.abspath(f),
                                  os.path.abspath(repo_root))
            with open(f, encoding="utf-8") as fh:
                findings.extend(lint_source(fh.read(), rel.replace(
                    os.sep, "/")))
    return assign_occurrences(findings)
