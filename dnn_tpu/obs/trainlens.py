"""trainlens: the training-step observatory — MFU, stall attribution,
gradient health, checkpoint freshness.

Training was the one ROADMAP pillar with zero observability: `fit()`
loops, the dp×tp/zero1 sharded steps, and the checkpoint path emitted
nothing — no clock, no goodput, no flight events — while ROADMAP item 2
names "step-time MFU ... as an asserted ledger row" as the pillar's
metric. This module is the instrument, built BEFORE the training-at-
scale PR it judges (the PR-10 StepClock / PR-16 shardcheck pattern),
in three connected pieces on the existing obs substrate:

  * **TrainClock** — the training loop's phase clock, in the StepClock
    idiom (single producer, one-None-check gate, 32-step batched
    registry flush honoring the <2% obs contract). `train.fit` splits
    every iteration into named contiguous phases:

        data      next(batch_iter): host input pipeline (+ any chaos
                  train_fault sleep — injected stalls land exactly here)
        dispatch  the jit call itself, call-to-return
        wait      dispatch-return -> loss-on-host (block_until_ready):
                  the window the compiled step program is in flight
        ckpt      periodic save_checkpoint_multihost wall
        eval      periodic in-training evaluation wall
        obs       sentinel + callbacks + this clock's own bookkeeping

    Derived series: `data_stall_fraction` = data / wall (THE input-
    pipeline starvation ratchet), steps/s and tokens/s over the ring's
    newest 60 s, and step-time **MFU** = flops_per_step × steps/s ÷
    peak — priced by the utils/flops.py training helpers
    (gpt_train_step_flops / llama_train_step_flops, 3× forward,
    microbatch/remat-aware) against the same `device_peak_flops`
    roofline the serving goodput gauges use (DNN_TPU_PEAK_FLOPS is the
    CPU-host opt-in). Exported as weak scrape-time gauges
    (`dnn_tpu_train_mfu`, `dnn_tpu_train_tokens_per_sec`,
    `dnn_tpu_train_data_stall`, ...), a `/trainz` endpoint
    (JSON|prom|trace) next to /stepz, a Perfetto host-track export,
    and `python -m dnn_tpu.obs trainlens [--url URL | PATH |
    --selftest]`.

  * **GradSentinel** — gradient-health sentinels over the opt-in
    on-device stats leg the train steps grow (`grad_stats=True`:
    global grad-norm, update/param-norm ratio, nonfinite count — ONE
    small-array readback per step, donation-safe). Host-side detectors
    feed bounded flight events: `grad_spike` (EMA spike detector),
    `loss_nan` (nonfinite loss or nonfinite grads — latched per
    episode, and optionally a full incident bundle via the PR-13
    forensics machinery, obs/slo.write_incident_bundle, so a diverging
    run produces a /debugz post-mortem instead of a silent flat loss),
    `train_stall` (update ratio pinned at ~0 for N consecutive steps —
    the wedged-optimizer signature).

  * **Checkpoint observability** — `note_ckpt_saved`/`note_ckpt_restored`
    (wired through train.fit / resume_or_init): save/restore
    duration+bytes histograms, `dnn_tpu_ckpt_last_good_step` /
    `dnn_tpu_ckpt_staleness_seconds` gauges (how much work a crash
    would lose RIGHT NOW), and `ckpt_saved`/`ckpt_restored` flight
    events, so a restore-latest-good incident reconstructs from
    /debugz.

The asserted baseline lives in benchmarks/train_goodput_probe.py:
phase coverage ≥95% of external wall, an MFU floor on the pinned
roofline, injected-sleep → data_stall attribution, injected-NaN →
sentinel within 2 steps, and a trainlens-live obs-overhead leg <2%
(BASELINE.md ratchets train_mfu_floor / train_phase_coverage /
trainlens_overhead_budget).

No jax import anywhere in this module — the clock is pure perf_counter
bookkeeping (the obs/__main__.py contract); peak-FLOPs resolution
touches utils.flops (and thus jax) lazily, goodput-style, only when no
explicit `peak_flops` was given.
"""

from __future__ import annotations

import math
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

from dnn_tpu import obs as _obs
from dnn_tpu.obs import flight
from dnn_tpu.obs.timeline import STEP_BUCKETS
from dnn_tpu.utils.metrics import labeled

__all__ = ["TrainClock", "GradSentinel", "TRAIN_PHASES",
           "active_trainlens", "note_ckpt_saved", "note_ckpt_restored",
           "CKPT_SECONDS_BUCKETS", "CKPT_BYTES_BUCKETS"]

#: phase names, in within-step order
TRAIN_PHASES = ("data", "dispatch", "wait", "ckpt", "eval", "obs")

#: checkpoint save/restore duration bounds (seconds): a toy npz lands in
#: ms; a multihost allgather + full-state write can take minutes
CKPT_SECONDS_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1.0, 5.0, 30.0, 120.0, 600.0)

#: checkpoint size bounds (bytes): test trees through full LLM states
CKPT_BYTES_BUCKETS = (1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11)


class _TrainRec:
    """One training iteration's phase boundaries: t0 at loop entry, then
    (phase, t) marks in order — phase P's duration is its mark minus the
    previous boundary; the remainder after the last mark folds into
    "obs" (the clock's own end-of-iteration bookkeeping). Folded lazily
    off the hot path, exactly like timeline._StepRec."""

    __slots__ = ("t0", "t_end", "marks", "tokens", "wall", "phases")

    def __init__(self, t0: float):
        self.t0 = t0
        self.t_end = t0
        self.marks: list = []
        self.tokens = 0
        self.wall = 0.0
        self.phases: "Optional[Dict[str, float]]" = None


def _fold(rec: _TrainRec) -> _TrainRec:
    """Fold a published record's marks into per-phase durations (in
    place, idempotent). Runs at flush and scrape time only."""
    if rec.phases is not None:
        return rec
    phases: Dict[str, float] = {}
    t = rec.t0
    for name, tm in rec.marks:
        phases[name] = tm - t
        t = tm
    if rec.t_end > t:
        phases["obs"] = phases.get("obs", 0.0) + (rec.t_end - t)
    rec.wall = rec.t_end - rec.t0
    rec.phases = phases
    return rec


class TrainClock:
    """Per-phase training-step clock. Attach via `TrainClock(...).
    install()` before calling train.fit — fit picks up the active clock
    (or takes one explicitly) and feeds it behind the obs gate.

    Producer protocol (what train.fit runs each iteration):

        rec = clock.begin()          # None when the obs gate is off
        batch = next(batch_iter)     # -> "data"
        clock.mark(rec, "data")
        out = step_fn(state, batch)  # -> "dispatch"
        clock.mark(rec, "dispatch")
        block_until_ready(loss)      # -> "wait"
        clock.mark(rec, "wait")
        ... ckpt / eval ...          # -> "ckpt", "eval"
        clock.end(rec, tokens=B*T)   # publishes; bulk-flushes every
                                     # FLUSH_EVERY steps

    `flops_per_step` is the analytic training-step cost at the run's
    pinned shape (utils.flops.gpt_train_step_flops / llama_...);
    `tokens_per_step` the tokens one optimizer step consumes (end()'s
    default). `peak_flops` pins the MFU roofline explicitly; left None
    it resolves lazily from utils.flops.device_peak_flops (TPU table /
    DNN_TPU_PEAK_FLOPS env) the first time a scrape asks — never at
    construction, and never fatally (a CPU host without the env opt-in
    simply reports no MFU rather than a made-up one).

    Threading/registry discipline is StepClock's verbatim: end() is one
    perf_counter read + a GIL-atomic append; `_land()` (ring-only) is
    the half gauge reads may run — a gauge read reaching Metrics.bulk
    would self-deadlock on the registry's non-reentrant lock; flush()
    does the batched histogram bill every FLUSH_EVERY steps and from
    the clock's own scrape surfaces."""

    FLUSH_EVERY = 32

    def __init__(self, capacity: int = 256, *,
                 flops_per_step: Optional[float] = None,
                 tokens_per_step: int = 0,
                 registry=None, peak_flops: Optional[float] = None,
                 now=time.perf_counter):
        self.capacity = int(capacity)
        self._ring: "deque[_TrainRec]" = deque(maxlen=self.capacity)
        self._now = now
        self._lock = threading.Lock()
        self.steps_total = 0
        self.flops_per_step = flops_per_step
        self.tokens_per_step = int(tokens_per_step)
        self._registry = registry
        self._peak = peak_flops
        self._peak_resolved = peak_flops is not None
        self._t_last_end: Optional[float] = None
        self._pending_flush: list = []
        self._pending_bulk: list = []
        self._derived_cache = None
        # checkpoint freshness (the supervisor-loop gauges)
        self._ckpt_last_good_step = 0
        self._ckpt_last_good_t: Optional[float] = None
        self._hist_keys = {p: labeled("train.phase_seconds", phase=p)
                           for p in TRAIN_PHASES}
        ref = weakref.ref(self)

        def _weak(method):
            def read():
                c = ref()
                return getattr(c, method)() if c is not None else 0.0
            return read

        # gauge keys are FULL prometheus family names (unlike the
        # clock-internal train.* counter/hist keys): the fleet rollup
        # reads these families off a polled target's /metrics text, so
        # the registry render must emit exactly `dnn_tpu_train_mfu`,
        # not a sanitized `train_mfu`
        self._gauges = {
            "dnn_tpu_train_mfu": _weak("_mfu_read"),
            "dnn_tpu_train_tokens_per_sec": _weak("tokens_per_sec"),
            "dnn_tpu_train_data_stall": _weak("data_stall_fraction"),
            "dnn_tpu_train_steps_per_sec": _weak("steps_per_sec"),
            "dnn_tpu_train_last_wall_ms": _weak("last_wall_ms"),
            "dnn_tpu_ckpt_last_good_step": _weak("_ckpt_step_read"),
            "dnn_tpu_ckpt_staleness_seconds": _weak("ckpt_staleness_s"),
        }

    def install(self) -> "TrainClock":
        """Make this the process's active training clock (what
        train.fit and the module-level ckpt notes pick up)."""
        global _active_trainlens
        _active_trainlens = weakref.ref(self)
        return self

    # -- roofline ------------------------------------------------------

    def peak_flops(self) -> Optional[float]:
        """The MFU denominator, resolved lazily (goodput-style): an
        explicit constructor value wins; else the utils.flops table /
        DNN_TPU_PEAK_FLOPS env the first time asked. Never raises — an
        unresolvable roofline means "no MFU", not a crash."""
        if not self._peak_resolved:
            self._peak_resolved = True
            try:
                from dnn_tpu.utils.flops import device_peak_flops

                self._peak = device_peak_flops()
            except Exception:  # noqa: BLE001 — no jax / no devices
                self._peak = None
        return self._peak

    # -- producer side (the fit loop's thread) -------------------------

    def begin(self) -> Optional[_TrainRec]:
        """Start one iteration's record — None when observability is
        off (fit's one None check covers every later site)."""
        if not _obs.enabled():
            return None
        return _TrainRec(self._now())

    def mark(self, rec: _TrainRec, phase: str):
        """Close the current phase at now (one perf_counter read + one
        tuple append on the hot path)."""
        rec.marks.append((phase, self._now()))

    def end(self, rec: _TrainRec, tokens: Optional[int] = None):
        """Stamp and publish one iteration — one perf_counter read and
        ONE GIL-atomic append (StepClock.end's budget discipline); the
        fold and the registry bulk run off this path in flush()."""
        rec.t_end = self._now()
        rec.tokens = self.tokens_per_step if tokens is None else tokens
        self.steps_total += 1
        self._t_last_end = rec.t_end
        pf = self._pending_flush
        pf.append(rec)
        if len(pf) >= self.FLUSH_EVERY:
            self.flush()

    def _land(self):
        """Move the pending batch into the scrape ring — the half of
        flush() ring readers need, and the ONLY half gauge-reachable
        code may run (a reader that reached Metrics.bulk from inside
        the registry's own gauge render would self-deadlock)."""
        if not self._pending_flush:
            return
        with self._lock:
            pending, self._pending_flush = self._pending_flush, []
            self._ring.extend(pending)
            self._pending_bulk.extend(pending)

    def flush(self):
        """Land + bill the accumulated observations in ONE bulk
        registry update. Called every FLUSH_EVERY steps by end() and by
        summary()/render_prom() — never from inside a registry render."""
        m = self._registry if self._registry is not None \
            else _obs.metrics()
        self._land()
        with self._lock:
            pending, self._pending_bulk = self._pending_bulk, []
        if m is None or not pending:
            return
        hists: Dict[str, list] = {}
        walls = []
        tokens = 0
        for r in pending:
            _fold(r)
            for p, v in r.phases.items():
                hists.setdefault(self._hist_keys[p], []).append(v)
            walls.append(r.wall)
            tokens += r.tokens
        hists["train.wall_seconds"] = walls
        m.bulk(counters={"train.steps_total": len(pending),
                         "train.tokens_total": tokens},
               hists=hists, hist_buckets=STEP_BUCKETS,
               gauge_fns=self._gauges)

    # -- checkpoint observability --------------------------------------

    def ckpt_saved(self, step: int, seconds: float, nbytes: float):
        """Feed one completed save: freshness gauges + duration/bytes
        histograms. The flight event is the module helper's job (one
        event per save regardless of how many clocks watch)."""
        self._ckpt_last_good_step = int(step)
        self._ckpt_last_good_t = self._now()
        m = self._registry if self._registry is not None \
            else _obs.metrics()
        if m is None:
            return
        m.observe_hist("train.ckpt_save_seconds", float(seconds),
                       CKPT_SECONDS_BUCKETS)
        m.observe_hist("train.ckpt_save_bytes", float(nbytes),
                       CKPT_BYTES_BUCKETS)
        m.bulk(counters={"train.ckpt_saves": 1},
               gauge_fns=self._gauges)

    def ckpt_restored(self, step: int, seconds: float, nbytes: float):
        """Feed one completed restore. The restored step is also the
        last KNOWN-GOOD step — a fresh resume must not report infinite
        staleness until the first new save."""
        self._ckpt_last_good_step = int(step)
        self._ckpt_last_good_t = self._now()
        m = self._registry if self._registry is not None \
            else _obs.metrics()
        if m is None:
            return
        m.observe_hist("train.ckpt_restore_seconds", float(seconds),
                       CKPT_SECONDS_BUCKETS)
        m.observe_hist("train.ckpt_restore_bytes", float(nbytes),
                       CKPT_BYTES_BUCKETS)
        m.bulk(counters={"train.ckpt_restores": 1},
               gauge_fns=self._gauges)

    def ckpt_staleness_s(self) -> float:
        """Seconds since the last known-good checkpoint — the work a
        crash right now would lose. 0.0 before any save/restore (a run
        with checkpointing disabled reads as 'nothing to lose' rather
        than alarming forever)."""
        t = self._ckpt_last_good_t
        return 0.0 if t is None else max(0.0, self._now() - t)

    def _ckpt_step_read(self) -> float:
        return float(self._ckpt_last_good_step)

    # -- derived series (scrape-time reads over the ring) --------------

    def _sums(self, last: Optional[int] = None):
        self._land()  # ring readers: land only, never the registry
        with self._lock:
            recs = list(self._ring)
        if last:
            recs = recs[-last:]
        tot: Dict[str, float] = {p: 0.0 for p in TRAIN_PHASES}
        wall = 0.0
        tokens = 0
        for r in recs:
            _fold(r)
            for p, v in r.phases.items():
                tot[p] = tot.get(p, 0.0) + v
            wall += r.wall
            tokens += r.tokens
        return recs, tot, wall, tokens

    def data_stall_fraction(self) -> float:
        """data-phase share of step wall over the ring — THE input-
        pipeline starvation series (memoized per landed step, like
        StepClock._derived: a /metrics render reads several gauges in
        one scrape and must not re-walk the ring for each)."""
        key = self.steps_total
        cached = self._derived_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        _, tot, wall, _ = self._sums()
        frac = tot["data"] / wall if wall > 0 else 0.0
        self._derived_cache = (key, frac)
        return frac

    def _rate(self):
        """(steps/s, tokens/s) over the ring's newest 60 s — computed
        at scrape time over the span the surviving records cover."""
        self._land()  # gauge-reachable: land only (registry deadlock)
        now = self._now()
        with self._lock:
            recent = [r for r in self._ring if now - r.t0 <= 60.0]
            oldest = self._ring[0].t0 if self._ring else now
        if not recent:
            return 0.0, 0.0
        span = max(min(60.0, now - oldest), 1e-9)
        return len(recent) / span, sum(r.tokens for r in recent) / span

    def steps_per_sec(self) -> float:
        return self._rate()[0]

    def tokens_per_sec(self) -> float:
        return self._rate()[1]

    def mfu(self) -> Optional[float]:
        """Step-time model-FLOPs utilization: flops_per_step × steps/s
        ÷ peak. None (not 0.0) when the cost or the roofline is unknown
        — callers omit the field rather than publish a made-up one."""
        peak = self.peak_flops()
        if peak is None or not self.flops_per_step:
            return None
        return self.flops_per_step * self.steps_per_sec() / peak

    def _mfu_read(self) -> float:
        return self.mfu() or 0.0

    def last_wall_ms(self) -> float:
        self._land()  # gauge-reachable: land only (registry deadlock)
        with self._lock:
            if not self._ring:
                return 0.0
            rec = self._ring[-1]
        return _fold(rec).wall * 1e3

    def last_step_age_s(self) -> Optional[float]:
        with self._lock:
            t = self._t_last_end
        return None if t is None else max(0.0, self._now() - t)

    def records(self, last: Optional[int] = None) -> List[dict]:
        """Ring records as plain dicts (newest last) — what the probe's
        coverage assertion reads."""
        self._land()
        with self._lock:
            recs = list(self._ring)
        if last:
            recs = recs[-last:]
        return [{"t0": r.t0, "wall": _fold(r).wall, "tokens": r.tokens,
                 "phases": dict(r.phases), "marks": list(r.marks)}
                for r in recs]

    # -- export surfaces -----------------------------------------------

    def summary(self, last: Optional[int] = None) -> dict:
        """The /trainz JSON payload: per-phase totals/means/fractions
        over the ring (or the newest `last` steps) plus the derived
        series and checkpoint freshness."""
        self.flush()  # scrapes read fresh histograms/counters
        recs, tot, wall, tokens = self._sums(last)
        n = len(recs)
        phases = {}
        for p in TRAIN_PHASES:
            s = tot.get(p, 0.0)
            phases[p] = {"s": round(s, 6),
                         "frac": round(s / wall, 4) if wall > 0 else 0.0,
                         "mean_ms": round(s / n * 1e3, 4) if n else 0.0}
        sps, tps = self._rate()
        m = self.mfu()
        return {
            "steps_total": self.steps_total,
            "window_steps": n,
            "window_wall_s": round(wall, 6),
            "tokens": tokens,
            "phases": phases,
            "data_stall_fraction": round(
                tot["data"] / wall, 4) if wall > 0 else 0.0,
            "steps_per_sec": round(sps, 3),
            "tokens_per_sec": round(tps, 1),
            "flops_per_step": self.flops_per_step,
            "peak_flops": self.peak_flops(),
            "mfu": None if m is None else round(m, 6),
            "last_wall_ms": round(self.last_wall_ms(), 4),
            "ckpt": {
                "last_good_step": self._ckpt_last_good_step,
                "staleness_s": round(self.ckpt_staleness_s(), 3),
            },
        }

    def status_component(self) -> dict:
        """A /statusz `train` component: progress at a glance.
        Informational — state stays "ok" (divergence escalation is the
        sentinel's flight-event job, not a health state)."""
        s = self.summary()
        age = self.last_step_age_s()
        mfu_txt = ("" if s["mfu"] is None
                   else f", mfu {s['mfu']:.1%}")
        return {
            "state": "ok",
            "detail": (f"step {s['steps_total']}, last "
                       f"{s['last_wall_ms']:.1f} ms "
                       f"({'never' if age is None else f'{age:.1f}s ago'})"
                       f", data stall {s['data_stall_fraction']:.0%}"
                       f"{mfu_txt}"),
            "steps_total": s["steps_total"],
            "last_step_age_s": None if age is None else round(age, 3),
            "data_stall_fraction": s["data_stall_fraction"],
            "mfu": s["mfu"],
        }

    def render_prom(self, last: Optional[int] = None) -> str:
        """The ?format=prom re-export: the summary as gauges, for
        scrape-only collectors. Family names match the weak gauges the
        registry exports, so a /trainz-only scrape and a /metrics
        scrape read the same series."""
        from dnn_tpu.utils.metrics import Metrics, render_prometheus

        s = self.summary(last)
        m = Metrics()
        m.set("dnn_tpu_train_steps_total", float(s["steps_total"]))
        m.set("dnn_tpu_train_window_wall_s", float(s["window_wall_s"]))
        m.set("dnn_tpu_train_mfu", float(s["mfu"] or 0.0))
        m.set("dnn_tpu_train_tokens_per_sec", float(s["tokens_per_sec"]))
        m.set("dnn_tpu_train_data_stall",
              float(s["data_stall_fraction"]))
        m.set("dnn_tpu_train_steps_per_sec", float(s["steps_per_sec"]))
        m.set("dnn_tpu_train_last_wall_ms", float(s["last_wall_ms"]))
        m.set("dnn_tpu_ckpt_last_good_step",
              float(s["ckpt"]["last_good_step"]))
        m.set("dnn_tpu_ckpt_staleness_seconds",
              float(s["ckpt"]["staleness_s"]))
        for p, d in s["phases"].items():
            m.set(labeled("dnn_tpu_train_phase_seconds_total", phase=p),
                  d["s"])
            m.set(labeled("dnn_tpu_train_phase_frac", phase=p),
                  d["frac"])
        return render_prometheus(m)

    def chrome_trace(self, last: Optional[int] = None) -> dict:
        """The ring as a Perfetto-loadable HOST track: one process
        ("trainlens"), one slice per phase per step, timestamps rebased
        so the oldest exported slice starts at ts 0 (absolute
        perf_counter stamps render days into the timeline)."""
        self._land()
        with self._lock:
            recs = list(self._ring)
        if last:
            recs = recs[-last:]
        origin = recs[0].t0 if recs else 0.0
        events = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "trainlens"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "train-step phases"}},
        ]
        for i, r in enumerate(recs):
            t = r.t0
            args = {"step": i, "tokens": r.tokens}
            for name, tm in r.marks:
                events.append({"ph": "X", "pid": 1, "tid": 1,
                               "name": name,
                               "ts": (t - origin) * 1e6,
                               "dur": (tm - t) * 1e6,
                               "args": args})
                t = tm
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# the process's active training clock (train.fit picks it up)
_active_trainlens: "Optional[weakref.ref]" = None


def active_trainlens() -> Optional[TrainClock]:
    ref = _active_trainlens
    if ref is None:
        return None
    return ref()


# ----------------------------------------------------------------------
# checkpoint observability: the module-level wires train.py calls
# ----------------------------------------------------------------------

def note_ckpt_saved(step: int, seconds: float, nbytes: float, *,
                    clock: Optional[TrainClock] = None):
    """One completed checkpoint save: a `ckpt_saved` flight event (the
    /debugz record a restore-latest-good post-mortem needs) + the
    active clock's freshness gauges and duration/bytes histograms.
    One boolean check when observability is off."""
    if not _obs.enabled():
        return
    flight.record("ckpt_saved", step=int(step),
                  seconds=round(float(seconds), 6),
                  bytes=int(nbytes))
    c = clock if clock is not None else active_trainlens()
    if c is not None:
        c.ckpt_saved(step, seconds, nbytes)


def note_ckpt_restored(step: int, seconds: float, nbytes: float, *,
                       clock: Optional[TrainClock] = None):
    """One completed checkpoint restore (resume_or_init's hit path)."""
    if not _obs.enabled():
        return
    flight.record("ckpt_restored", step=int(step),
                  seconds=round(float(seconds), 6),
                  bytes=int(nbytes))
    c = clock if clock is not None else active_trainlens()
    if c is not None:
        c.ckpt_restored(step, seconds, nbytes)


# ----------------------------------------------------------------------
# gradient-health sentinels
# ----------------------------------------------------------------------

class GradSentinel:
    """Host-side detectors over the train step's on-device stats leg.

    `observe(step, loss, stats)` each iteration — `stats` is the
    3-vector the `grad_stats=True` steps return ([global grad-norm,
    update/param-norm ratio, nonfinite grad count], already on host),
    or None when the step runs without the leg (the loss-only checks
    still fire). Returns the list of event kinds fired this call (what
    the probe asserts on); every firing is a bounded flight event:

      loss_nan     nonfinite loss OR any nonfinite gradient — latched
                   per episode (one event per divergence, not one per
                   step while it lasts). With `bundle_dir` set, the
                   FIRST firing also writes a full incident bundle via
                   obs/slo.write_incident_bundle (flight ring window +
                   the clock's /trainz snapshot) — the diverging run's
                   post-mortem, reconstructable offline with
                   `python -m dnn_tpu.obs incident PATH`.
      grad_spike   grad-norm > spike_factor × its EMA after `warmup`
                   observations — latched until the norm returns under
                   the threshold. The EMA updates on finite norms only
                   (a NaN norm must not poison the baseline).
      train_stall  update/param-norm ratio below `stall_ratio` for
                   `stall_steps` CONSECUTIVE steps — the wedged-
                   optimizer signature (lr 0, all-masked grads, a
                   frozen tree): loss flat, nothing moving.

    All checks degrade to one boolean when the obs gate is off."""

    def __init__(self, *, spike_factor: float = 8.0,
                 ema_alpha: float = 0.1, warmup: int = 5,
                 stall_ratio: float = 1e-9, stall_steps: int = 50,
                 bundle_dir: Optional[str] = None,
                 clock: Optional[TrainClock] = None):
        if spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor}")
        if not 0.0 < ema_alpha <= 1.0:
            raise ValueError(
                f"ema_alpha must be in (0, 1], got {ema_alpha}")
        self.spike_factor = float(spike_factor)
        self.ema_alpha = float(ema_alpha)
        self.warmup = int(warmup)
        self.stall_ratio = float(stall_ratio)
        self.stall_steps = int(stall_steps)
        self.bundle_dir = bundle_dir
        self._clock = clock
        self._ema: Optional[float] = None
        self._n_obs = 0
        self._nan_latched = False
        self._spike_latched = False
        self._stall_run = 0
        self._stall_latched = False
        self.events_fired = 0

    def observe(self, step: int, loss, stats=None) -> List[str]:
        if not _obs.enabled():
            return []
        fired: List[str] = []
        try:
            loss_f = float(loss)
        except (TypeError, ValueError):
            loss_f = float("nan")
        grad_norm = ratio = None
        nonfinite = 0
        if stats is not None:
            # ONE host transfer for the 3-vector: iterating a device
            # array element-wise costs three dispatched index reads —
            # measurable against the <2% per-step obs budget
            vals = stats.tolist() if hasattr(stats, "tolist") \
                else [float(v) for v in stats]
            grad_norm, ratio = vals[0], vals[1]
            nonfinite = int(vals[2]) if math.isfinite(vals[2]) else 1

        # -- loss_nan: the divergence sentinel -------------------------
        bad = not math.isfinite(loss_f) or nonfinite > 0
        if bad and not self._nan_latched:
            self._nan_latched = True
            fired.append("loss_nan")
            flight.record("loss_nan", step=int(step), loss=loss_f,
                          nonfinite_grads=nonfinite)
            if self.bundle_dir:
                self._write_bundle(step, loss_f, nonfinite)
        elif not bad:
            self._nan_latched = False

        # -- grad_spike: EMA spike detector ----------------------------
        if grad_norm is not None and math.isfinite(grad_norm):
            ema = self._ema
            if ema is not None and self._n_obs >= self.warmup \
                    and grad_norm > self.spike_factor * ema:
                if not self._spike_latched:
                    self._spike_latched = True
                    fired.append("grad_spike")
                    flight.record("grad_spike", step=int(step),
                                  grad_norm=grad_norm,
                                  ema=round(ema, 9),
                                  factor=round(grad_norm / ema, 2))
            else:
                self._spike_latched = False
            self._ema = grad_norm if ema is None else \
                (1.0 - self.ema_alpha) * ema + self.ema_alpha * grad_norm
            self._n_obs += 1

        # -- train_stall: nothing-moving detector ----------------------
        if ratio is not None and math.isfinite(ratio):
            if ratio < self.stall_ratio:
                self._stall_run += 1
                if self._stall_run >= self.stall_steps \
                        and not self._stall_latched:
                    self._stall_latched = True
                    fired.append("train_stall")
                    flight.record("train_stall", step=int(step),
                                  update_ratio=ratio,
                                  run=self._stall_run)
            else:
                self._stall_run = 0
                self._stall_latched = False

        self.events_fired += len(fired)
        return fired

    def _write_bundle(self, step: int, loss: float, nonfinite: int):
        """The diverging run's post-mortem: a minimal breach report +
        the flight ring window + the clock's /trainz snapshot, through
        the PR-13 forensics machinery. Never fatal — a full disk must
        not kill the training loop that just survived a NaN."""
        try:
            from dnn_tpu.obs.slo import SLOReport, write_incident_bundle

            now = time.time()
            clock = self._clock if self._clock is not None \
                else active_trainlens()
            report = SLOReport(
                scenario="train", ok=False,
                objectives=[{
                    "name": "loss_finite", "ok": False,
                    "measured": loss, "threshold": "finite",
                    "detail": (f"nonfinite loss/grads at step {step} "
                               f"({nonfinite} nonfinite grad elements)"),
                }],
                requests=int(step), completed=int(step), rejected=0,
                lost=0, goodput_tps=0.0, wall_s=0.0,
                breach_window=(now, now))
            write_incident_bundle(self.bundle_dir, report,
                                  stepclock=clock)
        except Exception:  # noqa: BLE001
            import logging

            logging.getLogger("dnn_tpu.obs").exception(
                "trainlens: incident bundle write failed")
