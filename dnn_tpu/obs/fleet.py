"""Fleet observability: cross-process aggregation and trace stitching.

The paper's core artifact is a MULTI-PROCESS pipeline (one node per
stage, activations relayed over gRPC), yet every obs surface built so
far (/metrics, /statusz, /debugz, /trace) is per-process: a 3-stage
operator gets three disjoint dashboards on three unsynchronized clocks,
and no answer to "which stage is the bottleneck, how big is the
pipeline bubble, and what fraction of peak are we using". This module
is the control-plane collector that merges them:

  * DISCOVERY + POLLING: stage endpoints come from explicit `targets`
    (base URLs of each node's obs endpoint) or from the pipeline config
    (`targets_from_config` — every node's host + one shared metrics
    port). A daemon thread polls each node's existing /metrics,
    /statusz, and /trace.jsonl on an interval; nothing new runs on the
    stages themselves.

  * MERGED VIEW (/fleetz, or the one-shot terminal report): worst-of
    health rollup (the fleet /healthz), per-stage RPC / decode / queue
    percentiles side by side, fleet-total throughput, live MFU/MBU per
    stage (obs/goodput.py gauges), and the estimated clock offsets.

  * CROSS-HOST TRACE STITCHING: every RPC hop already links spans
    across processes (the server's root span parents under the client's
    rpc span via the wire tag — obs/trace.py), but each host stamps its
    spans with ITS OWN clock. The collector estimates per-stage clock
    offset NTP-style from those very hops: the client span's wall-clock
    send/receive window (`cs`/`cr` attrs, comm/client.py) brackets the
    server span, so  offset = server_midpoint - client_midpoint  per
    hop; the median over hops gives the pair offset, and a BFS over the
    pair graph anchors every stage to one timeline. `stitch()` then
    emits ONE Perfetto/Chrome trace with one process track per stage.

  * CRITICAL PATH + BUBBLE: with one request's spans on one corrected
    timeline, `critical_path()` sweeps the leaf (work) spans from
    request start to end, yielding the chain of spans that actually
    gates latency and the BUBBLE FRACTION — the part of the request's
    wall time no stage was working on it (queueing, wire, scheduling
    gaps). MPMD pipeline work (arxiv 2412.14374) shows this is *the*
    actionable signal for pipeline configurations.

Pure stdlib + utils.metrics — no jax anywhere, so the collector runs on
any operator laptop. CLI: `python -m dnn_tpu.obs fleet` (obs/__main__).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional
from urllib.request import urlopen

log = logging.getLogger("dnn_tpu.obs")

__all__ = [
    "FleetCollector", "parse_prometheus", "estimate_offsets",
    "critical_path", "stitch_spans", "targets_from_config",
]

# health ranking for the worst-of rollup; "unreachable" sits between
# degraded and wedged: the stage may be mid-restart (don't page as hard
# as a confirmed-wedged chip) but the pipeline through it IS down.
# "draining" (ISSUE 8: a stage whose admission is closed while
# in-flight work finishes) ranks with degraded — route around it, but
# nothing is broken
_STATE_RANK = {"ok": 0, "degraded": 1, "draining": 1, "unreachable": 2,
               "wedged": 3}
# map a fleet state onto the watchdog's three-valued vocabulary so the
# existing /healthz handler (503 on "wedged") serves the fleet too
_STATE_AS_WATCHDOG = {"ok": "ok", "degraded": "degraded",
                      "draining": "degraded",
                      "unreachable": "wedged", "wedged": "wedged"}


# ----------------------------------------------------------------------
# Prometheus text parsing (the poller's half of render_prometheus)
# ----------------------------------------------------------------------

_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+([^\s]+)\s*$')
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict:
    """Prometheus text exposition -> {"types": {family: kind},
    "samples": [(family, labels_dict, value)]}. Tolerant: malformed
    lines are skipped (one stage on an older build must not take the
    fleet view down)."""
    types: Dict[str, str] = {}
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _LINE.match(line)
        if not m:
            continue
        name, _, labels_raw, val = m.groups()
        try:
            value = float(val.replace("+Inf", "inf"))
        except ValueError:
            continue
        labels = {}
        if labels_raw:
            for lm in _LABEL.finditer(labels_raw):
                labels[lm.group(1)] = (lm.group(2)
                                       .replace(r'\"', '"')
                                       .replace("\\\\", "\\"))
        samples.append((name, labels, value))
    return {"types": types, "samples": samples}


class _Samples:
    """Query helper over parsed samples."""

    def __init__(self, parsed: dict):
        self._samples = parsed["samples"]

    def get(self, family: str, default=None, **labels):
        for name, labs, v in self._samples:
            if name == family and all(labs.get(k) == str(w)
                                      for k, w in labels.items()):
                return v
        return default

    def sum(self, family: str, **labels) -> Optional[float]:
        hit = False
        total = 0.0
        for name, labs, v in self._samples:
            if name == family and all(labs.get(k) == str(w)
                                      for k, w in labels.items()):
                hit, total = True, total + v
        return total if hit else None

    def hist_quantile(self, family: str, q: float,
                      **labels) -> Optional[float]:
        """histogram_quantile over `family` (summed across any label
        sets matching `labels`, `le` excluded) — linear interpolation
        inside the winning bucket, the Prometheus convention."""
        buckets: Dict[float, float] = defaultdict(float)
        for name, labs, v in self._samples:
            if name != family + "_bucket":
                continue
            if not all(labs.get(k) == str(w) for k, w in labels.items()):
                continue
            try:
                le = float(labs.get("le", "").replace("+Inf", "inf"))
            except ValueError:
                continue
            buckets[le] += v
        if not buckets:
            return None
        pairs = sorted(buckets.items())
        total = pairs[-1][1]  # the +Inf bucket is cumulative total
        if total <= 0:
            return None
        target = q * total
        prev_le, prev_c = 0.0, 0.0
        for le, c in pairs:
            if c >= target:
                if le == float("inf"):
                    return prev_le
                span = c - prev_c
                frac = (target - prev_c) / span if span else 1.0
                return prev_le + (le - prev_le) * frac
            prev_le, prev_c = le, c
        return prev_le


# ----------------------------------------------------------------------
# clock-offset estimation (NTP-style, from the existing RPC spans)
# ----------------------------------------------------------------------

_CLIENT_SPAN_NAMES = ("rpc.SendTensor", "rpc.forward",
                      "rpc.GenerateStream", "rpc.SendMessage")

# leaf spans that measure WAITING, not stage work — critical_path must
# count their cover as bubble (see its docstring)
_WAIT_SPAN_NAMES = frozenset({"queue_wait"})


def estimate_offsets(spans_by_stage: Dict[str, List[dict]],
                     anchor: Optional[str] = None) -> Dict[str, float]:
    """Per-stage clock offset (seconds to SUBTRACT from a stage's span
    timestamps to land on the anchor stage's timeline).

    Every cross-process hop gives one sample: the client-side rpc span
    (stage U) brackets the server's root span (stage T, parented under
    it via the wire tag). With symmetric network delay the server span's
    midpoint coincides with the client window's midpoint on the TRUE
    timeline, so  offset(T rel U) = server_mid - client_mid  — the
    classic NTP midpoint estimate; the error is bounded by the one-way
    delay asymmetry, far below the multi-ms skew it corrects. The
    client midpoint prefers the `cs`/`cr` wall-clock attrs (the
    successful attempt's window, comm/client.py) over the span's ts/dur,
    which includes retry backoff. Per-pair samples reduce by MEDIAN
    (kills the retried-hop and GC-pause outliers); a BFS over the pair
    graph chains offsets for stages the anchor never calls directly."""
    # span_id -> (stage, span) for client-side rpc spans
    client_by_id: Dict[str, tuple] = {}
    for stage, spans in spans_by_stage.items():
        for s in spans:
            if s.get("name") in _CLIENT_SPAN_NAMES:
                client_by_id[s["span_id"]] = (stage, s)
    pair_samples: Dict[tuple, List[float]] = defaultdict(list)
    for stage, spans in spans_by_stage.items():
        for s in spans:
            p = s.get("parent_id")
            if not p or p not in client_by_id:
                continue
            c_stage, c = client_by_id[p]
            if c_stage == stage:
                continue  # same process: same clock, no information
            attrs = c.get("attrs") or {}
            cs, cr = attrs.get("cs"), attrs.get("cr")
            if cs and cr:
                client_mid = (cs + cr) / 2.0
            else:
                client_mid = c["ts"] + (c.get("dur") or 0.0) / 2.0
            server_mid = s["ts"] + (s.get("dur") or 0.0) / 2.0
            pair_samples[(c_stage, stage)].append(server_mid - client_mid)

    def med(xs):
        xs = sorted(xs)
        n = len(xs)
        return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2

    # undirected adjacency with directed medians
    adj: Dict[str, Dict[str, float]] = defaultdict(dict)
    for (u, t), xs in pair_samples.items():
        o = med(xs)
        adj[u][t] = o       # t's clock = u's clock + o
        adj[t].setdefault(u, -o)
    stages = list(spans_by_stage)
    if anchor is None:
        anchor = stages[0] if stages else None
    offsets: Dict[str, float] = {}
    if anchor is None:
        return offsets
    offsets[anchor] = 0.0
    frontier = [anchor]
    while frontier:
        u = frontier.pop()
        for t, o in adj.get(u, {}).items():
            if t not in offsets:
                offsets[t] = offsets[u] + o
                frontier.append(t)
    for s in stages:  # unlinked stages: no evidence, assume in sync
        offsets.setdefault(s, 0.0)
    return offsets


# ----------------------------------------------------------------------
# stitching + critical path
# ----------------------------------------------------------------------

def stitch_spans(spans_by_stage: Dict[str, List[dict]],
                 offsets: Optional[Dict[str, float]] = None,
                 trace_id: Optional[str] = None) -> dict:
    """Merge per-stage span dumps into ONE Chrome-trace/Perfetto JSON on
    one corrected timeline: one PROCESS track per stage (pid = stage
    order, process_name metadata), one thread track per original
    (stage, tid), every event's args carrying the stage and the offset
    applied. Spans are deduped by span_id (overlapping polls of a
    stage's ring re-fetch old spans)."""
    if offsets is None:
        offsets = estimate_offsets(spans_by_stage)
    events = []
    tid_tracks: Dict[tuple, int] = {}
    seen: set = set()
    for pid, (stage, spans) in enumerate(spans_by_stage.items(), 1):
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"stage {stage}"}})
        off = offsets.get(stage, 0.0)
        for s in spans:
            if trace_id is not None and s.get("trace_id") != trace_id:
                continue
            if s["span_id"] in seen:
                continue
            seen.add(s["span_id"])
            key = (pid, s.get("tid", 0))
            if key not in tid_tracks:
                tid_tracks[key] = len(tid_tracks) + 1
                events.append({
                    "ph": "M", "pid": pid, "tid": tid_tracks[key],
                    "name": "thread_name",
                    "args": {"name": f"{stage} thread {s.get('tid', 0)}"},
                })
            events.append({
                "name": s["name"], "cat": "dnn_tpu_fleet", "ph": "X",
                "ts": round((s["ts"] - off) * 1e6, 3),
                "dur": round((s.get("dur") or 0.0) * 1e6, 3),
                "pid": pid, "tid": tid_tracks[key],
                "args": {**(s.get("attrs") or {}),
                         "trace_id": s.get("trace_id"),
                         "span_id": s["span_id"],
                         "parent_id": s.get("parent_id"),
                         "stage": stage,
                         "clock_offset_s": round(off, 6)},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def critical_path(spans: List[dict]) -> dict:
    """Critical-path / bubble attribution for ONE request's spans on ONE
    corrected timeline (apply `estimate_offsets` first for cross-host
    trees).

    Work = the tree's LEAF spans (a parent span's self-time is
    coordination around its children), minus spans that MEASURE waiting
    (`queue_wait` — a leaf by construction, but its whole meaning is
    "no stage was working yet"; counting it as work would make an
    overloaded server read bubble-free). The sweep walks from the
    root's start to its end; at each instant the active leaf reaching
    furthest is "the" critical span, and instants covered by no leaf
    are BUBBLE — wall time no stage was working on the request
    (queueing, wire, scheduler gaps, pipeline stalls). Returns:

        {"total_s", "work_s", "bubble_s", "bubble_fraction",
         "path": [{"name", "stage", "enter_s", "exit_s"}, ...],
         "per_stage_busy_s": {stage: s}}

    `enter_s`/`exit_s` are relative to request start; a span appears in
    `path` only for the segment where it gates progress."""
    if not spans:
        return {"total_s": 0.0, "work_s": 0.0, "bubble_s": 0.0,
                "bubble_fraction": 0.0, "path": [],
                "per_stage_busy_s": {}}
    by_id = {s["span_id"]: s for s in spans}
    has_child: set = set()
    for s in spans:
        p = s.get("parent_id")
        if p in by_id:
            has_child.add(p)
    roots = [s for s in spans if s.get("parent_id") not in by_id]
    root = min(roots, key=lambda s: s["ts"]) if roots \
        else min(spans, key=lambda s: s["ts"])
    t0 = root["ts"]
    t1 = root["ts"] + (root.get("dur") or 0.0)
    leaves = [s for s in spans
              if s["span_id"] not in has_child and s is not root
              and s["name"] not in _WAIT_SPAN_NAMES]
    if not leaves:
        leaves = [root]
    ivs = []
    for s in leaves:
        a = max(s["ts"], t0)
        b = min(s["ts"] + (s.get("dur") or 0.0), t1)
        if b > a:
            ivs.append((a, b, s))
    ivs.sort(key=lambda x: (x[0], -x[1]))
    per_stage: Dict[str, float] = defaultdict(float)
    # union coverage for work_s / per-stage busy
    cur_a = cur_b = None
    work = 0.0
    for a, b, s in ivs:
        stage = (s.get("attrs") or {}).get("stage") \
            or s.get("_stage") or "?"
        per_stage[stage] += b - a
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                work += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        work += cur_b - cur_a
    # greedy chain: at time t, the active interval reaching furthest
    path = []
    t = t0
    i = 0
    n = len(ivs)
    while t < t1 and i < n:
        best = None
        j = i
        while j < n and ivs[j][0] <= t + 1e-9:
            if best is None or ivs[j][1] > best[1]:
                best = ivs[j]
            j += 1
        if best is None or best[1] <= t + 1e-9:
            # bubble: jump to the next interval's start
            nxt = ivs[i][0] if ivs[i][0] > t else None
            for a, b, _ in ivs[i:]:
                if a > t and b > t:
                    nxt = a
                    break
            if nxt is None:
                break
            t = nxt
            continue
        a, b, s = best
        path.append({
            "name": s["name"],
            "stage": (s.get("attrs") or {}).get("stage")
            or s.get("_stage") or "?",
            "enter_s": round(max(t, a) - t0, 6),
            "exit_s": round(b - t0, 6),
        })
        t = b
        while i < n and ivs[i][1] <= t + 1e-9:
            i += 1
    total = max(t1 - t0, 0.0)
    work = min(work, total)
    return {
        "total_s": round(total, 6),
        "work_s": round(work, 6),
        "bubble_s": round(total - work, 6),
        "bubble_fraction": round(1.0 - work / total, 4) if total else 0.0,
        "path": path,
        "per_stage_busy_s": {k: round(v, 6)
                             for k, v in sorted(per_stage.items())},
    }


# ----------------------------------------------------------------------
# the collector
# ----------------------------------------------------------------------

def targets_from_config(config, metrics_port: int) -> Dict[str, str]:
    """{stage name: obs base URL} from a pipeline TopologyConfig (or a
    path to one): every node's host + one shared metrics port — the
    deployment convention where each node passes the same
    --metrics_port."""
    if isinstance(config, str):
        from dnn_tpu.config import TopologyConfig

        config = TopologyConfig.from_json(config)
    out = {}
    for node in config.nodes:
        host = (node.address or "127.0.0.1").rsplit(":", 1)[0]
        out[node.id] = f"http://{host}:{metrics_port}"
    if len(set(out.values())) != len(out):
        # same-host nodes share one derived URL: one endpoint would be
        # polled under N names and the others silently never — refuse
        # rather than double-count
        raise ValueError(
            "pipeline config derives duplicate obs URLs (multiple nodes "
            "share a host, so one --metrics_port cannot address them "
            f"all): {out} — pass explicit per-stage targets instead "
            "(--fleet_targets / --targets)")
    return out


class FleetCollector:
    """Poll every stage's obs endpoint; serve the merged view.

    `targets`: {stage name: base URL} (or a list of URLs — names derive
    from the URLs). `interval_s`: poll period of the daemon thread
    (`start()`); `poll_once()` polls synchronously (the one-shot report
    path). All state is swapped atomically under a lock, so /fleetz
    renders a consistent snapshot while the poller runs."""

    def __init__(self, targets, *, interval_s: float = 5.0,
                 timeout_s: float = 5.0, span_cap: int = 20000,
                 poll_traces: bool = True):
        if isinstance(targets, (list, tuple)):
            targets = {u.split("//")[-1]: u for u in targets}
        self.targets: Dict[str, str] = {
            name: url.rstrip("/") for name, url in targets.items()}
        if not self.targets:
            raise ValueError("fleet collector needs at least one target")
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._span_cap = int(span_cap)
        # poll_traces=False skips each target's /trace.jsonl entirely —
        # signal-plane consumers (the router's ReplicaSet) poll at
        # ~1 s cadence and want fresh rows, not span stitching
        self._poll_traces = bool(poll_traces)
        self._lock = threading.Lock()
        self._snaps: Dict[str, dict] = {}
        # per-stage span cache keyed by span_id: successive polls of a
        # stage's bounded ring overlap; the cache keeps the union
        # (bounded — oldest evicted) so stitching sees whole requests
        # even when a poll lands mid-request
        self._spans: Dict[str, Dict[str, dict]] = {
            name: {} for name in self.targets}
        # derived-at-poll-time caches: offsets and trace-id ranking only
        # change when the span caches do, so scrapes (/fleetz every few
        # seconds) must not recompute them from full span copies
        self._offsets: Dict[str, float] = {}
        self._tids: List[str] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._polls = 0
        # autoscaling-signal memory: dnn_tpu_wanted_replicas is a
        # scrape-time gauge with no history — the collector is the one
        # place that sees every sample, so it records TRANSITIONS as
        # bounded flight events (`wanted_replicas_change`) and keeps a
        # bounded recent series on /fleetz: the demand trace a future
        # autoscaler replays (ROADMAP item 3). A step-function series —
        # one point per change — is complete: the gauge holds its value
        # between transitions.
        self._wanted_last: Optional[float] = None
        self._wanted_hist: "deque" = deque(maxlen=256)

    # -- polling -------------------------------------------------------

    def _fetch(self, url: str) -> str:
        with urlopen(url, timeout=self.timeout_s) as r:
            return r.read().decode()

    def _poll_target(self, name: str, url: str) -> dict:
        snap = {"url": url, "t": time.time(), "ok": False,
                "state": "unreachable", "error": None,
                "statusz": None, "metrics": None}
        try:
            snap["statusz"] = json.loads(self._fetch(url + "/statusz"))
            snap["metrics"] = parse_prometheus(
                self._fetch(url + "/metrics"))
            spans = []
            if self._poll_traces:
                for ln in self._fetch(url + "/trace.jsonl").splitlines():
                    ln = ln.strip()
                    if ln:
                        try:
                            spans.append(json.loads(ln))
                        except ValueError:
                            pass
            with self._lock:
                # scrape threads snapshot these caches under the same
                # lock (spans_by_stage) — hold it for the mutation so
                # the docstring's atomic-swap claim covers spans too
                cache = self._spans[name]
                for s in spans:
                    if "span_id" in s:
                        cache[s["span_id"]] = s
                while len(cache) > self._span_cap:
                    cache.pop(next(iter(cache)))
            snap["ok"] = True
            snap["state"] = (snap["statusz"] or {}).get("state", "ok")
            if snap["state"] not in _STATE_RANK:
                snap["state"] = "ok"
        except Exception as e:  # noqa: BLE001 — a down stage is a DATUM
            snap["error"] = str(e)[:200]  # (unreachable), never a crash
        return snap

    def poll_once(self) -> dict:
        """Poll every target (concurrently — one slow stage must not
        delay the others' freshness) and swap in the new snapshots."""
        results: Dict[str, dict] = {}
        threads = []

        def run(name, url):
            results[name] = self._poll_target(name, url)

        for name, url in self.targets.items():
            t = threading.Thread(target=run, args=(name, url),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(self.timeout_s * 2 + 5)
        # recompute the span-derived caches once per poll (a straggler
        # worker past its join timeout may still be ingesting — snapshot
        # under the lock it writes under)
        by_stage = self.spans_by_stage()
        offs = estimate_offsets(by_stage)
        counts: Dict[str, int] = defaultdict(int)
        for spans in by_stage.values():
            for s in spans:
                tid = s.get("trace_id")
                if tid:
                    counts[tid] += 1
        tids = [t for t, _ in
                sorted(counts.items(), key=lambda kv: -kv[1])]
        # wanted_replicas transition detection (explicit MAX across
        # targets, matching the fleetz rollup — a multi-router fleet
        # provisions for its hungriest front door). Outside the lock
        # for the fetch, inside for the history append; the flight
        # record self-gates on the obs env.
        wanted = None
        for snap in results.values():
            if snap.get("metrics") is not None:
                v = _Samples(snap["metrics"]).get(
                    "dnn_tpu_wanted_replicas")
                if v is not None and (wanted is None or v > wanted):
                    wanted = v
        with self._lock:
            self._snaps.update(results)
            self._offsets = offs
            self._tids = tids
            self._polls += 1
            if wanted is not None and wanted != self._wanted_last:
                self._wanted_hist.append(
                    {"t": round(time.time(), 3), "v": wanted})
                prev = self._wanted_last
                self._wanted_last = wanted
            else:
                prev = wanted = None
        if wanted is not None:
            from dnn_tpu.obs import flight as _flight

            _flight.record("wanted_replicas_change", prev=prev,
                           to=wanted)
        return results

    def start(self) -> "FleetCollector":
        def loop():
            while not self._stop.wait(
                    0.0 if self._polls == 0 else self.interval_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — keep polling
                    log.exception("fleet poll failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="obs-fleet-poller")
        self._thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- merged views --------------------------------------------------

    def status(self) -> dict:
        """Watchdog-shaped status for the fleet endpoint's /statusz +
        /healthz (obs/http.py expects {"state", "components"}): each
        stage is a component, the fleet state is the worst of them
        mapped onto ok|degraded|wedged (unreachable counts as wedged —
        the pipeline through that stage is down). Never-yet-polled
        reads degraded, not wedged: a collector that just started has
        no evidence either way."""
        with self._lock:
            snaps = dict(self._snaps)
        comps = {}
        worst = "ok"
        for name in self.targets:
            snap = snaps.get(name)
            if snap is None:
                st, detail = "degraded", "not polled yet"
            else:
                st = snap["state"]
                detail = snap["error"] or f"polled {snap['url']}"
            comps[name] = {"state": _STATE_AS_WATCHDOG[st],
                           "raw_state": st, "detail": detail}
            if _STATE_RANK.get(st, 1) > _STATE_RANK.get(worst, 0):
                worst = st
        return {"state": _STATE_AS_WATCHDOG[worst], "fleet_state": worst,
                "components": comps, "t": time.time()}

    def boot_signals(self, name: str) -> dict:
        """Raw boot/compile samples for one target — the caplens
        cold-start ledger's `signals` source (obs/caplens): the child
        measures its own boot (node.py `dnn_tpu_boot_*` gauges + the
        compile-telemetry counter), this collector scrapes it, the
        lens attributes the spawn->first-token wall. Empty dict while
        the target has no successful poll yet."""
        with self._lock:
            snap = self._snaps.get(name)
        if snap is None or snap.get("metrics") is None:
            return {}
        s = _Samples(snap["metrics"])
        return {
            "compile_seconds_total":
                s.sum("jax_compile_seconds_total"),
            "boot_imports_s": s.get("dnn_tpu_boot_imports_seconds"),
            "boot_weight_load_s":
                s.get("dnn_tpu_boot_weight_load_seconds"),
            "boot_compile_preready_s":
                s.get("dnn_tpu_boot_compile_preready_seconds"),
            "boot_ready_total_s":
                s.get("dnn_tpu_boot_ready_total_seconds"),
        }

    def spans_by_stage(self) -> Dict[str, List[dict]]:
        with self._lock:
            return {name: list(cache.values())
                    for name, cache in self._spans.items()}

    def offsets(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._offsets)

    def trace_ids(self) -> List[str]:
        """Distinct trace ids across the fleet, most-spanned first —
        the head is the best candidate for a complete stitched request.
        Computed at poll time (poll_once), not per scrape."""
        with self._lock:
            return list(self._tids)

    def stitch(self, trace_id: Optional[str] = None) -> dict:
        """One Perfetto JSON across all stages on the corrected
        timeline; `trace_id` restricts to one request."""
        return stitch_spans(self.spans_by_stage(), self.offsets(),
                            trace_id=trace_id)

    def request_report(self, trace_id: Optional[str] = None) -> dict:
        """Critical-path/bubble attribution for one request (default:
        the most-spanned trace). Spans are flattened across stages with
        offsets applied and each tagged with its stage."""
        by_stage = self.spans_by_stage()
        if trace_id is None:
            ids = self.trace_ids()
            if not ids:
                return {"error": "no traces collected yet"}
            trace_id = ids[0]
        offs = self.offsets()
        flat, seen = [], set()
        for stage, spans in by_stage.items():
            off = offs.get(stage, 0.0)
            for s in spans:
                if s.get("trace_id") != trace_id or s["span_id"] in seen:
                    continue
                seen.add(s["span_id"])
                c = dict(s)
                c["ts"] = s["ts"] - off
                c["_stage"] = stage
                flat.append(c)
        rep = critical_path(flat)
        rep["trace_id"] = trace_id
        rep["spans"] = len(flat)
        return rep

    def _stage_row(self, snap: Optional[dict]) -> dict:
        # never-yet-polled reads degraded (no evidence either way),
        # matching status() — "unreachable" is reserved for a poll that
        # actually failed, so a scrape between start() and the first
        # completed poll can't page as a down stage
        row = {"state": "degraded" if snap is None else snap["state"],
               "url": None if snap is None else snap["url"],
               "error": "not polled yet" if snap is None
               else snap["error"]}
        stz = (snap["statusz"] if snap is not None else None) or {}
        if stz.get("role"):
            # fleet role (dnn_tpu/control): replicas advertise
            # prefill|decode|both, the router advertises "router" — the
            # rollup's per-target role column
            row["role"] = stz["role"]
        if snap is None or snap["metrics"] is None:
            return row
        s = _Samples(snap["metrics"])
        ms = lambda v: None if v is None else round(v * 1e3, 3)  # noqa: E731
        row.update({
            "tokens_per_sec": s.get("serving_tokens_per_sec"),
            "goodput_tokens_per_sec":
                s.get("dnn_tpu_goodput_tokens_per_sec"),
            "mfu": s.get("dnn_tpu_mfu"),
            "mbu": s.get("dnn_tpu_mbu"),
            "queue_depth": s.get("serving_queue_depth"),
            "occupancy": s.get("serving_batch_occupancy"),
            "requests_total": s.sum("serving_requests_total"),
            "ttft_p50_ms": ms(s.get("serving_ttft_seconds",
                                    quantile="0.5")),
            "ttft_p99_ms": ms(s.get("serving_ttft_seconds",
                                    quantile="0.99")),
            "inter_token_p50_ms": ms(s.get("serving_inter_token_seconds",
                                           quantile="0.5")),
            "inter_token_p99_ms": ms(s.get("serving_inter_token_seconds",
                                           quantile="0.99")),
            "queue_wait_p99_ms": ms(s.get("serving_queue_wait_seconds",
                                          quantile="0.99")),
            "rpc_p50_ms": ms(s.hist_quantile("comm_rpc_latency_seconds",
                                             0.5)),
            "rpc_p99_ms": ms(s.hist_quantile("comm_rpc_latency_seconds",
                                             0.99)),
            "compiles_total": s.get("jax_compilations_total"),
            "kv_util": s.get("serving_kv_slot_utilization"),
            "slo_burn": {
                labs.get("slo"): v
                for name, labs, v in snap["metrics"]["samples"]
                if name == "dnn_tpu_slo_burn_rate"} or None,
        })
        # router-target series (dnn_tpu/control/router.py): present only
        # when this target IS a router — queue of in-flight forwards,
        # shed counts by reason, the autoscaling signal
        for fam, key in (("dnn_tpu_router_queue_depth", "router_queue"),
                         ("dnn_tpu_wanted_replicas", "wanted_replicas")):
            v = s.get(fam)
            if v is not None:
                row[key] = v
        # KV-tier series (dnn_tpu/kvtier): per-replica radix residency
        # + prefix effectiveness — present only when the replica serves
        # kv=paged with prefix_cache on
        for fam, key in (
                ("dnn_tpu_kvtier_blocks", "kvtier_blocks"),
                ("dnn_tpu_prefix_hit_ratio", "prefix_hit_ratio"),
                ("dnn_tpu_kvtier_remote_hit_ratio",
                 "kvtier_remote_ratio")):
            v = s.get(fam)
            if v is not None:
                row[key] = v
        # memory-economy series (obs/kvlens.py): the predicted hit
        # ratio at 1x/2x/4x of the replica's pool + the thrash bill —
        # present only when a lens rides the replica's radix store.
        # The 2x column is the capacity-sizing headline: "what would
        # doubling this replica's pool buy"
        for mult, key in (("1x", "kvlens_pred_1x"),
                          ("2x", "kvlens_pred_2x"),
                          ("4x", "kvlens_pred_4x")):
            v = s.get("dnn_tpu_kvlens_pred_hit_ratio", mult=mult)
            if v is not None:
                row[key] = v
        v = s.get("dnn_tpu_kvlens_thrash_chunk_seconds_total")
        if v is not None:
            row["kvlens_thrash_chunk_s"] = v
        # training series (obs/trainlens.py): present only when the
        # target is a training job serving /trainz's weak gauges — the
        # fleet view then answers "is the run compute-bound or
        # input-bound, and how stale is its newest checkpoint" without
        # a separate training dashboard
        for fam, key in (
                ("dnn_tpu_train_mfu", "train_mfu"),
                ("dnn_tpu_train_data_stall", "train_data_stall"),
                ("dnn_tpu_train_tokens_per_sec", "train_tokens_per_sec"),
                ("dnn_tpu_ckpt_staleness_seconds", "ckpt_staleness")):
            v = s.get(fam)
            if v is not None:
                row[key] = v
        sheds = s.sum("dnn_tpu_router_shed_total")
        if sheds is not None:
            row["shed_total"] = sheds
        # capacity series (obs/caplens.py on a router target) + the
        # per-replica cold-start evidence (node.py boot gauges,
        # obs/compile_watch compile counter) the ledger attributes from
        for fam, key in (
                ("dnn_tpu_caplens_arrival_rate_hz", "caplens_rate_hz"),
                ("dnn_tpu_caplens_peak_to_mean", "caplens_peak_to_mean"),
                ("dnn_tpu_caplens_coldstart_p50_seconds",
                 "coldstart_p50_s"),
                ("dnn_tpu_caplens_coldstart_coverage",
                 "coldstart_coverage"),
                ("dnn_tpu_boot_imports_seconds", "boot_imports_s"),
                ("dnn_tpu_boot_weight_load_seconds",
                 "boot_weight_load_s"),
                ("jax_compile_seconds_total", "compile_seconds")):
            v = s.get(fam)
            if v is not None:
                row[key] = v
        v = s.get("dnn_tpu_caplens_plan_availability", n="2")
        if v is not None:
            row["caplens_plan2_availability"] = v
        return row

    def fleetz(self) -> dict:
        """The merged fleet view (/fleetz): worst-of state, per-stage
        health + percentile tables side by side, fleet totals, clock
        offsets, and the current best-known trace ids."""
        with self._lock:
            snaps = dict(self._snaps)
            polls = self._polls
            wanted_hist = list(self._wanted_hist)
        stages = {name: self._stage_row(snaps.get(name))
                  for name in self.targets}
        status = self.status()

        def total(key):
            vals = [r[key] for r in stages.values()
                    if r.get(key) is not None]
            return round(sum(vals), 3) if vals else None

        return {
            "state": status["fleet_state"],
            "stages": stages,
            "fleet": {
                "tokens_per_sec": total("tokens_per_sec"),
                "goodput_tokens_per_sec": total("goodput_tokens_per_sec"),
                "requests_total": total("requests_total"),
                "stages_total": len(self.targets),
                "stages_ok": sum(1 for r in stages.values()
                                 if r["state"] == "ok"),
                # the autoscaling signal: explicit MAX across router
                # targets (a multi-front-door fleet must provision for
                # its hungriest router, and "first non-None" depended
                # on dict order) — the per-stage map keeps each
                # router's own verdict visible
                "wanted_replicas": max(
                    (r["wanted_replicas"] for r in stages.values()
                     if r.get("wanted_replicas") is not None),
                    default=None),
                "wanted_replicas_by_stage": {
                    name: r["wanted_replicas"]
                    for name, r in stages.items()
                    if r.get("wanted_replicas") is not None} or None,
                # the signal's recent history: one {"t", "v"} point per
                # TRANSITION observed by this collector (bounded; the
                # flight ring holds the same changes as events)
                "wanted_replicas_recent": wanted_hist,
                "shed_total": total("shed_total"),
            },
            "clock_offsets_s": {k: round(v, 6)
                                for k, v in self.offsets().items()},
            "trace_ids": self.trace_ids()[:20],
            "polls": polls,
            "t": time.time(),
        }

    def render_prom(self) -> str:
        """The fleet view re-exported in Prometheus text format (the
        /fleetz?format=prom passthrough): per-stage up/state plus the
        fleet totals, so one scrape of the collector covers the fleet's
        health without N scrape configs."""
        from dnn_tpu.utils.metrics import Metrics, labeled, \
            render_prometheus

        z = self.fleetz()
        m = Metrics()
        m.set("dnn_tpu_fleet_state",
              float(_STATE_RANK.get(z["state"], 1)))
        for key in ("tokens_per_sec", "goodput_tokens_per_sec"):
            if z["fleet"][key] is not None:
                m.set(f"dnn_tpu_fleet_{key}", z["fleet"][key])
        m.set("dnn_tpu_fleet_stages_ok", z["fleet"]["stages_ok"])
        m.set("dnn_tpu_fleet_stages_total", z["fleet"]["stages_total"])
        if z["fleet"].get("wanted_replicas") is not None:
            m.set("dnn_tpu_wanted_replicas",
                  z["fleet"]["wanted_replicas"])
        if z["fleet"].get("wanted_replicas_recent"):
            # how many transitions this collector has witnessed — a flat
            # line and a flapping autoscaler signal scrape differently
            m.set("dnn_tpu_wanted_replicas_changes_total",
                  float(len(z["fleet"]["wanted_replicas_recent"])))
        if z["fleet"].get("shed_total") is not None:
            m.set("dnn_tpu_fleet_shed_total", z["fleet"]["shed_total"])
        for name, row in z["stages"].items():
            m.set(labeled("dnn_tpu_fleet_stage_up", stage=name),
                  1.0 if row["state"] == "ok" else 0.0)
            m.set(labeled("dnn_tpu_fleet_stage_state", stage=name),
                  float(_STATE_RANK.get(row["state"], 1)))
            if row.get("role"):
                # role as a one-hot labeled gauge — the prom idiom for
                # a string-valued attribute
                m.set(labeled("dnn_tpu_fleet_stage_role", stage=name,
                              role=row["role"]), 1.0)
            for key in ("tokens_per_sec", "mfu", "mbu", "router_queue",
                        "shed_total", "kvtier_blocks",
                        "prefix_hit_ratio", "kvtier_remote_ratio",
                        "kvlens_pred_1x", "kvlens_pred_2x",
                        "kvlens_pred_4x", "kvlens_thrash_chunk_s",
                        "train_mfu", "train_data_stall",
                        "train_tokens_per_sec", "ckpt_staleness",
                        "wanted_replicas", "caplens_rate_hz",
                        "caplens_peak_to_mean", "coldstart_p50_s",
                        "coldstart_coverage", "compile_seconds",
                        "boot_imports_s", "boot_weight_load_s",
                        "caplens_plan2_availability"):
                if row.get(key) is not None:
                    m.set(labeled(f"dnn_tpu_fleet_stage_{key}",
                                  stage=name), row[key])
        for stage, off in z["clock_offsets_s"].items():
            m.set(labeled("dnn_tpu_fleet_clock_offset_seconds",
                          stage=stage), off)
        return render_prometheus(m)

    # -- the one-shot terminal report ----------------------------------

    def report(self, trace_id: Optional[str] = None) -> str:
        """Human-readable fleet report (the CLI's default output)."""
        z = self.fleetz()
        lines = [f"fleet state: {z['state']}  "
                 f"({z['fleet']['stages_ok']}/{z['fleet']['stages_total']}"
                 f" stages ok)"]
        cols = [("state", 11), ("role", 8), ("tokens_per_sec", 9),
                ("mfu", 7), ("mbu", 7), ("queue_depth", 6),
                ("ttft_p99_ms", 12), ("inter_token_p99_ms", 13),
                ("rpc_p99_ms", 11), ("kvtier_blocks", 8),
                ("prefix_hit_ratio", 9)]
        hdr = "stage".ljust(14) + "".join(h.rjust(w + 1)
                                          for h, w in cols)
        lines.append(hdr)

        def fmt(v, w):
            if v is None:
                return "-".rjust(w + 1)
            if isinstance(v, float):
                return f"{v:.3g}".rjust(w + 1)
            return str(v).rjust(w + 1)

        for name, row in z["stages"].items():
            lines.append(name.ljust(14) + "".join(
                fmt(row.get(h), w) for h, w in cols))
        ft = z["fleet"]
        if ft["tokens_per_sec"] is not None:
            lines.append(f"fleet total tokens/sec: "
                         f"{ft['tokens_per_sec']}")
        offs = {k: v for k, v in z["clock_offsets_s"].items()
                if abs(v) > 1e-4}
        if offs:
            lines.append("clock offsets (s, vs anchor): " + ", ".join(
                f"{k}={v:+.4f}" for k, v in offs.items()))
        rep = self.request_report(trace_id)
        if "error" not in rep:
            lines.append(
                f"request {rep['trace_id']}: total "
                f"{rep['total_s'] * 1e3:.1f} ms, bubble "
                f"{rep['bubble_fraction'] * 100:.1f}% "
                f"({rep['bubble_s'] * 1e3:.1f} ms idle)")
            for seg in rep["path"][:12]:
                lines.append(
                    f"  {seg['enter_s'] * 1e3:8.2f}.."
                    f"{seg['exit_s'] * 1e3:8.2f} ms  "
                    f"[{seg['stage']}] {seg['name']}")
        return "\n".join(lines)
