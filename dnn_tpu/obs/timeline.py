"""Step-timeline attribution: where does one decode step's wall time go?

ROADMAP item 4 claims the post-MBU gap is serialization — host dispatch
between steps, prefill stalling decode, per-token host syncs for
sampling — but until this module nothing in the repo DECOMPOSED a
decode step into those phases: MBU prices bytes, the loop-lag sanitizer
times callbacks, the fleet stitcher attributes inter-stage bubbles.
This is the intra-step instrument, in two connected halves:

  * **StepClock** — the serving step loop's phase clock. The
    ContinuousBatcher (and its speculative override) splits every
    decode iteration into named contiguous phases:

        admit     submit() end-to-end: validation, slot install,
                  prefill chunks, first-token sample (accumulated onto
                  the NEXT step's record — admits happen between steps)
        host      step-entry bookkeeping before the device call
                  (bucket growth, constraint-row flush)
        dispatch  the jit call itself, call-to-return — host time spent
                  handing the program to the runtime (the device begins
                  executing inside this window)
        wait      dispatch-return -> result-on-host: the blocking
                  device->host sync the per-token sampling commit
                  forces (np.asarray of the committed tokens — the
                  moral equivalent of block_until_ready)
        commit    the host slot loop: token append, stop/eos/constraint
                  checks, retirement (sampling/detokenize bookkeeping)
        obs       the step's one bulk registry update + goodput feed

    Derived series (definitions the item-4 overlap PR is judged by):

        device_s        = dispatch + wait   (the window the compiled
                          step program is in flight)
        host_s          = admit + host + commit + obs  (host work NOT
                          overlapped with the device program)
        host_fraction   = host_s / wall     — THE ratchet number: the
                          host-serialization share of step wall time.
                          Chunked-prefill interleave removes the admit
                          convoy; double-buffered dispatch hides
                          host/commit/obs under device steps.
        dispatch_slack  = host_s / device_s — the headroom
                          double-buffered dispatch would exploit
                          (< 1.0 means every host phase could hide
                          entirely under the device step)
        sync_tax        = wait / wall       — the per-token
                          device->host sampling sync's share (fused
                          on-device top-k/top-p sampling attacks this)

    All series land in the existing registry behind the one-None-check
    DNN_TPU_OBS gate: `begin()` returns None when the gate is off, and
    every producer site guards on that one None. Scrape-time CALLABLE
    gauges (step.dispatch_slack / step.sync_tax / step.host_fraction /
    step.per_sec / step.last_wall_ms) + fixed-bucket histograms
    (step.phase_seconds{phase=...}, step.wall_seconds). Phase-boundary
    timestamps are ring-buffered, so the last N steps export as a
    Perfetto-loadable host track (`chrome_trace()`, GET
    /stepz?format=trace).

  * **analyze()** — device-trace analysis: parses the gzipped Perfetto
    JSON the obs/profile.py Profiler already spools (stdlib gzip+json,
    no new deps) into structured numbers — per-track busy fraction,
    device busy/idle inside the capture window, the host-gap histogram
    between consecutive device ops (the serialization bubbles made
    visible), top-K ops by device time — and correlates them with the
    StepClock's step stream via the capture's sidecar `meta.json`
    (profile.py writes monotonic begin/end + step-counter range +
    backend), answering "how much of each step was the device actually
    busy".

Served via GET /stepz (JSON; ?format=prom|trace) on the obs endpoint
and `python -m dnn_tpu.obs timeline [--url URL | PATH]`. The asserted
baseline lives in benchmarks/step_timeline_probe.py: phase accounting
must cover >= 95% of externally measured wall time (no unattributed
dark time), and the measured host-serialization fraction is committed
to BASELINE.md as the floor item 4 must ratchet DOWN.

No jax import anywhere in this module — the clock is pure
perf_counter bookkeeping and analyze() is stdlib-only, so the CLI
works on any host (the obs/__main__.py contract).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

from dnn_tpu import obs as _obs
from dnn_tpu.utils.metrics import labeled

__all__ = ["StepClock", "PHASES", "STEP_BUCKETS", "analyze",
           "active_clock", "render_report"]

#: phase names, in within-step order (admit precedes the step proper)
PHASES = ("admit", "host", "dispatch", "wait", "commit", "obs")

#: histogram bounds for phase/wall series (seconds): decode phases run
#: tens of µs (host bookkeeping) through seconds (a cold dispatch)
STEP_BUCKETS = (2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                0.01, 0.025, 0.05, 0.1, 0.25, 1.0, 5.0)

_HOST_PHASES = ("admit", "host", "commit", "obs")
_DEVICE_PHASES = ("dispatch", "wait")

#: shared empty admit-slice seq — most steps have no admissions, and
#: the per-step allocation was measurable against the <2% obs budget;
#: end() REPLACES the attribute (never appends) when slices exist, and
#: every consumer (fold/summary/stepz) only iterates, so sharing is safe
_NO_ADMITS: tuple = ()


class _StepRec:
    """One step's phase boundaries: t0 at step entry, then (phase, t)
    marks in order — phase P's duration is its mark minus the previous
    boundary. `phases`/`wall` are folded LAZILY (`_fold`) at flush or
    scrape time: the producer path only stamps timestamps. The worker
    thread owns the record until `StepClock.end` publishes it into the
    ring; after that it is append-only, and the idempotent fold from a
    scrape thread recomputes the same values it would assign twice."""

    __slots__ = ("t0", "t_end", "marks", "n_adv", "wall", "phases",
                 "admit_slices", "mixed")

    def __init__(self, t0: float):
        self.t0 = t0
        self.t_end = t0
        self.marks: list = []
        self.n_adv = 0
        self.wall = 0.0
        self.phases: "Optional[Dict[str, float]]" = None
        self.admit_slices = _NO_ADMITS
        # mixed = this step's dispatch folded an interleaved prefill
        # chunk (serving prefill_chunk_tokens) — /stepz distinguishes
        # interleaved-prefill steps from pure-decode steps with it
        self.mixed = False


def _fold(rec: _StepRec) -> _StepRec:
    """Fold a published record's marks into per-phase durations (in
    place, idempotent). Runs off the step path — at flush and scrape
    time only."""
    if rec.phases is not None:
        return rec
    phases: Dict[str, float] = {}
    t = rec.t0
    for name, tm in rec.marks:  # marks are unique per step
        phases[name] = tm - t
        t = tm
    if rec.t_end > t:
        # remainder after the last mark (end() stamps right after the
        # "obs" mark, so this is ns-scale) stays attributed
        phases["obs"] = phases.get("obs", 0.0) + (rec.t_end - t)
    admit_s = sum(t1 - t0 for t0, t1 in rec.admit_slices)
    if admit_s:
        phases["admit"] = phases.get("admit", 0.0) + admit_s
    rec.wall = (rec.t_end - rec.t0) + admit_s
    rec.phases = phases
    return rec


class StepClock:
    """Per-phase decode-step clock. Attach post-construction like the
    goodput tracker (`batcher.step_clock = StepClock().install()`);
    the batcher's step()/submit() feed it behind the obs gate.

    Producer protocol (what serving.py calls):

        rec = clock.begin()            # None when the obs gate is off
        ... bookkeeping ...            # -> "host"
        clock.mark(rec, "host")
        ... device call ...            # -> "dispatch"
        clock.mark(rec, "dispatch")
        ...
        clock.end(rec, n_adv)          # publishes + one bulk registry
                                       # update (counters, histograms,
                                       # idempotent gauge re-register)

    submit() reports its whole wall as `note_admit(t0)`; pending admit
    slices attach to the NEXT step's record (admissions happen between
    steps, and the worker loop's iteration = admits + one step).

    Thread safety: the worker thread produces; /stepz scrapes read the
    ring under the lock. `now` is injectable for deterministic tests —
    but it governs only the CLOCK-driven methods (begin/mark/end/
    note_admit): the serving producers stamp `time.perf_counter()`
    inline (a method call per mark was measurable against the <2%
    obs budget), so attach only default-`now` clocks to a real pool;
    injected clocks are for hand-driven records.

    Registry cost: per-step observations are accumulated locally and
    FLUSHED in one bulk update every `FLUSH_EVERY` steps (summary()/
    render_prom() flush first, so scrapes stay fresh) — per-step
    histogram observes measurably taxed the sub-ms decode step this
    clock exists to measure (the obs_overhead <2% contract prices it).
    The derived gauges are scrape-time callables over the ring, so
    they are exact at every scrape regardless of the flush cadence.
    """

    FLUSH_EVERY = 32

    def __init__(self, capacity: int = 256, *, registry=None,
                 now=time.perf_counter):
        self.capacity = int(capacity)
        self._ring: "deque[_StepRec]" = deque(maxlen=self.capacity)
        self._now = now
        self._lock = threading.Lock()
        self._pending_admit: list = []
        self.steps_total = 0
        self._registry = registry
        self._t_last_end: Optional[float] = None
        # registry batch: records awaiting the bulk flush (end() only
        # appends; flush() does the per-phase fan-out off the hot path)
        self._pending_flush: list = []
        self._pending_bulk: list = []  # landed, not yet billed
        # (steps_total, {...}) memo for the derived gauges — see _derived
        self._derived_cache = None
        # memoized labeled histogram keys — string formatting is
        # measurable on the per-step path (the serving _bucket_key
        # lesson)
        self._hist_keys = {p: labeled("step.phase_seconds", phase=p)
                           for p in PHASES}
        # scrape-time callable gauges, weakly bound: the registry must
        # not pin a dead clock (and its ring) for the process lifetime
        ref = weakref.ref(self)

        def _weak(method):
            def read():
                c = ref()
                return getattr(c, method)() if c is not None else 0.0
            return read

        # overlap_depth: how many dispatched-but-uncommitted steps the
        # producer's pipeline holds (0 = classic dispatch→wait→commit;
        # 1 = the batcher's double-buffered dispatch is live). Set by
        # the producer with one attr store; scraped like every gauge.
        self.overlap_depth = 0
        # constrained_slots: how many of the producer's live slots hold
        # a grammar constraint (ISSUE 16: constrained requests ride the
        # same hot path, so the scrape must say WHEN the host_fraction
        # it reports covered constraint-live traffic). Set by the
        # producer at admit/retire with one attr store, never per step.
        self.constrained_slots = 0
        self._gauges = {
            "step.dispatch_slack": _weak("dispatch_slack"),
            "step.sync_tax": _weak("sync_tax"),
            "step.host_fraction": _weak("host_fraction"),
            "step.per_sec": _weak("steps_per_sec"),
            "step.last_wall_ms": _weak("last_wall_ms"),
            "step.overlap_depth": _weak("_overlap_depth_read"),
            "step.constrained_slots": _weak("_constrained_slots_read"),
        }

    def install(self) -> "StepClock":
        """Make this the process's active clock (what profile.py's
        sidecar meta reads its step-counter range from)."""
        global _active_clock
        _active_clock = weakref.ref(self)
        return self

    # -- producer side (the batcher worker thread) ---------------------

    def begin(self) -> Optional[_StepRec]:
        """Start one step's record — None when observability is off
        (the producer's one None check covers every later site)."""
        if not _obs.enabled():
            return None
        return _StepRec(self._now())

    def mark(self, rec: _StepRec, phase: str):
        """Close the current phase at now (one perf_counter read + one
        tuple append on the hot path)."""
        rec.marks.append((phase, self._now()))

    def note_admit(self, t0: float):
        """One submit()'s wall interval [t0, now) — attached to the
        next step's record. Bounded: a pathological admit storm with no
        steps keeps the newest 64 slices. Lock-free: submit and step
        run on the ONE thread that owns the batcher (the lm_server
        worker contract), so the producer side never races itself —
        and flush()'s swap-then-read is safe against a GIL-atomic
        append (an append racing the swap lands in whichever list the
        interpreter saw, and both are drained)."""
        if not _obs.enabled():
            return
        t1 = self._now()
        pa = self._pending_admit
        pa.append((t0, t1))
        if len(pa) > 64:
            del pa[0]

    def end(self, rec: _StepRec, n_adv: int = 0):
        """Stamp and publish one step. Deliberately MINIMAL — one
        perf_counter read and ONE GIL-atomic append, no lock: this
        runs inside the decode loop the clock exists to measure, and
        the obs_overhead <2% contract prices every microsecond here.
        Single-producer by the batcher's threading contract. The rec
        lands only in the pending batch here; flush() moves the batch
        into the scrape ring (and runs the ring's evictions) every
        FLUSH_EVERY steps — ring maintenance per step was measurable
        against the budget, and every ring reader (_sums, records,
        summary, render_prom) flushes first, so scrapes stay exact.
        The phase fold and the registry bulk run off this path too."""
        rec.t_end = self._now()
        rec.n_adv = n_adv
        if self._pending_admit:
            rec.admit_slices, self._pending_admit = \
                self._pending_admit, []
        self.steps_total += 1
        self._t_last_end = rec.t_end
        pf = self._pending_flush
        pf.append(rec)
        if len(pf) >= self.FLUSH_EVERY:
            self.flush()

    def _land(self):
        """Move the pending batch into the scrape ring (one extend +
        up to FLUSH_EVERY evictions instead of an append+eviction per
        step). This is the HALF of flush() ring readers need — and the
        only half they may run: the registry's own gauge render calls
        the ring-derived series (dispatch_slack & co.) while HOLDING
        the registry lock, so a reader that reached Metrics.bulk from
        there would self-deadlock on that non-reentrant lock. Landed
        recs queue in _pending_bulk for the next real flush()'s
        histogram bill. The swap is locked against concurrent landers
        (two scrapes must not double-land a batch); a producer append
        racing the swap is GIL-atomic and lands in one of the two
        lists, never lost."""
        if not self._pending_flush:
            return
        with self._lock:
            pending, self._pending_flush = self._pending_flush, []
            self._ring.extend(pending)
            self._pending_bulk.extend(pending)

    def flush(self):
        """Land the accumulated observations in ONE bulk registry
        update. Called every FLUSH_EVERY steps by end(), and by
        summary()/render_prom() — StepClock's own scrape surfaces,
        never reached from inside a registry render — so a /stepz
        scrape never reads a stale histogram. Pending work is dropped
        (not retried) when the gate went off mid-batch — re-enabling
        starts clean."""
        m = self._registry if self._registry is not None \
            else _obs.metrics()
        self._land()
        with self._lock:
            pending, self._pending_bulk = self._pending_bulk, []
        if m is None or not pending:
            return
        hists: Dict[str, list] = {}
        walls = []
        for r in pending:
            _fold(r)
            for p, v in r.phases.items():
                hists.setdefault(self._hist_keys[p], []).append(v)
            walls.append(r.wall)
        hists["step.wall_seconds"] = walls
        m.bulk(counters={"step.steps_total": len(pending)},
               hists=hists, hist_buckets=STEP_BUCKETS,
               gauge_fns=self._gauges)

    # -- derived series (scrape-time reads over the ring) --------------

    def _sums(self, last: Optional[int] = None):
        self._land()  # ring readers: land only, never the registry
        with self._lock:
            recs = list(self._ring)
        if last:
            recs = recs[-last:]
        tot: Dict[str, float] = {p: 0.0 for p in PHASES}
        wall = 0.0
        n_adv = 0
        for r in recs:
            _fold(r)
            for p, v in r.phases.items():
                tot[p] = tot.get(p, 0.0) + v
            wall += r.wall
            n_adv += r.n_adv
        return recs, tot, wall, n_adv

    def _derived(self) -> dict:
        """The three ring-derived gauges from ONE _sums pass, memoized
        on the step counter: a /metrics render calls each gauge in the
        same scrape, and three independent ring copies + folds per
        scrape is pointless lock traffic against the producer. The
        cache read/write is a benign race (gauges may be stale by the
        one step that landed mid-scrape)."""
        key = self.steps_total
        cached = self._derived_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        _, tot, wall, _ = self._sums()
        dev = sum(tot[p] for p in _DEVICE_PHASES)
        host = sum(tot[p] for p in _HOST_PHASES)
        d = {
            "dispatch_slack": host / dev if dev > 0 else 0.0,
            "sync_tax": tot["wait"] / wall if wall > 0 else 0.0,
            "host_fraction": host / wall if wall > 0 else 0.0,
        }
        self._derived_cache = (key, d)
        return d

    def dispatch_slack(self) -> float:
        return self._derived()["dispatch_slack"]

    def sync_tax(self) -> float:
        return self._derived()["sync_tax"]

    def host_fraction(self) -> float:
        return self._derived()["host_fraction"]

    def steps_per_sec(self) -> float:
        """Rate over the ring's newest 60 s of records — computed at
        scrape time (a per-step Throughput feed measurably taxed the
        step; the ring already carries every timestamp needed)."""
        self._land()  # gauge-reachable: land only (registry deadlock)
        now = self._now()
        with self._lock:
            n = sum(1 for r in self._ring if now - r.t0 <= 60.0)
            oldest = self._ring[0].t0 if self._ring else now
        if n == 0:
            return 0.0
        # divide by the span the surviving records actually cover: a
        # full ring may have evicted part of the 60 s window
        return n / max(min(60.0, now - oldest), 1e-9)

    def _overlap_depth_read(self) -> float:
        return float(self.overlap_depth)

    def _constrained_slots_read(self) -> float:
        return float(self.constrained_slots)

    def last_wall_ms(self) -> float:
        self._land()  # gauge-reachable: land only (registry deadlock)
        with self._lock:
            if not self._ring:
                return 0.0
            rec = self._ring[-1]
        return _fold(rec).wall * 1e3

    def last_step_age_s(self) -> Optional[float]:
        with self._lock:
            t = self._t_last_end
        return None if t is None else max(0.0, self._now() - t)

    def records(self, last: Optional[int] = None) -> List[dict]:
        """Ring records as plain dicts (newest last) — what the probe's
        coverage assertion and analyze()'s step alignment read."""
        self._land()
        with self._lock:
            recs = list(self._ring)
        if last:
            recs = recs[-last:]
        return [{"t0": r.t0, "wall": _fold(r).wall, "n_adv": r.n_adv,
                 "mixed": r.mixed,
                 "phases": dict(r.phases),
                 "admit_slices": list(r.admit_slices),
                 "marks": list(r.marks)} for r in recs]

    # -- export surfaces -----------------------------------------------

    def summary(self, last: Optional[int] = None) -> dict:
        """The /stepz JSON payload: per-phase totals/means/fractions
        over the ring (or the newest `last` steps) plus the derived
        series."""
        self.flush()  # scrapes read fresh histograms/counters
        recs, tot, wall, n_adv = self._sums(last)
        n = len(recs)
        n_mixed = sum(1 for r in recs if r.mixed)
        phases = {}
        for p in PHASES:
            s = tot.get(p, 0.0)
            phases[p] = {"s": round(s, 6),
                         "frac": round(s / wall, 4) if wall > 0 else 0.0,
                         "mean_ms": round(s / n * 1e3, 4) if n else 0.0}
        dev = sum(tot[p] for p in _DEVICE_PHASES)
        host = sum(tot[p] for p in _HOST_PHASES)
        return {
            "steps_total": self.steps_total,
            "window_steps": n,
            "window_wall_s": round(wall, 6),
            "tokens": n_adv,
            # interleaved-prefill steps in the window (the `mixed` tag:
            # the dispatch folded a prompt chunk into the decode program)
            "mixed_steps": n_mixed,
            "mixed_frac": round(n_mixed / n, 4) if n else 0.0,
            # the producer's dispatch-pipeline depth (0 = no overlap,
            # 1 = double-buffered dispatch live)
            "overlap_depth": self.overlap_depth,
            # live slots holding a grammar constraint — says whether
            # the window's host_fraction covered constrained traffic
            "constrained_slots": self.constrained_slots,
            "phases": phases,
            "device_s": round(dev, 6),
            "host_s": round(host, 6),
            "host_fraction": round(host / wall, 4) if wall > 0 else 0.0,
            "dispatch_slack": round(host / dev, 4) if dev > 0 else 0.0,
            "sync_tax": round(tot["wait"] / wall, 4) if wall > 0 else 0.0,
            "steps_per_sec": round(self.steps_per_sec(), 3),
            "last_wall_ms": round(self.last_wall_ms(), 4),
        }

    def status_component(self) -> dict:
        """The /statusz `step` component: slow-but-healthy vs wedged at
        a glance, no profile pull needed. Informational — state stays
        "ok"; the watchdog's decode_heartbeat owns escalation (both
        read the same worker loop, so their recency agrees)."""
        s = self.summary()
        age = self.last_step_age_s()
        return {
            "state": "ok",
            "detail": (f"last step {s['last_wall_ms']:.2f} ms "
                       f"({'never' if age is None else f'{age:.1f}s ago'}), "
                       f"host fraction {s['host_fraction']:.0%}, "
                       f"{s['steps_per_sec']:.1f} steps/s"),
            "last_wall_ms": s["last_wall_ms"],
            "last_step_age_s": None if age is None else round(age, 3),
            "host_fraction": s["host_fraction"],
            "steps_per_sec": s["steps_per_sec"],
            "steps_total": s["steps_total"],
        }

    def render_prom(self, last: Optional[int] = None) -> str:
        """The ?format=prom re-export: the summary as gauges, for
        scrape-only collectors (same pattern as /statusz?format=prom).
        `last` bounds the window like the JSON form."""
        from dnn_tpu.utils.metrics import Metrics, render_prometheus

        s = self.summary(last)
        m = Metrics()
        for k in ("steps_total", "window_steps", "window_wall_s",
                  "host_fraction", "dispatch_slack", "sync_tax",
                  "steps_per_sec", "last_wall_ms", "mixed_steps",
                  "overlap_depth", "constrained_slots"):
            m.set(f"dnn_tpu_step_{k}", float(s[k]))
        for p, d in s["phases"].items():
            m.set(labeled("dnn_tpu_step_phase_seconds_total", phase=p),
                  d["s"])
            m.set(labeled("dnn_tpu_step_phase_frac", phase=p), d["frac"])
        return render_prometheus(m)

    def chrome_trace(self, last: Optional[int] = None) -> dict:
        """The ring as a Perfetto-loadable HOST track: one process
        ("stepclock"), one slice per phase per step (admit slices keep
        their own real boundaries — they happened before the step).
        Timestamps are perf_counter µs REBASED so the oldest exported
        slice starts at ts 0 (Perfetto renders absolute monotonic
        stamps days into the timeline). A device capture has its OWN ts
        origin (the profiler session start), so the two files do not
        overlay directly — `analyze()` + the sidecar meta do that
        correlation numerically (per-step device busy / overlap)."""
        self._land()
        with self._lock:
            recs = list(self._ring)
        if last:
            recs = recs[-last:]
        origin = 0.0
        if recs:
            r0 = recs[0]
            origin = min([r0.t0] + [a for a, _ in r0.admit_slices])
        events = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "stepclock"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "decode-step phases"}},
        ]
        for i, r in enumerate(recs):
            for a0, a1 in r.admit_slices:
                events.append({"ph": "X", "pid": 1, "tid": 1,
                               "name": "admit",
                               "ts": (a0 - origin) * 1e6,
                               "dur": (a1 - a0) * 1e6,
                               "args": {"step": i}})
            t = r.t0
            args = {"step": i, "n_adv": r.n_adv}
            if r.mixed:
                args["mixed"] = True
            for name, tm in r.marks:
                events.append({"ph": "X", "pid": 1, "tid": 1,
                               "name": name,
                               "ts": (t - origin) * 1e6,
                               "dur": (tm - t) * 1e6,
                               "args": args})
                t = tm
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# the process's active clock (profile.py sidecar meta reads it)
_active_clock: "Optional[weakref.ref]" = None


def active_clock() -> Optional[StepClock]:
    ref = _active_clock
    if ref is None:
        return None
    return ref()


# ----------------------------------------------------------------------
# capture analysis: the device half of the attribution
# ----------------------------------------------------------------------

#: host-gap histogram bounds (seconds between consecutive device ops)
GAP_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
               5e-3, 0.01, 0.05, 0.25)


def _merge(intervals: List[tuple]) -> List[tuple]:
    """Union of [t0, t1) intervals, sorted."""
    out: List[tuple] = []
    for t0, t1 in sorted(intervals):
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def _load_trace(path: str) -> dict:
    """One Perfetto/Chrome trace JSON, possibly gzipped. ValueError
    with a plain message for anything that is not one — a truncated
    spool or a stray file must fail loud, not half-parse."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as f:
                data = json.load(f)
        else:
            with open(path, "r") as f:
                data = json.load(f)
    except (OSError, EOFError, gzip.BadGzipFile, json.JSONDecodeError,
            UnicodeDecodeError) as e:
        raise ValueError(f"not a readable Perfetto JSON trace: {path} "
                         f"({e})") from None
    if isinstance(data, list):  # chrome's bare-array form
        data = {"traceEvents": data}
    if not isinstance(data, dict) or not isinstance(
            data.get("traceEvents"), list):
        raise ValueError(f"no traceEvents array in {path}")
    return data


def find_trace_file(path: str) -> str:
    """Resolve a capture DIR (obs/profile.py spool layout) or a direct
    trace-JSON path to the trace file to analyze (newest when several)."""
    if os.path.isdir(path):
        hits = sorted(
            glob.glob(os.path.join(path, "plugins", "profile", "*",
                                   "*.trace.json.gz"))
            or glob.glob(os.path.join(path, "*.trace.json.gz"))
            or glob.glob(os.path.join(path, "*.json.gz"))
            or glob.glob(os.path.join(path, "*.json")))
        if not hits:
            raise ValueError(f"no trace json found under {path}")
        return hits[-1]
    return path


def find_meta(path: str) -> Optional[dict]:
    """The sidecar meta.json for a capture (profile.py writes it at the
    capture root; a trace FILE lives a few levels below it)."""
    d = path if os.path.isdir(path) else os.path.dirname(path)
    for _ in range(4):
        cand = os.path.join(d, "meta.json")
        if os.path.isfile(cand):
            try:
                with open(cand) as f:
                    return json.load(f)
            except (OSError, json.JSONDecodeError):
                return None
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def analyze(path: str, *, clock: Optional[StepClock] = None,
            meta: Optional[dict] = None, top_k: int = 10) -> dict:
    """Structured numbers out of one device capture.

    `path` is a capture dir (POST /profilez's return) or a trace JSON
    (.json / .json.gz). Returns:

      window_s            capture window (first event start to last end)
      tracks              per-(process/thread) busy seconds + fraction
      device              busy/idle fraction of the union of DEVICE ops
                          (events carrying an hlo_op arg, or any event
                          on a "/device:*" process — covers the TPU/GPU
                          per-device tracks AND the CPU backend's
                          execution thread)
      host_gaps           histogram of the gaps between consecutive
                          device ops — each gap is host serialization
                          the device sat idle through
      top_ops             top-K op names by summed device time
      steps               StepClock correlation when a sidecar meta
                          (and optionally a live clock) places the
                          capture on the step axis: steps in window,
                          per-step device busy, device-overlap fraction

    Stdlib only; tolerant of the capture's host-side noise (the
    profiler's own start_trace span, threadpool markers)."""
    trace_file = find_trace_file(path)
    data = _load_trace(trace_file)
    if meta is None:
        meta = find_meta(path)

    proc_names: Dict[int, str] = {}
    thread_names: Dict[tuple, str] = {}
    xs = []
    for e in data["traceEvents"]:
        ph = e.get("ph")
        if ph == "M":
            args = e.get("args") or {}
            if e.get("name") == "process_name":
                proc_names[e.get("pid")] = str(args.get("name", ""))
            elif e.get("name") == "thread_name":
                thread_names[(e.get("pid"), e.get("tid"))] = str(
                    args.get("name", ""))
        elif ph == "X":
            xs.append(e)
    if not xs:
        raise ValueError(f"trace has no complete (ph=X) events: "
                         f"{trace_file}")

    def _num(e, k):
        v = e.get(k, 0.0)
        return float(v) if isinstance(v, (int, float)) else 0.0

    t_min = min(_num(e, "ts") for e in xs)
    t_max = max(_num(e, "ts") + _num(e, "dur") for e in xs)

    # ts-axis anchor for StepClock correlation: the trace's ts 0 is the
    # profiler SESSION start (start_trace entry), but the sidecar meta's
    # perf_begin lands at start_trace RETURN — a first capture pays
    # seconds of profiler init in between. The host track records that
    # init as a "start_trace" span; its END is where perf_begin sits on
    # the ts axis. Synthetic/processed traces without one anchor at 0.
    anchor = 0.0
    for e in xs:
        if "start_trace" in str(e.get("name", "")):
            anchor = _num(e, "ts") + _num(e, "dur")
            break

    # analysis window: the ARMED capture window (meta perf bounds,
    # anchored) when available — a first capture's init seconds must
    # not read as device idle — else the events' own span
    w0, w1 = t_min, t_max
    if meta is not None and isinstance(meta.get("perf_begin"),
                                       (int, float)) \
            and isinstance(meta.get("perf_end"), (int, float)):
        w0 = anchor
        w1 = anchor + (meta["perf_end"] - meta["perf_begin"]) * 1e6
    window_s = max(w1 - w0, 1e-9) / 1e6

    def _clipped_busy(merged) -> float:
        return sum(max(0.0, min(t1, w1) - max(t0, w0))
                   for t0, t1 in merged) / 1e6

    by_track: Dict[tuple, list] = {}
    device_ops: list = []
    for e in xs:
        key = (e.get("pid"), e.get("tid"))
        by_track.setdefault(key, []).append(e)
        args = e.get("args") or {}
        pname = proc_names.get(e.get("pid"), "")
        if "hlo_op" in args or "/device:" in pname \
                or pname.startswith("/device"):
            # skip the CPU runtime's zero-width threadpool markers —
            # they carry no hlo_op but would otherwise ride a /device
            # pid on some backends
            if _num(e, "dur") > 0.0 or "hlo_op" in args:
                device_ops.append(e)

    tracks = {}
    for (pid, tid), evs in sorted(by_track.items(),
                                  key=lambda kv: str(kv[0])):
        merged = _merge([(_num(e, "ts"), _num(e, "ts") + _num(e, "dur"))
                         for e in evs])
        busy = _clipped_busy(merged)
        name = (proc_names.get(pid, str(pid)) + "/"
                + thread_names.get((pid, tid), str(tid)))
        tracks[name] = {"events": len(evs),
                        "busy_s": round(busy, 6),
                        "busy_frac": round(busy / window_s, 4)}

    dev_ivals = _merge([(_num(e, "ts"), _num(e, "ts") + _num(e, "dur"))
                        for e in device_ops])
    dev_busy_s = _clipped_busy(dev_ivals)
    device = {
        "ops": len(device_ops),
        "busy_s": round(dev_busy_s, 6),
        "busy_frac": round(dev_busy_s / window_s, 4),
        "idle_frac": round(1.0 - dev_busy_s / window_s, 4),
    }

    gaps = [(t0 - prev_t1) / 1e6
            for (_, prev_t1), (t0, _) in zip(dev_ivals, dev_ivals[1:])
            if t0 > prev_t1]
    gap_hist: Dict[str, int] = {}
    for b in GAP_BUCKETS:
        gap_hist[f"le_{b:g}"] = sum(1 for g in gaps if g <= b)
    gap_hist["inf"] = len(gaps)
    gaps_sorted = sorted(gaps)

    def _pct(q):
        if not gaps_sorted:
            return 0.0
        k = min(len(gaps_sorted) - 1,
                int(round(q / 100.0 * (len(gaps_sorted) - 1))))
        return gaps_sorted[k]

    host_gaps = {
        "count": len(gaps),
        "total_s": round(sum(gaps), 6),
        "p50_ms": round(_pct(50) * 1e3, 4),
        "p90_ms": round(_pct(90) * 1e3, 4),
        "max_ms": round((gaps_sorted[-1] if gaps_sorted else 0.0) * 1e3,
                        4),
        "hist": gap_hist,
    }

    by_op: Dict[str, list] = {}
    for e in device_ops:
        by_op.setdefault(str(e.get("name", "?")), [0.0, 0])
        rec = by_op[str(e.get("name", "?"))]
        rec[0] += _num(e, "dur") / 1e6
        rec[1] += 1
    top_ops = [{"name": n, "total_ms": round(s * 1e3, 4), "count": c,
                "frac_of_device": round(s / dev_busy_s, 4)
                if dev_busy_s > 0 else 0.0}
               for n, (s, c) in sorted(by_op.items(),
                                       key=lambda kv: -kv[1][0])[:top_k]]

    steps = None
    if meta is not None:
        steps = {
            "backend": meta.get("backend"),
            "step_begin": meta.get("step_begin"),
            "step_end": meta.get("step_end"),
            "steps_in_capture": None,
            "aligned": False,
        }
        sb, se = meta.get("step_begin"), meta.get("step_end")
        if isinstance(sb, int) and isinstance(se, int):
            steps["steps_in_capture"] = se - sb
        pb = meta.get("perf_begin")
        if clock is None:
            clock = active_clock()
        if clock is not None and isinstance(pb, (int, float)):
            pe = meta.get("perf_end", float("inf"))

            def _ivals(r):
                # a record's PHYSICAL extent: its admit slices (which
                # happened before t0 — submit runs between steps) plus
                # the in-step span; wall is the summed length of these
                admit_s = sum(t1 - t0 for t0, t1 in r["admit_slices"])
                return list(r["admit_slices"]) + [
                    (r["t0"], r["t0"] + (r["wall"] - admit_s))]

            recs = [r for r in clock.records()
                    if all(pb <= a and b <= pe for a, b in _ivals(r))]
            if recs:
                # map each step's perf intervals onto the capture's ts
                # axis (perf_begin sits at `anchor`) and intersect with
                # the merged device intervals: per-step device busy
                per_step = []
                for r in recs:
                    busy = 0.0
                    for ia, ib in _ivals(r):
                        a = (ia - pb) * 1e6 + anchor
                        b = (ib - pb) * 1e6 + anchor
                        busy += sum(max(0.0, min(b, t1) - max(a, t0))
                                    for t0, t1 in dev_ivals)
                    per_step.append((r["wall"], busy / 1e6))
                wall_sum = sum(w for w, _ in per_step)
                busy_sum = sum(b for _, b in per_step)
                steps.update({
                    "aligned": True,
                    "n_steps": len(per_step),
                    "mean_wall_ms": round(wall_sum / len(per_step) * 1e3,
                                          4),
                    "mean_device_busy_ms": round(
                        busy_sum / len(per_step) * 1e3, 4),
                    "device_overlap_frac": round(busy_sum / wall_sum, 4)
                    if wall_sum > 0 else 0.0,
                })

    return {
        "trace_file": trace_file,
        "window_s": round(window_s, 6),
        "events": len(xs),
        "tracks": tracks,
        "device": device,
        "host_gaps": host_gaps,
        "top_ops": top_ops,
        "steps": steps,
    }


def render_report(a: dict) -> str:
    """Human-readable one-capture report (the CLI's default output)."""
    lines = [f"capture: {a['trace_file']}",
             f"window: {a['window_s'] * 1e3:.2f} ms, "
             f"{a['events']} events",
             f"device: busy {a['device']['busy_frac']:.1%} / idle "
             f"{a['device']['idle_frac']:.1%} "
             f"({a['device']['ops']} ops, "
             f"{a['device']['busy_s'] * 1e3:.2f} ms)",
             f"host gaps between device ops: {a['host_gaps']['count']} "
             f"(total {a['host_gaps']['total_s'] * 1e3:.2f} ms, "
             f"p50 {a['host_gaps']['p50_ms']:.3f} ms, "
             f"p90 {a['host_gaps']['p90_ms']:.3f} ms, "
             f"max {a['host_gaps']['max_ms']:.3f} ms)"]
    if a["top_ops"]:
        lines.append("top device ops:")
        for op in a["top_ops"]:
            lines.append(f"  {op['total_ms']:10.3f} ms  "
                         f"{op['frac_of_device']:6.1%}  x{op['count']:<5d}"
                         f" {op['name']}")
    st = a.get("steps")
    if st:
        if st.get("aligned"):
            lines.append(
                f"steps: {st['n_steps']} aligned to the capture — mean "
                f"wall {st['mean_wall_ms']:.3f} ms, device busy "
                f"{st['mean_device_busy_ms']:.3f} ms/step (overlap "
                f"{st['device_overlap_frac']:.1%})")
        elif st.get("steps_in_capture") is not None:
            lines.append(f"steps: {st['steps_in_capture']} in capture "
                         f"(counter {st['step_begin']}..{st['step_end']},"
                         f" backend {st.get('backend')}); none aligned "
                         "(no step records inside the window, or no "
                         "live clock)")
    lines.append("tracks:")
    for name, t in sorted(a["tracks"].items(),
                          key=lambda kv: -kv[1]["busy_s"]):
        lines.append(f"  {t['busy_frac']:6.1%} busy "
                     f"({t['busy_s'] * 1e3:9.2f} ms, {t['events']:6d} ev)"
                     f"  {name}")
    return "\n".join(lines)
