"""Hung-device watchdog: bounded liveness probes + heartbeat staleness.

The failure this machine keeps demonstrating (BENCH_r05, VERDICT round
5): a wedged TPU hangs `jax.devices()` — or the first device op — for
90+ seconds, IN PROCESS, where nothing can catch it. A server on a
wedged chip doesn't crash; it just stops, and /healthz (which only
checked thread liveness) kept saying "ok". This module is the
detector:

  * a daemon thread runs a DEVICE PROBE once per period, in a
    SUBPROCESS with a hard deadline (`subprocess_device_probe`) — a
    wedged chip hangs the probe child, never the server. Custom probe
    callables (tests stub a hanging one) are additionally bounded by a
    probe thread joined with the deadline, so even an in-process hang
    costs one leaked daemon thread, not the watchdog;
  * a DECODE HEARTBEAT: the LM batcher worker calls `beat()` every loop
    iteration; a heartbeat older than `heartbeat_stale_s` while the
    thread is supposedly alive means a step wedged inside the device
    runtime — the in-process hang the probe subprocess cannot see;
  * state is the worst component: `ok` -> `degraded` (probe errored
    fast — backend unhealthy but not hung) -> `wedged` (probe deadline
    exceeded, or heartbeat stale). Transitions land in the flight
    recorder (obs/flight.py) and the `dnn_tpu_watchdog_state` gauge
    (0/1/2); `GET /statusz` serves the full per-component detail and
    /healthz degrades from binary to ok|degraded|wedged (obs/http.py).

`bench.py`'s backend probe reuses `subprocess_device_probe` — the
round-robin bench and the serving watchdog share one definition of
"the chip answered".
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from typing import Callable, Optional, Tuple

__all__ = ["Watchdog", "subprocess_device_probe", "STATE_VALUES"]

STATE_VALUES = {"ok": 0.0, "degraded": 1.0, "wedged": 2.0}

_PROBE_CODE = ("import jax, jax.numpy as jnp; {pin}"
               "x = jnp.ones((128,128)) @ jnp.ones((128,128)); "
               "x.block_until_ready(); print(jax.default_backend())")
# in-process config, NOT a JAX_PLATFORMS env var: an out-of-tree device
# plugin can win platform selection over the env var, and the whole
# point of pinning is that a cpu-substrate server's probe must not
# touch (or queue behind) a device it doesn't serve on
_PIN_CODE = "jax.config.update('jax_platforms', {platform!r}); "


def subprocess_device_probe(deadline_s: float = 10.0,
                            platform: Optional[str] = None,
                            ) -> Tuple[bool, str, bool]:
    """One bounded probe: a tiny matmul in a child process, on
    `platform` if given (the backend the CALLER serves on — a probe
    that queues behind a device the server never uses answers the
    wrong liveness question), else the default backend. Returns
    (ok, detail, timed_out) — `timed_out` is the STRUCTURED hung-vs-
    failed distinction the watchdog classifies on (wedged vs degraded);
    the free-text detail is for humans only.
    Popen + wait(timeout), NOT subprocess.run: run()
    reaps the child after kill(), and a probe stuck in uninterruptible
    device I/O (D-state inside a wedged driver) cannot be reaped until
    the syscall returns — run() would hang right here. On timeout we
    kill best-effort and move on.

    The deadline clock covers the child's whole lifetime, `import jax`
    included (~4 s cold on a quiet 2-core host) — deadlines below ~6 s
    read a HEALTHY backend as wedged."""
    pin = _PIN_CODE.format(platform=platform) if platform else ""
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_CODE.format(pin=pin)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        rc = proc.wait(timeout=deadline_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        return False, f"probe timeout after {deadline_s:.0f}s", True
    return rc == 0, "ok" if rc == 0 else f"probe exited rc={rc}", False


class Watchdog:
    """Liveness monitor for one serving process. Construct, then
    `start()`; read `state()` / `status()`; `close()` to stop.

    device_probe: callable(deadline_s) -> (ok, detail) or (ok, detail,
    timed_out), or None to disable the device leg (CPU-only test
    servers). The default is `subprocess_device_probe`. Hung-vs-failed
    is decided STRUCTURALLY, never by sniffing the detail text: wedged
    when the probe reports timed_out=True, or when the call itself
    outlives its deadline (even if it eventually returns); a fast
    (False, detail) from a 2-tuple custom probe is by definition not
    hung and reads as degraded.

    alive_check: optional callable -> bool for the serving worker
    thread; False -> wedged (the work loop is gone).

    on_wedged: optional callable(detail) fired ONCE per wedged EPISODE
    (latched while the state stays wedged, re-armed when it recovers) —
    the escalation hook `--on_wedged restart|drain` wires to the
    supervisor/drain path (runtime/lm_server.py). Fired from the
    watchdog thread AFTER the state flip, so /statusz already reads
    wedged when the policy runs; exceptions are swallowed-but-logged
    (a broken policy must not kill the detector). The first-step
    warm-up grace rules are unchanged — a cold chip's compile still
    reads degraded, so the policy can never evict a healthy warming
    server.

    Chaos hook (dnn_tpu/chaos): when a fault plan with an active
    `wedge_device` window is installed in this process, the probe
    round reports that injected wedge (timed_out=True semantics)
    WITHOUT touching any device — the injection exercises exactly the
    classification + escalation path a real wedge would.
    """

    def __init__(self, *, period_s: float = 30.0,
                 probe_deadline_s: float = 10.0,
                 device_probe: "Optional[Callable]" = subprocess_device_probe,
                 heartbeat_stale_s: float = 120.0,
                 alive_check: Optional[Callable[[], bool]] = None,
                 on_wedged: Optional[Callable[[str], None]] = None,
                 registry=None):
        self.period_s = float(period_s)
        self.probe_deadline_s = float(probe_deadline_s)
        self.device_probe = device_probe
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.alive_check = alive_check
        self.on_wedged = on_wedged
        self._wedged_latched = False
        self._lock = threading.Lock()
        self._components: dict = {}
        self._t_beat: Optional[float] = None
        self._warmed = False  # a step has completed: see step_done()
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_result: Optional[tuple] = None  # (ok, detail[, timed_out])
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-watchdog")
        self._register_gauge(registry)

    def _register_gauge(self, registry):
        from dnn_tpu import obs

        reg = registry if registry is not None else obs.metrics()
        if reg is None:
            return
        import weakref

        ref = weakref.ref(self)

        def read() -> float:
            wd = ref()
            return STATE_VALUES[wd.state()] if wd is not None else 0.0

        reg.set_fn("dnn_tpu_watchdog_state", read)

    # -- producer side --------------------------------------------------

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def beat(self):
        """Heartbeat from the serving work loop (one perf_counter read +
        one attribute store; called every worker iteration)."""
        self._t_beat = time.perf_counter()

    def step_done(self):
        """A decode/prefill step COMPLETED (one attribute store; the LM
        worker calls this after every successful step). Until the first
        one, a stale heartbeat reads `degraded`, not `wedged`: the first
        step's XLA compile on a cold chip legitimately blocks the loop
        for minutes (bench.py allows 300 s for exactly this), and a 503
        there makes an orchestrator evict a healthy warming server —
        potentially forever, since each restart re-compiles."""
        self._warmed = True

    def close(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.period_s + 1)

    # -- state ----------------------------------------------------------

    def _set_component(self, name: str, state: str, detail: str):
        from dnn_tpu.obs import flight

        with self._lock:
            prev = self._components.get(name, {}).get("state")
            self._components[name] = {
                "state": state, "detail": detail, "t": time.time()}
        if prev != state:
            flight.record("watchdog", component=name,
                          prev=prev or "unknown", state=state,
                          detail=detail)

    def _check_heartbeat(self):
        if self.alive_check is not None and not self.alive_check():
            self._set_component("decode_heartbeat", "wedged",
                                "serving worker thread is not alive")
            return
        tb = self._t_beat
        if tb is None:
            return  # no loop has ever beaten: component not tracked
        age = time.perf_counter() - tb
        if age > self.heartbeat_stale_s:
            if not self._warmed:
                # no step has EVER completed: the loop is most likely
                # blocked in the first step's XLA compile (minutes on a
                # cold chip), not a wedge — visible, but not a 503
                self._set_component(
                    "decode_heartbeat", "degraded",
                    f"last heartbeat {age:.0f}s ago with no completed "
                    "step yet: first-step compile in progress, or the "
                    "device wedged at init")
                return
            self._set_component(
                "decode_heartbeat", "wedged",
                f"last heartbeat {age:.0f}s ago (stale > "
                f"{self.heartbeat_stale_s:.0f}s: a step is stuck inside "
                "the device runtime)")
        else:
            self._set_component("decode_heartbeat", "ok",
                                f"last heartbeat {age:.1f}s ago")

    def _run_probe(self):
        """One device-probe round. The probe runs on ITS OWN thread and
        we join with the deadline (+ slack for the subprocess probe,
        which bounds itself): a stubbed/in-process probe that hangs
        leaks exactly one daemon thread and reads as a timeout — and no
        new probe is spawned while the stuck one lives."""
        from dnn_tpu.chaos import inject as _chaos_inject

        injected = _chaos_inject.wedge_detail()
        if injected is not None:
            # chaos wedge_device window: the probe result IS the
            # injection (structural timed_out semantics) — no device
            # touched, same classification path as a real hang
            self._set_component("device", "wedged", injected)
            return
        if self._probe_thread is not None and self._probe_thread.is_alive():
            self._set_component(
                "device", "wedged",
                "previous probe still hung past its deadline")
            return

        def probe_main():
            try:
                self._probe_result = self.device_probe(self.probe_deadline_s)
            except Exception as e:  # noqa: BLE001 — a broken probe is a
                self._probe_result = (False, f"probe raised: {e}")  # result

        self._probe_result = None
        t = threading.Thread(target=probe_main, daemon=True,
                             name="obs-watchdog-probe")
        self._probe_thread = t
        t.start()
        # +2 s slack covers thread scheduling + Popen spawn only — the
        # subprocess probe's deadline clock already covers the child's
        # whole lifetime (jax import included), so a wedged chip reads
        # as wedged within probe_deadline_s + 2, well inside one period
        # at the production 30 s/10 s defaults
        t.join(timeout=self.probe_deadline_s + 2.0)
        res = self._probe_result
        if t.is_alive() or (res is None):
            self._set_component(
                "device", "wedged",
                f"device probe hung past {self.probe_deadline_s:.0f}s "
                "deadline")
            return
        ok, detail = res[0], res[1]
        timed_out = len(res) > 2 and bool(res[2])
        if ok:
            self._set_component("device", "ok", detail)
        elif timed_out:
            self._set_component("device", "wedged", detail)
        else:
            # fast failure: the backend answered, unhealthily — a HUNG
            # probe never reaches here (child timeout sets timed_out;
            # an in-process hang is caught by the join deadline above)
            self._set_component("device", "degraded", detail)

    def _fire_escalation(self):
        """Once-per-episode wedged escalation: latched while wedged,
        re-armed on recovery. Runs AFTER the component flip, so the
        policy sees consistent /statusz state."""
        if self.state() == "wedged":
            if not self._wedged_latched:
                self._wedged_latched = True
                cb = self.on_wedged
                if cb is not None:
                    detail = "; ".join(
                        f"{k}: {v['detail']}"
                        for k, v in self.status()["components"].items()
                        if v["state"] == "wedged")
                    try:
                        cb(detail)
                    except Exception:  # noqa: BLE001 — a broken policy
                        import logging

                        logging.getLogger("dnn_tpu.obs").exception(
                            "on_wedged escalation hook failed")
        else:
            self._wedged_latched = False

    def _run(self):
        while not self._stop.is_set():
            if self.device_probe is not None:
                self._run_probe()
            self._check_heartbeat()
            self._fire_escalation()
            # first round runs immediately (a wedged chip must be
            # reported within ONE period of startup), then period cadence
            self._stop.wait(self.period_s)

    def state(self) -> str:
        with self._lock:
            states = [c["state"] for c in self._components.values()]
        if not states:
            return "ok"
        return max(states, key=lambda s: STATE_VALUES[s])

    def status(self) -> dict:
        self._check_heartbeat()  # staleness must be fresh at read time
        with self._lock:
            comps = {k: dict(v) for k, v in self._components.items()}
        states = [c["state"] for c in comps.values()]
        return {
            "state": max(states, key=lambda s: STATE_VALUES[s])
            if states else "ok",
            "components": comps,
            "period_s": self.period_s,
            "probe_deadline_s": self.probe_deadline_s,
            "t": time.time(),
        }
