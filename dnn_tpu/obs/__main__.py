"""CLI: `python -m dnn_tpu.obs {trace,flight,fleet,timeline,incident,
kvlens,trainlens,caplens} ...` — obs tooling.

    python -m dnn_tpu.obs caplens --url http://host:port
        Fetch a running router's /capz (the capacity observatory,
        obs/caplens.py) and print the demand window (arrival rate,
        burstiness, per-scenario tokens), the learned per-role service
        distribution, the cold-start ledger (spawn->first-token p50
        with process-start/weight-load/compile/warmup buckets and
        coverage), the what-if plans at 1/2/4 replicas, and the
        audited wanted-replicas verdict. --json for the raw dict.

    python -m dnn_tpu.obs caplens PATH
        Render a saved /capz JSON dump (a `curl .../capz > capz.json`
        capture) with the same table — post-mortems read dumps, not
        live servers.

    python -m dnn_tpu.obs caplens --selftest
        In-process smoke: hand-computed planner goldens on an injected
        clock (1 replica shed-bound at 0.50 availability, 2 warm at
        1.00, bit-identical replay, cold-start debt priced), the
        audited 1->2 wanted transition, demand-window arithmetic,
        cold-start bucket attribution, gate-off-records-nothing, and
        the /capz endpoint in both formats; exit 0 on success. Tier-1
        wired (tests/test_obs_caplens.py).

    python -m dnn_tpu.obs trainlens --url http://host:port
        Fetch a running trainer's /trainz (the training-step
        observatory, obs/trainlens.py) and print the per-phase step
        decomposition (data/dispatch/wait/ckpt/eval/obs with fractions),
        the data-stall fraction, MFU against the device roofline,
        tokens/sec, and the checkpoint staleness. --json for the raw
        dict.

    python -m dnn_tpu.obs trainlens PATH
        Render a saved /trainz JSON dump (a `curl .../trainz >
        trainz.json` capture) with the same table — post-mortems read
        dumps, not live servers.

    python -m dnn_tpu.obs trainlens --selftest
        In-process smoke: hand-computed phase/stall/MFU goldens on an
        injected clock, checkpoint staleness arithmetic, the
        gradient-sentinel NaN latch, gate-off-records-nothing, and the
        /trainz endpoint in both formats; exit 0 on success. Tier-1
        wired (tests/test_obs_trainlens.py).

    python -m dnn_tpu.obs kvlens --url http://host:port
        Fetch a running server's /kvz (the memory-economy observatory,
        obs/kvlens.py) and print the miss-ratio curve — predicted
        block-hit ratio at 0.5x..8x of the configured KV pool — next
        to the measured ratio at the real capacity, the sampling
        stats, and the thrash bill (evict→refetch re-prefill
        chunk-seconds + migrated bytes). --json for the raw dict.

    python -m dnn_tpu.obs kvlens PATH
        Render a saved /kvz JSON dump (a `curl .../kvz > kvz.json`
        capture) with the same table — post-mortems read dumps, not
        live servers.

    python -m dnn_tpu.obs kvlens --selftest
        In-process smoke: hand-computed LRU stack-distance/MRC
        goldens (rate=1), SHARDS sampling determinism (same seed ⇒
        bit-identical curve), thrash-window arithmetic on an injected
        clock, gate-off-records-nothing, and the /kvz endpoint in both
        formats; exit 0 on success. Tier-1 wired
        (tests/test_obs_kvlens.py).

    python -m dnn_tpu.obs incident PATH [--json]
        Render an SLO-breach incident bundle (obs/slo.py — written
        automatically by the workload runner when a scenario's verdict
        is a breach): the verdict header, each failed objective, and
        the flight ring's event-by-event timeline over the breach
        window, plus the step-clock and fleet snapshots when captured.

    python -m dnn_tpu.obs timeline --url http://host:port
        Fetch a running server's /stepz and print the per-phase
        decode-step decomposition (admit/host/dispatch/wait/commit/obs
        with fractions, dispatch-slack, sync-tax, host fraction).
        --out steps.json additionally writes the last N steps as a
        Perfetto-loadable host track (?format=trace).

    python -m dnn_tpu.obs timeline PATH
        Analyze one device capture (a POST /profilez capture dir, or a
        *.trace.json[.gz] file) with obs/timeline.analyze: per-track
        busy fractions, device busy/idle, the host-gap histogram
        between consecutive device ops, top-K ops by device time, and
        — when the capture's sidecar meta.json is present — its
        position on the step axis. --json for the raw dict.

    python -m dnn_tpu.obs timeline --selftest
        In-process smoke: a deterministic StepClock (injected clock)
        plus a synthetic gzipped Perfetto trace, checked end to end;
        exit 0 on success. Tier-1 wired (tests/test_obs_timeline.py).

    python -m dnn_tpu.obs fleet --targets http://h1:9100,http://h2:9100
        One-shot fleet report: poll every stage's /metrics /statusz
        /trace.jsonl, print the merged rollup (worst-of health,
        per-stage percentiles, fleet throughput, clock offsets) and the
        newest request's critical-path/bubble attribution.
        --config config.json --metrics_port 9100  derives the targets
        from the pipeline config instead (every node's host + one
        shared metrics port). --out stitched.json additionally writes
        the stitched cross-host Perfetto trace (--id to pick a trace).

    python -m dnn_tpu.obs fleet --targets ... --serve PORT
        Long-lived collector: poll on --interval (default 5 s) and
        serve /fleetz (+ /metrics /statusz /healthz with the fleet's
        worst-of health) until interrupted.

    python -m dnn_tpu.obs fleet --selftest
        In-process smoke: two real stage HTTP endpoints with injected
        clock skew, poll, merged rollup, offset recovery, stitched
        trace, critical-path golden; exit 0 on success. Tier-1 wired
        (tests/test_obs_fleet.py).

    python -m dnn_tpu.obs trace --selftest
        In-process smoke of the whole span pipeline (nested spans,
        cross-thread explicit parents, wire-tag round-trip, JSONL and
        Chrome-trace export, Prometheus render) with schema validation;
        exit 0 on success. Wired into tier-1 (tests/test_obs.py).

    python -m dnn_tpu.obs trace --jsonl spans.jsonl --out chrome.json \
        [--id TRACE_ID]
        Convert a JSONL span dump (the /trace.jsonl endpoint's format,
        or TraceCollector.dump_jsonl) into Chrome-trace JSON for
        Perfetto / chrome://tracing.

    python -m dnn_tpu.obs flight --url http://host:port \
        [--out ring.jsonl] [--kind KIND] [--trace ID] [--last N]
        Fetch a running server's flight-recorder ring (GET /debugz,
        obs/flight.py) and print or save it as JSONL.

    python -m dnn_tpu.obs flight --selftest
        In-process smoke of the flight ring (record/overflow/filters/
        crash-dump schema); exit 0 on success.

No jax import anywhere on these paths — the tooling works on any host.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def _selftest() -> int:
    from dnn_tpu import obs

    obs.set_enabled(True)
    col = obs.TraceCollector(capacity=256)
    # route this selftest's spans into a private collector so a shared
    # process (the test suite) keeps its ring clean
    import dnn_tpu.obs.trace as _t

    saved = _t._collector
    _t._collector = col
    try:
        with obs.span("request", kind="selftest") as root:
            with obs.span("prefill", chunks=2):
                time.sleep(0.001)
            # cross-thread child via explicit parent (the batcher-worker
            # pattern)
            def worker():
                s = obs.start_span("decode", parent=root, bucket=64)
                time.sleep(0.001)
                s.end(tokens=3)

            t = threading.Thread(target=worker)
            t.start()
            t.join()
            # wire round-trip: tag -> parse -> remote child
            rid = obs.tag_request_id("gen:8", root)
            parsed = obs.parse_wire_tag(rid)
            assert parsed is not None and parsed[0] == root.trace_id, rid
            assert obs.strip_wire_tag(rid) == "gen:8", rid
            remote = obs.start_span("rpc.remote", trace_id=parsed[0],
                                    parent_id=parsed[1])
            remote.end()

        spans = col.spans(root.trace_id)
        names = {s.name for s in spans}
        assert names == {"request", "prefill", "decode", "rpc.remote"}, names
        by_name = {s.name: s for s in spans}
        for child in ("prefill", "decode", "rpc.remote"):
            assert by_name[child].parent_id == root.span_id, child
            assert by_name[child].trace_id == root.trace_id, child
        assert by_name["request"].parent_id is None

        # JSONL: one valid object per line, schema keys present
        lines = [json.loads(ln) for ln in
                 col.jsonl(root.trace_id).splitlines()]
        assert len(lines) == 4
        for d in lines:
            assert {"trace_id", "span_id", "parent_id", "name", "ts",
                    "dur", "tid", "attrs"} <= set(d), d
            assert d["dur"] >= 0.0

        # Chrome trace: X events with µs timestamps + thread metadata
        ct = col.chrome_trace(root.trace_id)
        xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        ms = [e for e in ct["traceEvents"] if e.get("ph") == "M"]
        assert len(xs) == 4 and ms, ct
        for e in xs:
            assert e["ts"] > 0 and e["dur"] >= 0
            assert e["args"]["trace_id"] == root.trace_id

        # Prometheus render smoke (the other export surface)
        from dnn_tpu.utils.metrics import Metrics, labeled, render_prometheus

        m = Metrics()
        m.inc(labeled("selftest_total", leg="trace"))
        m.observe("selftest_seconds", 0.001)
        text = render_prometheus(m)
        assert "# TYPE selftest_total counter" in text
        assert 'selftest_total{leg="trace"} 1' in text
    finally:
        _t._collector = saved
    print(f"obs selftest ok: {len(spans)} spans, 1 trace "
          f"({root.trace_id}), chrome+jsonl+prometheus schemas valid")
    return 0


def _convert(jsonl_path: str, out_path: str, trace_id=None) -> int:
    from dnn_tpu.obs.trace import spans_to_chrome

    dicts = []
    with open(jsonl_path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            d = json.loads(ln)
            if trace_id is None or d.get("trace_id") == trace_id:
                dicts.append(d)
    chrome = spans_to_chrome(dicts)
    with open(out_path, "w") as f:
        json.dump(chrome, f)
    n = sum(1 for e in chrome["traceEvents"] if e.get("ph") == "X")
    print(f"wrote {out_path}: {n} spans"
          + (f" (trace {trace_id})" if trace_id else ""))
    return 0


def _flight_selftest() -> int:
    from dnn_tpu import obs
    from dnn_tpu.obs.flight import FlightRecorder

    obs.set_enabled(True)
    fr = FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("probe", i=i)
    evs = fr.events()
    assert len(evs) == 4, evs  # bounded: newest 4 survive
    assert [e["i"] for e in evs] == [2, 3, 4, 5], evs
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    fr.record("deadline_miss", trace_id="cafe", rid=7)
    hit = fr.events(kind="deadline_miss")
    assert len(hit) == 1 and hit[0]["trace_id"] == "cafe"
    assert fr.events(trace_id="cafe") == hit
    assert len(fr.events(last=2)) == 2
    lines = [json.loads(ln) for ln in fr.jsonl().splitlines()]
    for d in lines:
        assert {"seq", "ts", "kind"} <= set(d), d
    win = fr.window(hit[0]["ts"], before_s=60, after_s=1)
    assert hit[0] in win and len(win) >= 2  # surrounding events ride along
    print(f"flight selftest ok: {len(lines)} events, overflow/filters/"
          "window/schema valid")
    return 0


def _flight_fetch(url: str, out=None, kind=None, trace=None,
                  last=None) -> int:
    from urllib.parse import urlencode
    from urllib.request import urlopen

    q = {k: v for k, v in
         (("kind", kind), ("trace", trace), ("last", last))
         if v is not None}
    full = url.rstrip("/") + "/debugz" + ("?" + urlencode(q) if q else "")
    body = urlopen(full, timeout=10).read().decode()
    if out:
        with open(out, "w") as f:
            f.write(body)
        print(f"wrote {out}: {len(body.splitlines())} events")
    else:
        sys.stdout.write(body)
    return 0


def _fleet_selftest() -> int:
    """Two REAL stage HTTP endpoints in-process (private registries +
    collectors, ±500 ms injected skew on the second), one FleetCollector
    over them: merged rollup, offset recovery, stitching, critical-path
    math, and the prom re-export all checked end to end."""
    import time as _time

    from dnn_tpu import obs
    from dnn_tpu.obs import trace as _t
    from dnn_tpu.obs.fleet import FleetCollector, critical_path
    from dnn_tpu.obs.http import MetricsHTTPServer
    from dnn_tpu.utils.metrics import Metrics

    obs.set_enabled(True)
    SKEW = 0.5
    regA, regB = Metrics(), Metrics()
    regA.set("serving.tokens_per_sec", 10.0)
    regB.set("serving.tokens_per_sec", 5.0)
    colA, colB = obs.TraceCollector(), obs.TraceCollector()

    def mk(col, trace_id, span_id, parent_id, name, ts, dur, **attrs):
        s = _t.Span(name, trace_id, span_id, parent_id, attrs)
        s.t0, s.dur, s._done = ts - _t._EPOCH0, dur, True
        col.add(s)

    now = _time.time()
    # client hop on A (true timeline), server span on B stamped by a
    # clock running SKEW ahead
    mk(colA, "t1", "c1", None, "rpc.forward", now, 0.10,
       cs=now, cr=now + 0.10)
    mk(colB, "t1", "s1", "c1", "stage.request", now + 0.02 + SKEW, 0.06,
       stage="node2")
    sA = MetricsHTTPServer(port=0, registry=regA, collector=colA,
                           healthy=lambda: True)
    sB = MetricsHTTPServer(
        port=0, registry=regB, collector=colB,
        status=lambda: {"state": "degraded", "components": {}})
    try:
        fc = FleetCollector({"node1": f"http://127.0.0.1:{sA.port}",
                             "node2": f"http://127.0.0.1:{sB.port}"})
        fc.poll_once()
        z = fc.fleetz()
        assert z["state"] == "degraded", z["state"]  # worst-of rollup
        assert z["fleet"]["tokens_per_sec"] == 15.0, z["fleet"]
        assert z["stages"]["node1"]["state"] == "ok"
        off = z["clock_offsets_s"]["node2"]
        assert abs(off - SKEW) < 0.1 * SKEW, off  # ±500 ms within 10%
        ct = fc.stitch("t1")
        xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 2, ct
        pids = {e["args"]["stage"]: e["pid"] for e in xs}
        assert len(set(pids.values())) == 2, pids  # one track per stage
        # after correction the server span sits INSIDE the client hop
        by_name = {e["name"]: e for e in xs}
        c, s = by_name["rpc.forward"], by_name["stage.request"]
        assert c["ts"] <= s["ts"] <= s["ts"] + s["dur"] \
            <= c["ts"] + c["dur"] + 1e3, (c, s)
        assert "dnn_tpu_fleet_state" in fc.render_prom()
        rep = fc.request_report("t1")
        assert rep["spans"] == 2 and 0.0 < rep["bubble_fraction"] < 1.0
        # critical-path golden: 3 sequential leaves under a 10 ms root
        # with a 1 ms gap -> bubble exactly 10%
        g = critical_path([
            {"span_id": "r", "parent_id": None, "name": "request",
             "ts": 0.0, "dur": 0.010, "attrs": {}},
            {"span_id": "a", "parent_id": "r", "name": "compute",
             "ts": 0.0, "dur": 0.003, "attrs": {"stage": "s0"}},
            {"span_id": "b", "parent_id": "r", "name": "compute",
             "ts": 0.004, "dur": 0.003, "attrs": {"stage": "s1"}},
            {"span_id": "c", "parent_id": "r", "name": "compute",
             "ts": 0.007, "dur": 0.003, "attrs": {"stage": "s2"}},
        ])
        assert abs(g["bubble_fraction"] - 0.1) < 1e-6, g
        assert [p["stage"] for p in g["path"]] == ["s0", "s1", "s2"], g
        fc.close()
    finally:
        sA.close()
        sB.close()
    print(f"fleet selftest ok: rollup worst-of, offset {off:+.3f}s "
          f"recovered (true {SKEW:+.3f}s), stitch + critical-path/"
          "bubble golden, prom re-export valid")
    return 0


def _timeline_selftest() -> int:
    """Deterministic StepClock (injected clock) + a synthetic gzipped
    Perfetto capture with a sidecar meta, checked end to end: phase
    arithmetic, derived series, chrome export, prom render, registry
    histograms, capture analysis, step alignment, garbage rejection."""
    import gzip
    import os
    import tempfile

    from dnn_tpu import obs
    from dnn_tpu.obs.timeline import StepClock, analyze
    from dnn_tpu.utils.metrics import Metrics

    obs.set_enabled(True)
    t = [100.0]
    reg = Metrics()
    clk = StepClock(capacity=8, registry=reg, now=lambda: t[0])
    for _i in range(3):
        t[0] += 0.0005  # one admit per iteration, 0.5 ms
        clk.note_admit(t[0] - 0.0005)
        rec = clk.begin()
        assert rec is not None
        for phase, dt in (("host", 0.001), ("dispatch", 0.002),
                          ("wait", 0.004), ("commit", 0.001),
                          ("obs", 0.001)):
            t[0] += dt
            clk.mark(rec, phase)
        clk.end(rec, n_adv=4)
        t[0] += 0.0005  # inter-step gap: genuinely unattributed
    s = clk.summary()
    assert s["window_steps"] == 3 and s["steps_total"] == 3, s
    # per step: wall 9 ms + 0.5 ms admit; host 3.5 ms, device 6 ms
    assert abs(s["host_fraction"] - 3.5 / 9.5) < 1e-3, s
    assert abs(s["dispatch_slack"] - 3.5 / 6.0) < 1e-3, s
    assert abs(s["sync_tax"] - 4.0 / 9.5) < 1e-3, s
    assert s["tokens"] == 12, s
    ct = clk.chrome_trace()
    xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 3 * 6, len(xs)  # 5 phases + 1 admit slice / step
    assert {e["name"] for e in xs} == {"admit", "host", "dispatch",
                                       "wait", "commit", "obs"}
    prom = clk.render_prom()
    assert "dnn_tpu_step_host_fraction" in prom, prom
    snap = reg.snapshot()
    assert 'step.phase_seconds{phase="wait"}' in snap["histogram"], snap

    # synthetic capture: one 6 ms device op per step's in-flight window
    d = tempfile.mkdtemp(prefix="tl-selftest")
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient"}},
    ]
    for i in range(3):
        t0_rel = (0.0005 + 0.010 * i + 0.001) * 1e6  # dispatch start
        events.append({"ph": "X", "pid": 7, "tid": 2, "name": "fusion.1",
                       "ts": t0_rel, "dur": 6000.0,
                       "args": {"hlo_op": "fusion.1",
                                "hlo_module": "jit_step"}})
    with gzip.open(os.path.join(d, "vm.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ns"}, f)
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump({"perf_begin": 100.0, "perf_end": 100.0305,
                   "step_begin": 0, "step_end": 3, "backend": "cpu"}, f)
    a = analyze(d, clock=clk)
    assert a["device"]["ops"] == 3, a["device"]
    assert abs(a["device"]["busy_s"] - 0.018) < 1e-6, a["device"]
    assert a["host_gaps"]["count"] == 2, a["host_gaps"]
    assert abs(a["host_gaps"]["p50_ms"] - 4.0) < 0.01, a["host_gaps"]
    assert a["top_ops"][0]["name"] == "fusion.1", a["top_ops"]
    st = a["steps"]
    assert st and st["aligned"] and st["n_steps"] == 3, st
    assert st["steps_in_capture"] == 3, st
    # each step: 6 ms device busy inside a 9.5 ms attributed wall
    assert abs(st["device_overlap_frac"] - 18.0 / 28.5) < 1e-3, st

    # garbage and truncated inputs fail loud, not half-parsed
    bad = os.path.join(d, "garbage.json")
    with open(bad, "w") as f:
        f.write("not a trace {{{")
    for p in (bad,):
        try:
            analyze(p)
            raise AssertionError("garbage input must raise ValueError")
        except ValueError:
            pass
    print("timeline selftest ok: 3 deterministic steps (host fraction "
          f"{s['host_fraction']:.2%}, slack {s['dispatch_slack']:.2f}, "
          f"sync tax {s['sync_tax']:.2%}), synthetic capture analyzed "
          f"(device busy {a['device']['busy_frac']:.1%}, 3 steps "
          "aligned), garbage rejected")
    return 0


def _timeline_url(url: str, out=None, last=None) -> int:
    from urllib.request import urlopen

    base = url.rstrip("/") + "/stepz"
    q = f"?last={last}" if last else ""
    s = json.loads(urlopen(base + q, timeout=10).read().decode())
    phases = s.get("phases", {})
    print(f"steps: {s.get('steps_total')} total, "
          f"{s.get('window_steps')} in window "
          f"({s.get('window_wall_s', 0) * 1e3:.1f} ms wall, "
          f"{s.get('tokens')} tokens)")
    for p, d in phases.items():
        print(f"  {p:<9} {d['frac']:7.1%}  {d['mean_ms']:9.3f} ms/step")
    print(f"host fraction {s.get('host_fraction', 0):.1%} | "
          f"dispatch slack {s.get('dispatch_slack', 0):.2f} | "
          f"sync tax {s.get('sync_tax', 0):.1%} | "
          f"{s.get('steps_per_sec', 0):.1f} steps/s | last step "
          f"{s.get('last_wall_ms', 0):.2f} ms")
    if out:
        trace = urlopen(base + "?format=trace"
                        + (f"&last={last}" if last else ""),
                        timeout=10).read().decode()
        with open(out, "w") as f:
            f.write(trace)
        n = sum(1 for e in json.loads(trace)["traceEvents"]
                if e.get("ph") == "X")
        print(f"wrote {out}: {n} phase slices (load in Perfetto)")
    return 0


def _timeline_path(path: str, as_json: bool, top: int) -> int:
    from dnn_tpu.obs.timeline import analyze, render_report

    a = analyze(path, top_k=top)
    if as_json:
        print(json.dumps(a, indent=2))
    else:
        print(render_report(a))
    return 0


def _kvlens_selftest() -> int:
    """Deterministic KVLens end to end: MRC goldens at rate=1 (every
    access sampled — stack distances are exact), sampling determinism,
    thrash-window arithmetic on an injected clock, the gate, and the
    /kvz endpoint in both formats."""
    from types import SimpleNamespace
    from urllib.request import urlopen

    import numpy as np

    from dnn_tpu import obs
    from dnn_tpu.obs.kvlens import KVLens

    obs.set_enabled(True)
    # -- MRC golden: pool=4, caps (2,4,8,16,32); trace A B C A --------
    bp = 4
    A = np.arange(0, bp)
    B = np.arange(100, 100 + bp)
    C = np.arange(200, 200 + bp)
    lens = KVLens(4, bp, seed=0, rate=1.0, now=lambda: 0.0)
    for p in (A, B, C, A):
        lens.on_access(p)
    # the re-accessed A sits at stack distance 2 (B, C more recent):
    # a hit at every capacity > 2, a miss at the 0.5x (=2) pool
    got = [c["predicted_hit_ratio"] for c in lens.curve()]
    assert got == [0.0, 0.25, 0.25, 0.25, 0.25], got
    assert lens.sampled == 4 and lens.sampled_cold == 3, (
        lens.sampled, lens.sampled_cold)

    # -- sampling determinism: same seed ⇒ bit-identical curve --------
    def run(seed):
        ln = KVLens(8, bp, seed=seed, rate=0.3, now=lambda: 0.0)
        for i in range(200):
            ln.on_access(np.arange((i % 17) * bp, (i % 17) * bp + bp))
        return ln

    l1, l2 = run(7), run(7)
    assert l1.curve() == l2.curve() and l1.sampled == l2.sampled
    assert 0 < l1.sampled < l1.accesses  # the rate really subsamples

    # -- thrash-window arithmetic (injected clock) --------------------
    t = [0.0]
    lens = KVLens(4, bp, seed=0, rate=1.0, thrash_window_s=10.0,
                  bytes_per_block=64, now=lambda: t[0])
    lens.note_prefill(2, 1.0)   # EMA seeds at 0.5 s/chunk
    node = SimpleNamespace(depth=1, obskey=None)
    lens.on_insert(A, [node])
    assert node.obskey is not None
    lens.on_evict([node.obskey], cause="capacity")
    t[0] = 5.0                  # inside the window: a refetch
    lens.on_insert(A, [SimpleNamespace(depth=1, obskey=None)])
    assert lens.refetch_blocks == 1, lens.refetch_blocks
    assert abs(lens.thrash_chunk_seconds - 0.5) < 1e-9
    nb = SimpleNamespace(depth=1, obskey=None)
    lens.on_insert(B, [nb])
    lens.on_evict([nb.obskey], cause="capacity")
    t[0] = 16.0                 # past the window: churn, not thrash
    lens.on_insert(B, [SimpleNamespace(depth=1, obskey=None)])
    assert lens.refetch_blocks == 1, lens.refetch_blocks
    # an ADOPTED refetch bills the wire too
    na = SimpleNamespace(depth=1, obskey=None)
    lens.on_insert(C, [na], origin="adopted")
    lens.on_evict([na.obskey], cause="capacity")
    t[0] = 17.0
    lens.on_insert(C, [SimpleNamespace(depth=1, obskey=None)],
                   origin="adopted")
    assert lens.refetch_blocks == 2
    assert lens.thrash_migrated_bytes == 64
    kinds = [e["kind"] for e in lens.ledger.events()]
    assert kinds.count("refetch") == 2 and "evict" in kinds, kinds

    # -- gate off records NOTHING -------------------------------------
    obs.set_enabled(False)
    try:
        off = KVLens(4, bp, seed=0, rate=1.0)
        off.on_access(A)
        off.on_insert(A, [SimpleNamespace(depth=1, obskey=None)])
        off.on_evict([b"x" * 16])
        off.on_share(3)
        off.note_prefill(1, 1.0)
        assert off.accesses == 0 and off.births == 0
        assert off.shares == 0 and len(off.ledger) == 0
    finally:
        obs.set_enabled(True)

    # -- /kvz endpoint, both formats ----------------------------------
    srv = obs.serve_metrics(0, kvlens=lens)
    try:
        base = f"http://127.0.0.1:{srv.port}/kvz"
        z = json.loads(urlopen(base, timeout=10).read().decode())
        assert [c["mult"] for c in z["curve"]] == \
            ["0.5x", "1x", "2x", "4x", "8x"], z["curve"]
        assert z["thrash"]["refetch_blocks"] == 2, z["thrash"]
        prom = urlopen(base + "?format=prom",
                       timeout=10).read().decode()
        assert 'dnn_tpu_kvlens_pred_hit_ratio{mult="2x"}' in prom
        assert "dnn_tpu_kvlens_thrash_chunk_seconds_total" in prom
    finally:
        srv.close()
    print("kvlens selftest ok: MRC golden [0, .25, .25, .25, .25] at "
          f"caps (2..32), determinism ({l1.sampled}/{l1.accesses} "
          "sampled twice, bit-identical), thrash 2 refetches = "
          f"{lens.thrash_chunk_seconds:.1f} chunk-s + 64 B wire, gate "
          "off silent, /kvz json+prom served")
    return 0


def _kvlens_render(z: dict) -> None:
    cfg = z.get("config", {})
    smp = z.get("samples", {})
    meas = z.get("measured", {})
    print(f"pool {cfg.get('pool_blocks')} blocks x block_len "
          f"{cfg.get('block_len')} | sampling rate {cfg.get('rate')} "
          f"seed {cfg.get('seed')} | {smp.get('sampled')}/"
          f"{smp.get('accesses')} accesses sampled "
          f"({smp.get('cold')} cold)")
    print(f"{'capacity':>10} {'mult':>6} {'predicted hit':>14}")
    for c in z.get("curve", []):
        v = c.get("predicted_hit_ratio")
        print(f"{c.get('capacity_blocks'):>10} {c.get('mult'):>6} "
              + (f"{v:>13.1%}" if v is not None else f"{'—':>13}"))
    mr = meas.get("hit_ratio")
    print(f"measured at 1x: "
          + (f"{mr:.1%}" if mr is not None else "—")
          + f" ({meas.get('hits')}/{meas.get('accesses')} blocks)")
    th = z.get("thrash", {})
    print(f"thrash: {th.get('refetch_blocks')} refetches inside "
          f"{th.get('window_s')}s = {th.get('chunk_seconds')} "
          f"re-prefill chunk-s + {th.get('migrated_bytes')} B "
          "re-migrated")
    lc = z.get("lifecycle", {})
    print(f"lifecycle: {lc.get('births')} births, {lc.get('shares')} "
          f"shares ({lc.get('cows')} COW), {lc.get('migrations')} "
          f"migrated blocks, evictions {lc.get('evictions_by_cause')}")


def _kvlens_url(url: str, as_json: bool) -> int:
    from urllib.request import urlopen

    z = json.loads(urlopen(url.rstrip("/") + "/kvz",
                           timeout=10).read().decode())
    if as_json:
        print(json.dumps(z, indent=2, default=str))
    else:
        _kvlens_render(z)
    return 0


def _kvlens_path(path: str, as_json: bool) -> int:
    with open(path) as f:
        z = json.load(f)
    if as_json:
        print(json.dumps(z, indent=2, default=str))
    else:
        _kvlens_render(z)
    return 0


def _caplens_selftest() -> int:
    """Deterministic CapLens end to end: planner replay goldens on an
    injected clock (hand-computed shed/availability at 1 and 2
    replicas), bit-identical replay, demand-window arithmetic,
    cold-start bucket attribution, the audit trail, the gate, and the
    /capz endpoint in both formats."""
    from urllib.request import urlopen

    from dnn_tpu import obs
    from dnn_tpu.obs.caplens import CapLens, CapSLO

    obs.set_enabled(True)
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    def build(seed=0):
        lens = CapLens(slots_per_replica=1, max_inflight=1,
                       deadline_s=2.0, seed=seed, window_s=60.0,
                       slo=CapSLO(availability=0.9), now=clock)
        # 20 arrivals 0.25 s apart; 10 committed forwards of exactly
        # 0.5 s on a free slot — the learned service CDF is a spike
        for i in range(20):
            t[0] = i * 0.25
            lens.on_arrival(8, scenario="gen")
        for i in range(10):
            t[0] = 5.0 + i * 0.1
            lens.on_commit("r0", role="both", tokens=24, wall_s=0.5,
                           inflight_at_dispatch=0)
        return lens

    lens = build()
    # -- planner golden, 1 replica: service 0.5 s, arrivals 0.25 s
    # apart, in-system bound 1 => exactly every other arrival sheds
    p1 = lens.plan(1)
    assert p1["availability"] == 0.5 and p1["shed_frac"] == 0.5, p1
    assert p1["ttft_p95_s"] == 0.5 and p1["wait_p95_s"] == 0.0, p1
    # -- 2 warm replicas: alternate servers, no queue, no shed
    p2 = lens.plan(2, warm=2)
    assert p2["availability"] == 1.0 and p2["shed_frac"] == 0.0, p2
    # -- replay determinism: same ring + reservoir => bit-identical
    assert lens.plan(1) == p1 and build().plan(1) == p1
    # -- cold replica priced: default cold delay exceeds the trace
    # span, so plan(2, warm=1) cannot reach the warm-pair verdict
    p2c = lens.plan(2, warm=1)
    assert p2c["cold"] == 1 and p2c["coldstart_debt_s"] > 0.0
    assert p2c["availability"] < p2["availability"], (p2c, p2)
    # -- wanted: 1 replica misses the 0.9 SLO, 2 warm meet it; the
    # transition lands in the audit trail with its decision inputs
    t[0] = 6.0
    w = lens.wanted_replicas(n_live=2)
    assert w == 2, w
    audit = list(lens._audit)
    assert audit and audit[-1]["to"] == 2 \
        and audit[-1]["plans"][0]["meets_slo"] is False, audit
    # -- demand-window arithmetic: 20 arrivals in 60 s, steady trace
    d = lens.demand()
    assert d["arrivals"] == 20 and abs(
        d["rate_hz"] - 20 / 60.0) < 1e-3, d
    assert d["change_point"] is False and d["peak_to_mean"] is not None
    assert d["scenarios"]["gen"]["count"] == 20, d["scenarios"]
    # -- queued commits stay OUT of the planning reservoir
    t[0] = 7.0
    lens.on_commit("r0", role="both", tokens=24, wall_s=3.0,
                   inflight_at_dispatch=5)
    assert lens._queued_commits == 1 and lens.plan(1) == p1
    # -- cold-start bucket attribution (child-measured signals)
    cl = CapLens(now=clock, settle_s=1.0, signals=lambda name: {
        "boot_imports_s": 3.0, "boot_weight_load_s": 1.0,
        "compile_seconds_total": 2.5, "boot_compile_preready_s": 0.5,
        "boot_ready_total_s": 4.5})
    t[0] = 0.0
    cl.spawn_begin("r0", "both")
    t[0] = 5.0
    cl.spawn_ready("r0")
    t[0] = 10.0
    cl.on_commit("r0", tokens=24, wall_s=2.4, inflight_at_dispatch=0)
    t[0] = 12.0
    cs = cl.coldstart()
    e = cs["entries"][0]
    # total 10; ready_total 4.5; post-ready compile 2.0; warmup =
    # 10 - 4.5 - 2.0 = 3.5; coverage (3+1+2.5+3.5)/10 = 1.0
    assert e["total_s"] == 10.0 and e["buckets"]["warmup_s"] == 3.5, e
    assert e["coverage"] == 1.0 and cs["finalized"] == 1, cs
    assert any(ev["kind"] == "coldstart"
               for ev in cl.ledger.events()), cl.ledger.events()
    # -- gate off records NOTHING
    obs.set_enabled(False)
    try:
        off = CapLens(now=clock)
        off.on_arrival(8)
        off.on_shed("saturated")
        off.on_commit("r0", tokens=4, wall_s=0.1)
        off.spawn_begin("r0")
        assert off.arrivals_total == 0 and off.commits_total == 0
        assert not off._pending and len(off.ledger) == 0
    finally:
        obs.set_enabled(True)
    # -- /capz endpoint, both formats ---------------------------------
    srv = obs.serve_metrics(0, caplens=lens)
    try:
        base = f"http://127.0.0.1:{srv.port}/capz"
        z = json.loads(urlopen(base, timeout=10).read().decode())
        assert z["demand"]["arrivals_total"] == 20, z["demand"]
        assert z["wanted_replicas"] == 2, z["wanted_replicas"]
        assert any(p["n"] == 1 for p in z["plans"]), z["plans"]
        prom = urlopen(base + "?format=prom",
                       timeout=10).read().decode()
        assert "dnn_tpu_caplens_arrival_rate_hz" in prom
        assert 'dnn_tpu_caplens_plan_availability{n="2"}' in prom
    finally:
        srv.close()
    print("caplens selftest ok: planner goldens (1 replica 0.50 avail "
          "shed-bound, 2 warm 1.00, bit-identical replay, cold debt "
          "priced), wanted 1->2 audited, demand window 0.333 Hz, "
          "cold-start buckets 3.0/1.0/2.5/3.5 cover 100%, gate off "
          "silent, /capz json+prom served")
    return 0


def _caplens_render(z: dict) -> None:
    cfg = z.get("config", {})
    d = z.get("demand", {})
    print(f"slots/replica {cfg.get('slots_per_replica')} x inflight "
          f"bound {cfg.get('max_inflight_per_replica')} | deadline "
          f"{cfg.get('deadline_s')}s | slo {cfg.get('slo')}")
    print(f"demand: {d.get('rate_hz')} Hz over {d.get('window_s')}s "
          f"({d.get('arrivals')} arrivals; total "
          f"{d.get('arrivals_total')}) | dispersion "
          f"{d.get('index_of_dispersion')} peak/mean "
          f"{d.get('peak_to_mean')} change_point "
          f"{d.get('change_point')}")
    print(f"tokens/s: prefill-in {d.get('prefill_tokens_per_s')} "
          f"committed {d.get('committed_tokens_per_s')} | scenarios "
          f"{d.get('scenarios')}")
    cap = z.get("capacity", {})
    print(f"capacity: service {cap.get('service_by_role')} | "
          f"tokens/s by replica {cap.get('tokens_per_s_by_replica')} "
          f"| cold-start price {cap.get('coldstart_delay_s')}s")
    cs = z.get("coldstart", {})
    print(f"cold-start: {cs.get('finalized')}/{cs.get('spawns')} "
          f"spawns finalized, p50 {cs.get('total_p50_s')}s, buckets "
          f"p50 {cs.get('buckets_p50_s')}, coverage "
          f"{cs.get('coverage_mean')}")
    plans = z.get("plans") or []
    if plans:
        print(f"{'n':>3} {'avail':>7} {'shed':>7} {'wait_p95':>9} "
              f"{'ttft_p95':>9} {'cold_debt':>10}")
        for p in plans:
            print(f"{p['n']:>3} {p['availability']:>7.3f} "
                  f"{p['shed_frac']:>7.3f} {p['wait_p95_s']:>8.3f}s "
                  f"{p['ttft_p95_s']:>8.3f}s "
                  f"{p['coldstart_debt_s']:>9.3f}s")
    print(f"wanted_replicas: {z.get('wanted_replicas')} "
          f"({len(z.get('audit') or [])} audited transitions shown)")


def _caplens_url(url: str, as_json: bool) -> int:
    from urllib.request import urlopen

    z = json.loads(urlopen(url.rstrip("/") + "/capz",
                           timeout=10).read().decode())
    if as_json:
        print(json.dumps(z, indent=2, default=str))
    else:
        _caplens_render(z)
    return 0


def _caplens_path(path: str, as_json: bool) -> int:
    with open(path) as f:
        z = json.load(f)
    if as_json:
        print(json.dumps(z, indent=2, default=str))
    else:
        _caplens_render(z)
    return 0


def _trainlens_selftest() -> int:
    """Deterministic trainlens end to end: hand-computed phase/stall/
    MFU goldens on an injected clock, checkpoint staleness arithmetic,
    the sentinel's NaN latch, gate-off-records-nothing, and the /trainz
    endpoint in both formats."""
    from urllib.request import urlopen

    from dnn_tpu import obs
    from dnn_tpu.obs.trainlens import GradSentinel, TrainClock
    from dnn_tpu.utils.metrics import Metrics

    obs.set_enabled(True)
    t = [100.0]
    reg = Metrics()
    clk = TrainClock(capacity=8, registry=reg, flops_per_step=1e6,
                     tokens_per_step=64, peak_flops=1e9,
                     now=lambda: t[0])
    # 4 steps: data 10 ms, dispatch 2 ms, wait 30 ms, 2 ms tail -> obs
    for _i in range(4):
        rec = clk.begin()
        assert rec is not None
        for phase, dt in (("data", 0.010), ("dispatch", 0.002),
                          ("wait", 0.030)):
            t[0] += dt
            clk.mark(rec, phase)
        t[0] += 0.002
        clk.end(rec)
    s = clk.summary()
    assert s["window_steps"] == 4 and s["steps_total"] == 4, s
    # per step: wall 44 ms, data 10 ms -> stall fraction 10/44
    assert abs(s["data_stall_fraction"] - 10.0 / 44.0) < 1e-3, s
    assert abs(s["window_wall_s"] - 4 * 0.044) < 1e-9, s
    assert s["tokens"] == 4 * 64, s
    # rate window: 4 steps over the 176 ms the ring spans
    sps = 4 / 0.176
    assert abs(s["steps_per_sec"] - sps) < 0.1, s
    # MFU golden: flops_per_step x steps/s / peak, hand-computed
    assert s["mfu"] is not None
    assert abs(s["mfu"] - 1e6 * sps / 1e9) < 1e-4, s["mfu"]
    # checkpoint freshness: a save at now, read 7 s later
    clk.ckpt_saved(4, 0.01, 12345)
    t[0] += 7.0
    assert abs(clk.ckpt_staleness_s() - 7.0) < 1e-9
    s = clk.summary()
    assert s["ckpt"]["last_good_step"] == 4, s["ckpt"]
    ct = clk.chrome_trace()
    xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 4 * 3, len(xs)  # 3 marked slices per step
    prom = clk.render_prom()
    assert "dnn_tpu_train_mfu" in prom, prom
    assert 'dnn_tpu_train_phase_frac{phase="data"}' in prom, prom
    snap = reg.snapshot()
    assert 'train.phase_seconds{phase="wait"}' in snap["histogram"], snap

    # sentinel: NaN latches ONCE per episode, recovers, re-fires
    sen = GradSentinel(warmup=1, spike_factor=4.0)
    assert sen.observe(1, 1.0, [1.0, 0.01, 0]) == []
    assert sen.observe(2, float("nan"), [1.0, 0.01, 0]) == ["loss_nan"]
    assert sen.observe(3, float("nan"), [1.0, 0.01, 0]) == []  # latched
    assert sen.observe(4, 0.9, [1.0, 0.01, 0]) == []           # recovers
    assert sen.observe(5, 1.0, [99.0, 0.01, 0]) == ["grad_spike"]

    # gate off records NOTHING
    obs.set_enabled(False)
    try:
        assert clk.begin() is None
        assert sen.observe(6, float("nan")) == []
    finally:
        obs.set_enabled(True)

    # /trainz endpoint, both formats
    srv = obs.serve_metrics(0, trainlens=clk)
    try:
        base = f"http://127.0.0.1:{srv.port}/trainz"
        z = json.loads(urlopen(base, timeout=10).read().decode())
        assert z["steps_total"] == 4, z
        assert set(z["phases"]) == {"data", "dispatch", "wait", "ckpt",
                                    "eval", "obs"}, z["phases"]
        ptext = urlopen(base + "?format=prom",
                        timeout=10).read().decode()
        assert "dnn_tpu_train_data_stall" in ptext
        assert "dnn_tpu_ckpt_staleness_seconds" in ptext
    finally:
        srv.close()
    print("trainlens selftest ok: 4 deterministic steps (data stall "
          f"{10 / 44:.1%}, mfu {1e6 * sps / 1e9:.2%} hand-checked), "
          "ckpt staleness 7.0s, sentinel nan-latch + spike, gate off "
          "silent, /trainz json+prom served")
    return 0


def _trainlens_render(z: dict) -> None:
    print(f"steps: {z.get('steps_total')} total, "
          f"{z.get('window_steps')} in window "
          f"({z.get('window_wall_s', 0) * 1e3:.1f} ms wall, "
          f"{z.get('tokens')} tokens)")
    for p, d in z.get("phases", {}).items():
        print(f"  {p:<9} {d['frac']:7.1%}  {d['mean_ms']:9.3f} ms/step")
    mfu = z.get("mfu")
    print(f"data stall {z.get('data_stall_fraction', 0):.1%} | "
          + (f"mfu {mfu:.2%} | " if mfu is not None
             else "mfu - (no roofline) | ")
          + f"{z.get('steps_per_sec', 0):.2f} steps/s | "
          f"{z.get('tokens_per_sec', 0):.0f} tokens/s | last step "
          f"{z.get('last_wall_ms', 0):.2f} ms")
    ck = z.get("ckpt", {})
    print(f"ckpt: last good step {ck.get('last_good_step')}, "
          f"staleness {ck.get('staleness_s')}s")


def _trainlens_url(url: str, as_json: bool, last=None) -> int:
    from urllib.request import urlopen

    base = url.rstrip("/") + "/trainz"
    q = f"?last={last}" if last else ""
    z = json.loads(urlopen(base + q, timeout=10).read().decode())
    if as_json:
        print(json.dumps(z, indent=2, default=str))
    else:
        _trainlens_render(z)
    return 0


def _trainlens_path(path: str, as_json: bool) -> int:
    with open(path) as f:
        z = json.load(f)
    if as_json:
        print(json.dumps(z, indent=2, default=str))
    else:
        _trainlens_render(z)
    return 0


def _fleet_cmd(args) -> int:
    from dnn_tpu.obs.fleet import FleetCollector, targets_from_config

    if args.targets:
        urls = [u.strip() for u in args.targets.split(",") if u.strip()]
        if args.names:
            names = [n.strip() for n in args.names.split(",")]
            if len(names) != len(urls):
                print("--names must match --targets in count",
                      file=sys.stderr)
                return 2
            targets = dict(zip(names, urls))
        else:
            targets = {f"stage{i}" if len(urls) > 1 else "stage0": u
                       for i, u in enumerate(urls)}
    elif args.config:
        if args.metrics_port is None:
            print("--config needs --metrics_port (the port every node "
                  "passed to --metrics_port)", file=sys.stderr)
            return 2
        targets = targets_from_config(args.config, args.metrics_port)
    else:
        print("fleet needs --targets, --config, or --selftest",
              file=sys.stderr)
        return 2
    fc = FleetCollector(targets, interval_s=args.interval)
    if args.serve is not None:
        from dnn_tpu import obs

        fc.start()
        srv = obs.serve_metrics(args.serve, host=args.host, fleet=fc)
        print(f"fleet collector serving http://{args.host}:{srv.port}"
              f"/fleetz over {len(targets)} stages "
              f"(poll every {args.interval:g}s); Ctrl-C to stop")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            srv.close()
            fc.close()
        return 0
    fc.poll_once()
    print(fc.report(args.trace_id))
    if args.out:
        chrome = fc.stitch(args.trace_id)
        with open(args.out, "w") as f:
            json.dump(chrome, f)
        n = sum(1 for e in chrome["traceEvents"] if e.get("ph") == "X")
        print(f"wrote {args.out}: {n} spans across "
              f"{len(targets)} stages (load in Perfetto)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m dnn_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("trace", help="trace export tooling")
    tr.add_argument("--selftest", action="store_true",
                    help="in-process span-pipeline smoke; exit 0 on pass")
    tr.add_argument("--jsonl", help="input JSONL span dump to convert")
    tr.add_argument("--out", help="output Chrome-trace JSON path")
    tr.add_argument("--id", dest="trace_id", default=None,
                    help="restrict conversion to one trace id")
    fl = sub.add_parser("flight", help="flight-recorder tooling")
    fl.add_argument("--selftest", action="store_true",
                    help="in-process flight-ring smoke; exit 0 on pass")
    fl.add_argument("--url", help="obs endpoint base URL to fetch "
                                  "/debugz from (http://host:port)")
    fl.add_argument("--out", help="write the JSONL here instead of stdout")
    fl.add_argument("--kind", default=None, help="filter by event kind")
    fl.add_argument("--trace", default=None, help="filter by trace id")
    fl.add_argument("--last", default=None, type=int,
                    help="keep only the newest N events")
    fz = sub.add_parser("fleet", help="cluster-wide aggregation + "
                        "cross-host trace stitching (obs/fleet.py)")
    fz.add_argument("--selftest", action="store_true",
                    help="in-process fleet smoke (two endpoints, "
                         "injected skew); exit 0 on pass")
    fz.add_argument("--targets", default=None,
                    help="comma-separated obs endpoint base URLs "
                         "(http://host:port), one per stage")
    fz.add_argument("--names", default=None,
                    help="comma-separated stage names matching --targets")
    fz.add_argument("--config", default=None,
                    help="pipeline config JSON — stages derive from its "
                         "nodes' hosts + --metrics_port")
    fz.add_argument("--metrics_port", type=int, default=None,
                    help="with --config: the obs port every node serves")
    fz.add_argument("--interval", type=float, default=5.0,
                    help="--serve poll period in seconds")
    fz.add_argument("--serve", type=int, default=None, metavar="PORT",
                    help="run the long-lived collector and serve "
                         "/fleetz on this port (0 = ephemeral)")
    fz.add_argument("--host", default="127.0.0.1",
                    help="--serve bind host (default loopback; "
                         "0.0.0.0 exposes to the network)")
    fz.add_argument("--out", default=None,
                    help="write the stitched cross-host Perfetto JSON "
                         "here (one-shot mode)")
    fz.add_argument("--id", dest="trace_id", default=None,
                    help="restrict the report/stitch to one trace id")
    inc = sub.add_parser("incident", help="render an SLO-breach "
                         "incident bundle (obs/slo.py) as an event-by-"
                         "event timeline")
    inc.add_argument("path", help="bundle directory (manifest.json + "
                                  "flight.jsonl [+ stepz/fleetz.json])")
    inc.add_argument("--json", action="store_true",
                     help="print the raw loaded bundle instead of the "
                          "rendered timeline")
    tl = sub.add_parser("timeline", help="step-timeline attribution: "
                        "/stepz fetch + device-capture analysis "
                        "(obs/timeline.py)")
    tl.add_argument("path", nargs="?", default=None,
                    help="capture dir (POST /profilez result) or "
                         "*.trace.json[.gz] file to analyze")
    tl.add_argument("--selftest", action="store_true",
                    help="in-process smoke (deterministic clock + "
                         "synthetic capture); exit 0 on pass")
    tl.add_argument("--url", default=None,
                    help="obs endpoint base URL to fetch /stepz from")
    tl.add_argument("--out", default=None,
                    help="with --url: write the step host track "
                         "(?format=trace Perfetto JSON) here")
    tl.add_argument("--last", type=int, default=None,
                    help="bound the /stepz window to the newest N steps")
    tl.add_argument("--json", action="store_true",
                    help="print the raw analysis dict instead of the "
                         "report")
    tl.add_argument("--top", type=int, default=10,
                    help="top-K device ops to report (default 10)")
    kv = sub.add_parser("kvlens", help="memory-economy observatory: "
                        "/kvz fetch — miss-ratio curve, thrash bill, "
                        "block forensics (obs/kvlens.py)")
    kv.add_argument("path", nargs="?", default=None,
                    help="saved /kvz JSON dump to render")
    kv.add_argument("--selftest", action="store_true",
                    help="in-process smoke (MRC goldens, sampling "
                         "determinism, thrash arithmetic, /kvz); "
                         "exit 0 on pass")
    kv.add_argument("--url", default=None,
                    help="obs endpoint base URL to fetch /kvz from")
    kv.add_argument("--json", action="store_true",
                    help="print the raw /kvz dict instead of the table")
    tn = sub.add_parser("trainlens", help="training-step observatory: "
                        "/trainz fetch — phase decomposition, MFU, "
                        "data-stall, ckpt freshness (obs/trainlens.py)")
    tn.add_argument("path", nargs="?", default=None,
                    help="saved /trainz JSON dump to render")
    tn.add_argument("--selftest", action="store_true",
                    help="in-process smoke (phase/stall/MFU goldens, "
                         "sentinel latch, /trainz); exit 0 on pass")
    tn.add_argument("--url", default=None,
                    help="obs endpoint base URL to fetch /trainz from")
    tn.add_argument("--json", action="store_true",
                    help="print the raw /trainz dict instead of the "
                         "table")
    tn.add_argument("--last", type=int, default=None,
                    help="bound the /trainz window to the newest N "
                         "steps")
    cp = sub.add_parser("caplens", help="capacity observatory: /capz "
                        "fetch — demand window, cold-start ledger, "
                        "what-if replica plans (obs/caplens.py)")
    cp.add_argument("path", nargs="?", default=None,
                    help="saved /capz JSON dump to render")
    cp.add_argument("--selftest", action="store_true",
                    help="in-process smoke (planner goldens, replay "
                         "determinism, cold-start buckets, /capz); "
                         "exit 0 on pass")
    cp.add_argument("--url", default=None,
                    help="obs endpoint base URL to fetch /capz from")
    cp.add_argument("--json", action="store_true",
                    help="print the raw /capz dict instead of the "
                         "table")
    args = ap.parse_args(argv)

    if args.cmd == "trace":
        if args.selftest:
            return _selftest()
        if args.jsonl and args.out:
            return _convert(args.jsonl, args.out, args.trace_id)
        ap.error("trace needs --selftest or --jsonl FILE --out FILE")
    if args.cmd == "flight":
        if args.selftest:
            return _flight_selftest()
        if args.url:
            return _flight_fetch(args.url, args.out, args.kind,
                                 args.trace, args.last)
        ap.error("flight needs --selftest or --url URL")
    if args.cmd == "fleet":
        if args.selftest:
            return _fleet_selftest()
        return _fleet_cmd(args)
    if args.cmd == "incident":
        from dnn_tpu.obs.slo import load_incident, render_incident

        bundle = load_incident(args.path)
        if args.json:
            print(json.dumps(bundle, indent=2, default=str))
        else:
            print(render_incident(bundle))
        return 0
    if args.cmd == "timeline":
        if args.selftest:
            return _timeline_selftest()
        if args.url:
            return _timeline_url(args.url, args.out, args.last)
        if args.path:
            return _timeline_path(args.path, args.json, args.top)
        ap.error("timeline needs --selftest, --url URL, or a capture "
                 "PATH")
    if args.cmd == "kvlens":
        if args.selftest:
            return _kvlens_selftest()
        if args.url:
            return _kvlens_url(args.url, args.json)
        if args.path:
            return _kvlens_path(args.path, args.json)
        ap.error("kvlens needs --selftest, --url URL, or a saved /kvz "
                 "JSON PATH")
    if args.cmd == "trainlens":
        if args.selftest:
            return _trainlens_selftest()
        if args.url:
            return _trainlens_url(args.url, args.json, args.last)
        if args.path:
            return _trainlens_path(args.path, args.json)
        ap.error("trainlens needs --selftest, --url URL, or a saved "
                 "/trainz JSON PATH")
    if args.cmd == "caplens":
        if args.selftest:
            return _caplens_selftest()
        if args.url:
            return _caplens_url(args.url, args.json)
        if args.path:
            return _caplens_path(args.path, args.json)
        ap.error("caplens needs --selftest, --url URL, or a saved "
                 "/capz JSON PATH")
    return 2


if __name__ == "__main__":
    sys.exit(main())
