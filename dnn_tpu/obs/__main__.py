"""CLI: `python -m dnn_tpu.obs {trace,flight} ...` — obs tooling.

    python -m dnn_tpu.obs trace --selftest
        In-process smoke of the whole span pipeline (nested spans,
        cross-thread explicit parents, wire-tag round-trip, JSONL and
        Chrome-trace export, Prometheus render) with schema validation;
        exit 0 on success. Wired into tier-1 (tests/test_obs.py).

    python -m dnn_tpu.obs trace --jsonl spans.jsonl --out chrome.json \
        [--id TRACE_ID]
        Convert a JSONL span dump (the /trace.jsonl endpoint's format,
        or TraceCollector.dump_jsonl) into Chrome-trace JSON for
        Perfetto / chrome://tracing.

    python -m dnn_tpu.obs flight --url http://host:port \
        [--out ring.jsonl] [--kind KIND] [--trace ID] [--last N]
        Fetch a running server's flight-recorder ring (GET /debugz,
        obs/flight.py) and print or save it as JSONL.

    python -m dnn_tpu.obs flight --selftest
        In-process smoke of the flight ring (record/overflow/filters/
        crash-dump schema); exit 0 on success.

No jax import anywhere on these paths — the tooling works on any host.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def _selftest() -> int:
    from dnn_tpu import obs

    obs.set_enabled(True)
    col = obs.TraceCollector(capacity=256)
    # route this selftest's spans into a private collector so a shared
    # process (the test suite) keeps its ring clean
    import dnn_tpu.obs.trace as _t

    saved = _t._collector
    _t._collector = col
    try:
        with obs.span("request", kind="selftest") as root:
            with obs.span("prefill", chunks=2):
                time.sleep(0.001)
            # cross-thread child via explicit parent (the batcher-worker
            # pattern)
            def worker():
                s = obs.start_span("decode", parent=root, bucket=64)
                time.sleep(0.001)
                s.end(tokens=3)

            t = threading.Thread(target=worker)
            t.start()
            t.join()
            # wire round-trip: tag -> parse -> remote child
            rid = obs.tag_request_id("gen:8", root)
            parsed = obs.parse_wire_tag(rid)
            assert parsed is not None and parsed[0] == root.trace_id, rid
            assert obs.strip_wire_tag(rid) == "gen:8", rid
            remote = obs.start_span("rpc.remote", trace_id=parsed[0],
                                    parent_id=parsed[1])
            remote.end()

        spans = col.spans(root.trace_id)
        names = {s.name for s in spans}
        assert names == {"request", "prefill", "decode", "rpc.remote"}, names
        by_name = {s.name: s for s in spans}
        for child in ("prefill", "decode", "rpc.remote"):
            assert by_name[child].parent_id == root.span_id, child
            assert by_name[child].trace_id == root.trace_id, child
        assert by_name["request"].parent_id is None

        # JSONL: one valid object per line, schema keys present
        lines = [json.loads(ln) for ln in
                 col.jsonl(root.trace_id).splitlines()]
        assert len(lines) == 4
        for d in lines:
            assert {"trace_id", "span_id", "parent_id", "name", "ts",
                    "dur", "tid", "attrs"} <= set(d), d
            assert d["dur"] >= 0.0

        # Chrome trace: X events with µs timestamps + thread metadata
        ct = col.chrome_trace(root.trace_id)
        xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        ms = [e for e in ct["traceEvents"] if e.get("ph") == "M"]
        assert len(xs) == 4 and ms, ct
        for e in xs:
            assert e["ts"] > 0 and e["dur"] >= 0
            assert e["args"]["trace_id"] == root.trace_id

        # Prometheus render smoke (the other export surface)
        from dnn_tpu.utils.metrics import Metrics, labeled, render_prometheus

        m = Metrics()
        m.inc(labeled("selftest_total", leg="trace"))
        m.observe("selftest_seconds", 0.001)
        text = render_prometheus(m)
        assert "# TYPE selftest_total counter" in text
        assert 'selftest_total{leg="trace"} 1' in text
    finally:
        _t._collector = saved
    print(f"obs selftest ok: {len(spans)} spans, 1 trace "
          f"({root.trace_id}), chrome+jsonl+prometheus schemas valid")
    return 0


def _convert(jsonl_path: str, out_path: str, trace_id=None) -> int:
    from dnn_tpu.obs.trace import spans_to_chrome

    dicts = []
    with open(jsonl_path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            d = json.loads(ln)
            if trace_id is None or d.get("trace_id") == trace_id:
                dicts.append(d)
    chrome = spans_to_chrome(dicts)
    with open(out_path, "w") as f:
        json.dump(chrome, f)
    n = sum(1 for e in chrome["traceEvents"] if e.get("ph") == "X")
    print(f"wrote {out_path}: {n} spans"
          + (f" (trace {trace_id})" if trace_id else ""))
    return 0


def _flight_selftest() -> int:
    from dnn_tpu import obs
    from dnn_tpu.obs.flight import FlightRecorder

    obs.set_enabled(True)
    fr = FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("probe", i=i)
    evs = fr.events()
    assert len(evs) == 4, evs  # bounded: newest 4 survive
    assert [e["i"] for e in evs] == [2, 3, 4, 5], evs
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    fr.record("deadline_miss", trace_id="cafe", rid=7)
    hit = fr.events(kind="deadline_miss")
    assert len(hit) == 1 and hit[0]["trace_id"] == "cafe"
    assert fr.events(trace_id="cafe") == hit
    assert len(fr.events(last=2)) == 2
    lines = [json.loads(ln) for ln in fr.jsonl().splitlines()]
    for d in lines:
        assert {"seq", "ts", "kind"} <= set(d), d
    win = fr.window(hit[0]["ts"], before_s=60, after_s=1)
    assert hit[0] in win and len(win) >= 2  # surrounding events ride along
    print(f"flight selftest ok: {len(lines)} events, overflow/filters/"
          "window/schema valid")
    return 0


def _flight_fetch(url: str, out=None, kind=None, trace=None,
                  last=None) -> int:
    from urllib.parse import urlencode
    from urllib.request import urlopen

    q = {k: v for k, v in
         (("kind", kind), ("trace", trace), ("last", last))
         if v is not None}
    full = url.rstrip("/") + "/debugz" + ("?" + urlencode(q) if q else "")
    body = urlopen(full, timeout=10).read().decode()
    if out:
        with open(out, "w") as f:
            f.write(body)
        print(f"wrote {out}: {len(body.splitlines())} events")
    else:
        sys.stdout.write(body)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m dnn_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("trace", help="trace export tooling")
    tr.add_argument("--selftest", action="store_true",
                    help="in-process span-pipeline smoke; exit 0 on pass")
    tr.add_argument("--jsonl", help="input JSONL span dump to convert")
    tr.add_argument("--out", help="output Chrome-trace JSON path")
    tr.add_argument("--id", dest="trace_id", default=None,
                    help="restrict conversion to one trace id")
    fl = sub.add_parser("flight", help="flight-recorder tooling")
    fl.add_argument("--selftest", action="store_true",
                    help="in-process flight-ring smoke; exit 0 on pass")
    fl.add_argument("--url", help="obs endpoint base URL to fetch "
                                  "/debugz from (http://host:port)")
    fl.add_argument("--out", help="write the JSONL here instead of stdout")
    fl.add_argument("--kind", default=None, help="filter by event kind")
    fl.add_argument("--trace", default=None, help="filter by trace id")
    fl.add_argument("--last", default=None, type=int,
                    help="keep only the newest N events")
    args = ap.parse_args(argv)

    if args.cmd == "trace":
        if args.selftest:
            return _selftest()
        if args.jsonl and args.out:
            return _convert(args.jsonl, args.out, args.trace_id)
        ap.error("trace needs --selftest or --jsonl FILE --out FILE")
    if args.cmd == "flight":
        if args.selftest:
            return _flight_selftest()
        if args.url:
            return _flight_fetch(args.url, args.out, args.kind,
                                 args.trace, args.last)
        ap.error("flight needs --selftest or --url URL")
    return 2


if __name__ == "__main__":
    sys.exit(main())
