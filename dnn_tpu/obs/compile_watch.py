"""Compile telemetry: XLA compilations as live counters.

PR 2's static recompile census (PRG004) bounds how many programs a
workload SHOULD compile; this module is the runtime cross-check. A
jax.monitoring duration listener turns every backend compile into two
registry series:

    jax_compilations_total        — count of XLA backend compiles
    jax_compile_seconds_total     — wall seconds spent compiling
    jax_trace_seconds_total       — jaxpr tracing seconds (the Python
                                    side of a cache miss)

A serving daemon whose step programs are stable sits at a small constant;
a recompile storm (shape churn, traced-value leaks) shows up as a
climbing counter on /metrics instead of a mystery stall. The listener
writes only when observability is enabled (the gate is re-checked per
event), costs ~a dict update per compile, and never raises into jax.
"""

from __future__ import annotations

import logging

log = logging.getLogger("dnn_tpu.obs")

# event keys fired by jax.monitoring during a jit cache miss
_COMPILE_KEY = "/jax/core/compile/backend_compile_duration"
_TRACE_KEY = "/jax/core/compile/jaxpr_trace_duration"


def _on_duration(name: str, dur: float, **kwargs):
    try:
        from dnn_tpu import obs

        m = obs.metrics()
        if m is None:
            return
        if name == _COMPILE_KEY:
            m.inc("jax_compilations_total")
            m.inc("jax_compile_seconds_total", dur)
            # the flight ring keeps compiles next to the admissions/
            # retirements they interleave with — a post-mortem dump shows
            # "recompile right before the deadline miss" as adjacency
            from dnn_tpu.obs import flight

            flight.record("compile", seconds=round(dur, 4))
        elif name == _TRACE_KEY:
            m.inc("jax_trace_seconds_total", dur)
    except Exception:  # noqa: BLE001 — telemetry must never break compiles
        log.debug("compile telemetry listener failed", exc_info=True)


def _install() -> bool:
    """Register the listener with jax.monitoring. Called once per process
    via obs.install_compile_telemetry(); returns False (and stays
    uninstalled) on jax versions without the monitoring API."""
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_duration)
        return True
    except Exception:  # noqa: BLE001 — absent/old jax: telemetry is optional
        log.debug("jax.monitoring unavailable; compile telemetry off",
                  exc_info=True)
        return False
