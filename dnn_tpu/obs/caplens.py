"""caplens: the capacity observatory for the elastic fleet.

PR 12's router emits `dnn_tpu_wanted_replicas` and nothing consumes
it; ROADMAP item 3 (demand-matched capacity) is the last pillar with
no instrument. The repo's proven sequence — StepClock before overlap,
kvlens before the hierarchical tier, trainlens before training at
scale — says the autoscaler must be judged by an observatory built
first. This module is that observatory, three instruments in one
object:

  1. **Demand estimator.** The router's admission seam feeds every
     arrival (monotonic stamp, prefill tokens, scenario tag from the
     request id) into a seed-pinned bounded ring; commits feed a
     second ring of delivered tokens. Scrape-side `demand()` derives
     the windowed arrival rate, burstiness (index of dispersion and
     peak-to-mean over per-second buckets — the PR 13 diurnal/bursty
     envelopes show up here), per-scenario token demand, and a
     change-point flag (recent-half vs prior-half rate ratio).

  2. **Learned per-replica capacity + cold-start ledger.** Committed
     forwards teach per-role service-time reservoirs — a sample is
     admitted to the PLANNING reservoir only when the replica had a
     free slot at dispatch (`inflight_at_dispatch < slots`), so the
     learned distribution is service, not service-plus-queue — and a
     per-replica delivered-tokens/s EMA. Each replica spawn opens a
     ledger entry; `spawn_ready` and the first committed token close
     it, attributing the spawn->first-token wall into process-start /
     weight-load / compile / warmup buckets using the child's boot
     gauges (`dnn_tpu_boot_*_seconds`, node.py) and the existing
     compile-telemetry counter (`jax_compile_seconds_total`,
     obs/compile_watch). Buckets are measured INDEPENDENTLY — the
     ledger reports the coverage fraction they explain rather than
     defining a residual bucket to claim 100% — and each finalized
     spawn is a `coldstart` flight event.

  3. **What-if planner + audited wanted-replicas v2.** `plan(n)`
     deterministically replays the recorded arrival ring through a
     discrete-event simulation of n replicas (slots-per-replica
     servers, the router's n*max_inflight admission bound, service
     times drawn from the learned reservoir by seed-pinned inverse
     CDF — same ring => bit-identical verdict), pricing cold-start
     debt as a not-yet-free interval on cold replicas' slots. It
     predicts availability (admitted AND inside the deadline),
     queue-wait and TTFT quantiles, and shed fraction at n replicas.
     `wanted_replicas(n_live)` is the smallest n whose predicted SLO
     holds; every transition records its full decision inputs (demand
     window, capacity estimates, per-n verdicts, SLO margins) in a
     bounded audit trail and as a `caplens_decision` flight event.
     Served on `/capz` (JSON | `?format=prom`), as `/fleetz` rollup
     columns, and via `python -m dnn_tpu.obs caplens
     [--url|PATH|--selftest]`. `benchmarks/capacity_plan_probe.py`
     closes the loop the kvlens way: observe a 1-replica fleet under
     a PR 13 arrival trace, predict the 2-replica fleet, then measure
     the real 2-replica fleet on the identical trace and assert the
     prediction-error ceiling.

Overhead contract: every producer opens with the obs gate check and
the router/replicaset hook sites guard with one `lens is not None`
test; producers append to bounded deques and bump counters — all
derivation (windowing, quantiles, planning) is scrape-side, and
planning is additionally throttled by `replan_interval_s`. The
`obs_overhead_probe --caplens` leg holds the admission path under
the repo-wide <2% tax with the lens live.

Threading: producers run on the router's event loop and the
replicaset monitor thread; scrape-side readers copy bounded deques
and load ints/floats — the same tolerance every serving gauge lives
with (kvlens contract).
"""

from __future__ import annotations

import hashlib
import heapq
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

from dnn_tpu.obs.flight import FlightRecorder
from dnn_tpu.utils.metrics import labeled

__all__ = ["CapLens", "CapSLO", "MIN_RING", "MIN_SERVICE"]

# the planner refuses to plan (wanted_replicas returns None -> v1
# heuristic fallback) below these floors: a verdict replayed from a
# handful of arrivals is noise wearing a confidence interval
MIN_RING = 16
MIN_SERVICE = 8

_ROLES = ("prefill", "decode", "both")

_obs = None  # lazy: breaks the obs<->caplens import cycle (flight idiom)


def _enabled() -> bool:
    global _obs
    if _obs is None:
        from dnn_tpu import obs as _o

        _obs = _o
    return _obs.enabled()


def _q(sorted_vals: List[float], frac: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(int(frac * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


class CapSLO:
    """The serving objective the planner sizes against."""

    def __init__(self, availability: float = 0.99,
                 wait_p95_s: Optional[float] = None):
        self.availability = float(availability)
        self.wait_p95_s = None if wait_p95_s is None else float(wait_p95_s)

    def as_dict(self) -> dict:
        return {"availability": self.availability,
                "wait_p95_s": self.wait_p95_s}


class CapLens:
    """One lens per Router. See module docstring."""

    def __init__(self, *, slots_per_replica: int = 4,
                 max_inflight: int = 8,
                 deadline_s: float = 30.0,
                 seed: int = 0,
                 window_s: float = 60.0,
                 ring_cap: int = 4096,
                 service_cap: int = 512,
                 ledger_cap: int = 256,
                 max_replicas: int = 8,
                 slo: Optional[CapSLO] = None,
                 coldstart_default_s: float = 20.0,
                 replan_interval_s: float = 1.0,
                 settle_s: float = 2.0,
                 now=time.monotonic,
                 signals: Optional[Callable[[str], dict]] = None):
        self.slots_per_replica = max(1, int(slots_per_replica))
        self.max_inflight = max(1, int(max_inflight))
        self.deadline_s = float(deadline_s)
        self.seed = int(seed)
        self.window_s = float(window_s)
        self.max_replicas = max(1, int(max_replicas))
        self.slo = slo if slo is not None else CapSLO()
        self.coldstart_default_s = float(coldstart_default_s)
        self.replan_interval_s = float(replan_interval_s)
        # a committed spawn's buckets are computed this long after the
        # first token, so the 1 s fleet scrape has flushed the child's
        # compile counter for the first (compiling) request
        self.settle_s = float(settle_s)
        self._now = now
        self._signals = signals
        self._prefix = f"caplens:{self.seed}:"
        # demand: bounded arrival/commit rings (producers append only)
        self._ring: "deque[tuple]" = deque(maxlen=int(ring_cap))
        self._commits: "deque[tuple]" = deque(maxlen=int(ring_cap))
        self.arrivals_total = 0
        self.prefill_tokens_total = 0
        self.committed_tokens_total = 0
        self.commits_total = 0
        self.sheds_by_reason: Dict[str, int] = {}
        self._scenarios: Dict[str, list] = {}  # name -> [count, tokens]
        # capacity: per-role service reservoirs (bounded, deterministic
        # ring-replacement so the same commit sequence always leaves
        # the same reservoir) + per-replica tokens/s EMA
        self._svc_cap = max(MIN_SERVICE, int(service_cap))
        self._svc: Dict[str, list] = {r: [] for r in _ROLES}
        self._svc_n: Dict[str, int] = {r: 0 for r in _ROLES}
        self._svc_all: List[float] = []
        self._svc_all_n = 0
        self._tps_ema: Dict[str, float] = {}
        self._queued_commits = 0  # samples kept out of the planning set
        # cold-start ledger: name -> open entry; finalized ring
        self._pending: Dict[str, dict] = {}
        self._finalized: "deque[dict]" = deque(maxlen=64)
        self.spawns_total = 0
        self.ledger = FlightRecorder(ledger_cap)
        # planner cache + audit trail
        self._plan_cache: Dict[int, dict] = {}
        self._plan_cache_key = None
        self._wanted_last: Optional[int] = None
        self._wanted_ts = 0.0
        self._audit: "deque[dict]" = deque(maxlen=64)

    # -- deterministic randomness (chaos-planner idiom) ----------------

    def _uniform(self, name: str, i: int) -> float:
        h = hashlib.blake2s(f"{self._prefix}{name}:{i}".encode(),
                            digest_size=8).digest()
        return int.from_bytes(h, "big") / 2.0 ** 64

    # -- producers (router event loop / replicaset monitor) ------------

    def on_arrival(self, prefill_tokens: int, scenario: str = "other",
                   now: Optional[float] = None):
        """One request hit the router's front door (pre-admission)."""
        if not _enabled():
            return
        t = self._now() if now is None else now
        tok = max(0, int(prefill_tokens))
        self._ring.append((t, tok, scenario))
        self.arrivals_total += 1
        self.prefill_tokens_total += tok
        s = self._scenarios.get(scenario)
        if s is None:
            if len(self._scenarios) < 64:
                self._scenarios[scenario] = [1, tok]
        else:
            s[0] += 1
            s[1] += tok

    def on_shed(self, reason: str):
        if not _enabled():
            return
        self.sheds_by_reason[reason] = \
            self.sheds_by_reason.get(reason, 0) + 1

    def on_commit(self, replica: str, role: str = "both", *,
                  tokens: int = 0, wall_s: float = 0.0,
                  inflight_at_dispatch: int = 0,
                  now: Optional[float] = None):
        """One forward committed on `replica`. `wall_s` is the router's
        dispatch->response wall; it is admitted to the PLANNING
        reservoir only when the replica had a free slot at dispatch
        (otherwise it prices replica-internal queueing into "service"
        and the sim double-counts the queue it simulates)."""
        if not _enabled():
            return
        t = self._now() if now is None else now
        tok = max(0, int(tokens))
        w = float(wall_s)
        self.commits_total += 1
        self.committed_tokens_total += tok
        self._commits.append((t, tok))
        role = role if role in _ROLES else "both"
        if w > 0.0:
            if int(inflight_at_dispatch) < self.slots_per_replica:
                self._res_push(self._svc, self._svc_n, role, w)
            else:
                self._queued_commits += 1
            i = self._svc_all_n % self._svc_cap
            if len(self._svc_all) <= i:
                self._svc_all.append(w)
            else:
                self._svc_all[i] = w
            self._svc_all_n += 1
            if tok > 0:
                tps = tok / w
                prev = self._tps_ema.get(replica)
                self._tps_ema[replica] = tps if prev is None \
                    else 0.2 * tps + 0.8 * prev
        ent = self._pending.get(replica)
        if ent is not None and ent.get("t_first") is None:
            ent["t_first"] = t
            ent["first_wall_s"] = w

    def _res_push(self, res: Dict[str, list], counts: Dict[str, int],
                  role: str, v: float):
        i = counts[role] % self._svc_cap
        lst = res[role]
        if len(lst) <= i:
            lst.append(v)
        else:
            lst[i] = v
        counts[role] += 1

    # cold-start ledger producers (replicaset lifecycle seams)

    def spawn_begin(self, name: str, role: str = "both",
                    now: Optional[float] = None):
        if not _enabled():
            return
        t = self._now() if now is None else now
        self.spawns_total += 1
        self._pending[name] = {"replica": name, "role": role,
                               "t_spawn": t, "t_ready": None,
                               "t_first": None, "first_wall_s": None}
        self.ledger.record("spawn_begin", replica=name, role=role)

    def spawn_ready(self, name: str, now: Optional[float] = None):
        if not _enabled():
            return
        t = self._now() if now is None else now
        ent = self._pending.get(name)
        if ent is not None and ent.get("t_ready") is None:
            ent["t_ready"] = t
            self.ledger.record("spawn_ready", replica=name,
                               spawn_to_ready_s=round(
                                   t - ent["t_spawn"], 3))

    def spawn_gone(self, name: str):
        """The replica died or drained before its first token: close
        the ledger entry unfinalized (a spawn that never served)."""
        if not _enabled():
            return
        ent = self._pending.pop(name, None)
        if ent is not None and ent.get("t_first") is None:
            self.ledger.record("spawn_abandoned", replica=name,
                               role=ent["role"])

    # -- cold-start attribution (scrape side) --------------------------

    def _signals_for(self, name: str) -> dict:
        if self._signals is None:
            return {}
        try:
            return self._signals(name) or {}
        except Exception:  # noqa: BLE001 — a scrape hiccup is not a
            return {}      # reason to drop a ledger entry

    def _maybe_finalize(self, now: float):
        """Commit->buckets, `settle_s` after the first token (so the
        periodic fleet scrape has flushed the child's compile counter
        for the first, compiling, request). Buckets:

          process_start  child's dnn_tpu_boot_imports_seconds gauge
                         (exec + interpreter + imports, from /proc)
          weight_load    child's dnn_tpu_boot_weight_load_seconds
                         (engine build + weight prepare wall, minus
                         compile seconds inside that span)
          compile        jax_compile_seconds_total at finalize (the
                         child is fresh: its whole counter is boot)
          warmup         post-ready wall to the first token, minus
                         the compile seconds that landed after ready

        Coverage = sum(buckets) / (t_first - t_spawn). What the sum
        honestly misses: fork->exec lag, the child's serve-bind span
        (grpc server construction), and the caller's poll gap before
        the first request — the capacity_plan_probe asserts these
        stay under 5% of the wall."""
        done = []
        for name, ent in list(self._pending.items()):
            t_first = ent.get("t_first")
            if t_first is None or now - t_first < self.settle_s:
                continue
            sig = self._signals_for(name)
            t_spawn = ent["t_spawn"]
            t_ready = ent.get("t_ready")
            total = max(t_first - t_spawn, 1e-9)
            imports = float(sig.get("boot_imports_s") or 0.0)
            weight = float(sig.get("boot_weight_load_s") or 0.0)
            compile_s = float(sig.get("compile_seconds_total") or 0.0)
            pre = float(sig.get("boot_compile_preready_s") or 0.0)
            ready_total = float(sig.get("boot_ready_total_s") or 0.0)
            post_compile = max(0.0, compile_s - pre)
            if ready_total > 0.0:
                warm = max(0.0, total - ready_total - post_compile)
            elif t_ready is not None:
                warm = max(0.0, (t_first - t_ready) - post_compile)
            else:
                warm = 0.0
            buckets = {"process_start_s": round(imports, 3),
                       "weight_load_s": round(weight, 3),
                       "compile_s": round(compile_s, 3),
                       "warmup_s": round(warm, 3)}
            covered = imports + weight + compile_s + warm
            rec = {"replica": name, "role": ent["role"],
                   "total_s": round(total, 3),
                   "spawn_to_ready_s":
                       round(t_ready - t_spawn, 3)
                       if t_ready is not None else None,
                   "buckets": buckets,
                   "coverage": round(min(covered / total, 1.0), 4)}
            self._finalized.append(rec)
            self.ledger.record("coldstart", **{
                "replica": name, "role": ent["role"],
                "total_s": rec["total_s"],
                "coverage": rec["coverage"], **buckets})
            done.append(name)
        for name in done:
            self._pending.pop(name, None)

    def coldstart(self) -> dict:
        """Finalized-spawn distributions (the /capz coldstart block)."""
        self._maybe_finalize(self._now())
        ents = list(self._finalized)
        totals = sorted(e["total_s"] for e in ents)
        out = {"spawns": self.spawns_total,
               "finalized": len(ents),
               "pending": len(self._pending),
               "total_p50_s": _q(totals, 0.5),
               "total_p95_s": _q(totals, 0.95),
               "coverage_mean": round(
                   sum(e["coverage"] for e in ents) / len(ents), 4)
               if ents else None,
               "buckets_p50_s": {}, "entries": ents[-8:]}
        if ents:
            for b in ("process_start_s", "weight_load_s", "compile_s",
                      "warmup_s"):
                vals = sorted(e["buckets"][b] for e in ents)
                out["buckets_p50_s"][b] = _q(vals, 0.5)
        return out

    def coldstart_delay_s(self) -> float:
        """The planner's price for one cold replica (p50 observed
        spawn->first-token wall; the configured default until any
        spawn has finalized)."""
        self._maybe_finalize(self._now())
        totals = sorted(e["total_s"] for e in self._finalized)
        v = _q(totals, 0.5)
        return float(v) if v is not None else self.coldstart_default_s

    # -- demand (scrape side) ------------------------------------------

    def demand(self, now: Optional[float] = None) -> dict:
        t = self._now() if now is None else now
        lo = t - self.window_s
        win = [(a, tok, sc) for (a, tok, sc) in list(self._ring)
               if a >= lo]
        n = len(win)
        out = {"window_s": self.window_s, "arrivals": n,
               "arrivals_total": self.arrivals_total,
               "rate_hz": round(n / self.window_s, 4),
               "prefill_tokens_per_s": round(
                   sum(w[1] for w in win) / self.window_s, 2),
               "committed_tokens_per_s": round(
                   sum(tok for (a, tok) in list(self._commits)
                       if a >= lo) / self.window_s, 2),
               "index_of_dispersion": None, "peak_to_mean": None,
               "change_point": False, "scenarios": {
                   k: {"count": v[0], "prefill_tokens": v[1]}
                   for k, v in sorted(self._scenarios.items())}}
        if n >= 2:
            t0 = win[0][0]
            span = max(win[-1][0] - t0, 1e-9)
            nb = max(2, min(int(span) + 1, 120))
            buckets = [0] * nb
            for (a, _tok, _sc) in win:
                buckets[min(int((a - t0) / span * nb), nb - 1)] += 1
            mean = n / nb
            var = sum((b - mean) ** 2 for b in buckets) / nb
            out["index_of_dispersion"] = round(var / mean, 3)
            out["peak_to_mean"] = round(max(buckets) / mean, 3)
            mid = t0 + span / 2.0
            early = sum(1 for (a, _t, _s) in win if a < mid)
            late = n - early
            ratio = late / max(early, 1)
            out["rate_ratio_recent"] = round(ratio, 3)
            out["change_point"] = bool(ratio > 2.0 or ratio < 0.5)
        return out

    # -- capacity (scrape side) ----------------------------------------

    def _planning_services(self) -> List[float]:
        """The sorted service-time sample the sim draws from: the
        free-slot-at-dispatch reservoir, falling back to the
        unconditioned one while the conditioned set is too thin."""
        svc = [v for r in _ROLES for v in self._svc[r]]
        if len(svc) < MIN_SERVICE:
            svc = list(self._svc_all)
        return sorted(svc)

    def capacity(self) -> dict:
        per_role = {}
        for r in _ROLES:
            vals = sorted(self._svc[r])
            if vals:
                per_role[r] = {"samples": min(self._svc_n[r],
                                              self._svc_cap),
                               "service_p50_s": _q(vals, 0.5),
                               "service_p95_s": _q(vals, 0.95)}
        return {"slots_per_replica": self.slots_per_replica,
                "max_inflight_per_replica": self.max_inflight,
                "commits_total": self.commits_total,
                "queued_commits_excluded": self._queued_commits,
                "service_by_role": per_role,
                "tokens_per_s_by_replica": {
                    k: round(v, 2)
                    for k, v in sorted(self._tps_ema.items())},
                "coldstart_delay_s": round(self.coldstart_delay_s(), 3)}

    # -- the what-if planner -------------------------------------------

    def plan(self, n: int, warm: Optional[int] = None
             ) -> Optional[dict]:
        """Deterministically replay the recorded arrival ring against
        an n-replica fleet: n*slots servers (FIFO, earliest-free),
        the router's n*max_inflight admission bound (arrivals beyond
        it shed, exactly `shed_reason`'s saturation test), service
        times drawn from the learned reservoir by seed-pinned inverse
        CDF. Replicas beyond `warm` start cold: their slots are not
        free until the observed p50 spawn->first-token wall has
        elapsed. Same ring + reservoir + seed => bit-identical
        verdict. None until MIN_RING arrivals and MIN_SERVICE
        committed samples exist — a planner with no evidence defers
        to the v1 heuristic."""
        n = int(n)
        if n < 1:
            return None
        ring = list(self._ring)
        svc = self._planning_services()
        if len(ring) < MIN_RING or len(svc) < MIN_SERVICE:
            return None
        warm_n = n if warm is None else max(0, min(n, int(warm)))
        cold = n - warm_n
        cold_delay = self.coldstart_delay_s()
        t0 = ring[0][0]
        servers: List[float] = []
        for r in range(n):
            free0 = t0 if r < warm_n else t0 + cold_delay
            servers.extend([free0] * self.slots_per_replica)
        heapq.heapify(servers)
        bound = n * self.max_inflight
        active: List[float] = []  # in-system finish times
        m = len(svc)
        ok = shed = late = 0
        waits: List[float] = []
        walls: List[float] = []
        for i, (t, _tok, _sc) in enumerate(ring):
            while active and active[0] <= t:
                heapq.heappop(active)
            if len(active) >= bound:
                shed += 1
                continue
            s = svc[min(int(self._uniform("svc", i) * m), m - 1)]
            free = heapq.heappop(servers)
            start = max(t, free)
            finish = start + s
            heapq.heappush(servers, finish)
            heapq.heappush(active, finish)
            waits.append(start - t)
            walls.append(finish - t)
            if finish - t <= self.deadline_s:
                ok += 1
            else:
                late += 1
        total = len(ring)
        waits.sort()
        walls.sort()
        return {"n": n, "warm": warm_n, "cold": cold,
                "arrivals": total,
                "availability": round(ok / total, 4),
                "shed_frac": round(shed / total, 4),
                "deadline_frac": round(late / total, 4),
                "wait_p50_s": round(_q(waits, 0.5) or 0.0, 4),
                "wait_p95_s": round(_q(waits, 0.95) or 0.0, 4),
                "ttft_p95_s": round(_q(walls, 0.95) or 0.0, 4),
                "coldstart_debt_s": round(cold * cold_delay, 3),
                "service_samples": m}

    def _meets_slo(self, p: dict) -> bool:
        if p["availability"] < self.slo.availability:
            return False
        if self.slo.wait_p95_s is not None \
                and p["wait_p95_s"] > self.slo.wait_p95_s:
            return False
        return True

    def wanted_replicas(self, n_live: int = 0,
                        now: Optional[float] = None) -> Optional[int]:
        """Smallest n in 1..max_replicas whose predicted SLO holds
        (max_replicas when none does — saturate loud, not silent).
        None while the planner lacks evidence (caller falls back to
        the v1 occupancy heuristic). Cached for `replan_interval_s`;
        every transition appends its full decision inputs to the
        audit trail."""
        t = self._now() if now is None else now
        if self._wanted_last is not None \
                and t - self._wanted_ts < self.replan_interval_s:
            return self._wanted_last
        plans = []
        chosen = None
        for n in range(1, self.max_replicas + 1):
            p = self.plan(n, warm=min(n, max(0, int(n_live))))
            if p is None:
                return None
            p["meets_slo"] = self._meets_slo(p)
            p["availability_margin"] = round(
                p["availability"] - self.slo.availability, 4)
            plans.append(p)
            if chosen is None and p["meets_slo"]:
                chosen = n
                break
        wanted = chosen if chosen is not None else self.max_replicas
        prev = self._wanted_last
        self._wanted_last = wanted
        self._wanted_ts = t
        if wanted != prev:
            entry = {"t": round(t, 3), "from": prev, "to": wanted,
                     "n_live": int(n_live),
                     "slo": self.slo.as_dict(),
                     "slo_unmet": chosen is None,
                     "demand": self.demand(now=t),
                     "capacity": self.capacity(),
                     "plans": plans}
            self._audit.append(entry)
            self.ledger.record(
                "caplens_decision", wanted=wanted,
                prev=prev, n_live=int(n_live),
                slo_unmet=chosen is None,
                rate_hz=entry["demand"]["rate_hz"],
                availability=plans[-1]["availability"])
        return wanted

    # -- scrape surface ------------------------------------------------

    def summary(self) -> dict:
        """The /capz JSON body."""
        now = self._now()
        plans = [p for p in (self.plan(n, warm=None)
                             for n in (1, 2, 4)) if p is not None]
        return {
            "config": {"slots_per_replica": self.slots_per_replica,
                       "max_inflight_per_replica": self.max_inflight,
                       "deadline_s": self.deadline_s,
                       "seed": self.seed,
                       "window_s": self.window_s,
                       "max_replicas": self.max_replicas,
                       "slo": self.slo.as_dict()},
            "demand": self.demand(now=now),
            "sheds_by_reason": dict(self.sheds_by_reason),
            "capacity": self.capacity(),
            "coldstart": self.coldstart(),
            "plans": plans,
            "wanted_replicas": self._wanted_last,
            "audit": list(self._audit)[-8:],
            "ledger": self.ledger.events(last=64),
        }

    def render_prom(self) -> str:
        """Prometheus text for `/capz?format=prom` (self-contained:
        the lens's own families, not the shared registry)."""
        d = self.demand()
        cs = self.coldstart()
        lines = [
            "# HELP dnn_tpu_caplens_arrival_rate_hz windowed arrival "
            "rate seen at the router front door",
            "# TYPE dnn_tpu_caplens_arrival_rate_hz gauge",
            f"dnn_tpu_caplens_arrival_rate_hz {d['rate_hz']:.6f}",
            "# TYPE dnn_tpu_caplens_index_of_dispersion gauge",
            f"dnn_tpu_caplens_index_of_dispersion "
            f"{(d['index_of_dispersion'] or 0.0):.6f}",
            "# TYPE dnn_tpu_caplens_peak_to_mean gauge",
            f"dnn_tpu_caplens_peak_to_mean "
            f"{(d['peak_to_mean'] or 0.0):.6f}",
            "# TYPE dnn_tpu_caplens_change_point gauge",
            f"dnn_tpu_caplens_change_point "
            f"{1.0 if d['change_point'] else 0.0}",
            "# TYPE dnn_tpu_caplens_arrivals_total counter",
            f"dnn_tpu_caplens_arrivals_total {self.arrivals_total}",
            "# TYPE dnn_tpu_caplens_commits_total counter",
            f"dnn_tpu_caplens_commits_total {self.commits_total}",
            "# TYPE dnn_tpu_caplens_coldstart_p50_seconds gauge",
            f"dnn_tpu_caplens_coldstart_p50_seconds "
            f"{(cs['total_p50_s'] or 0.0):.6f}",
            "# TYPE dnn_tpu_caplens_coldstart_coverage gauge",
            f"dnn_tpu_caplens_coldstart_coverage "
            f"{(cs['coverage_mean'] or 0.0):.6f}",
            "# TYPE dnn_tpu_caplens_wanted_replicas gauge",
            f"dnn_tpu_caplens_wanted_replicas "
            f"{float(self._wanted_last or 0)}",
        ]
        if cs["buckets_p50_s"]:
            lines.append("# TYPE dnn_tpu_caplens_coldstart_bucket"
                         "_p50_seconds gauge")
            for b, v in sorted(cs["buckets_p50_s"].items()):
                lines.append(
                    f'dnn_tpu_caplens_coldstart_bucket_p50_seconds'
                    f'{{bucket="{b}"}} {(v or 0.0):.6f}')
        lines.append("# TYPE dnn_tpu_caplens_plan_availability gauge")
        for n in (1, 2, 4):
            p = self.plan(n)
            if p is not None:
                lines.append(
                    f'dnn_tpu_caplens_plan_availability{{n="{n}"}} '
                    f"{p['availability']:.6f}")
        lines.append("# TYPE dnn_tpu_caplens_shed_total counter")
        for reason in sorted(self.sheds_by_reason):
            lines.append(
                f'dnn_tpu_caplens_shed_total{{reason="{reason}"}} '
                f"{self.sheds_by_reason[reason]}")
        return "\n".join(lines) + "\n"

    def prom_gauges(self) -> dict:
        """Weak scrape-time gauge closures for the serving registry
        (`_obs_gauges` idiom, kvlens contract): the registry outlives
        any router, so closures hold a weakref — a collected lens
        reads 0, never a dangling object."""
        ref = weakref.ref(self)

        def _g(fn):
            def read():
                lens = ref()
                if lens is None:
                    return 0.0
                v = fn(lens)
                return float(v) if v is not None else 0.0
            return read

        out = {
            "dnn_tpu_caplens_arrival_rate_hz":
                _g(lambda l: l.demand()["rate_hz"]),
            "dnn_tpu_caplens_peak_to_mean":
                _g(lambda l: l.demand()["peak_to_mean"]),
            "dnn_tpu_caplens_coldstart_p50_seconds":
                _g(lambda l: l.coldstart()["total_p50_s"]),
            "dnn_tpu_caplens_coldstart_coverage":
                _g(lambda l: l.coldstart()["coverage_mean"]),
            "dnn_tpu_caplens_wanted_replicas":
                _g(lambda l: l._wanted_last),
        }
        for n in (1, 2, 4):
            out[labeled("dnn_tpu_caplens_plan_availability",
                        n=str(n))] = _g(
                lambda l, nn=n: (l.plan(nn) or {}).get("availability"))
        return out
