"""Request tracing: span trees, a bounded collector, and wire propagation.

The reference has zero request visibility — a request's life across the
pipeline is reconstructable only from interleaved stdout prints (SURVEY
§5). This module gives every request a TRACE: a 64-bit trace id plus a
tree of timed spans (queue wait, admission, prefill, per-bucket decode,
per-hop RPC), collected into a bounded in-memory ring and exportable as
JSONL or Chrome-trace/Perfetto JSON (`chrome_trace`), so a single
request's 900 ms renders as a timeline instead of a mystery.

Propagation rides the EXISTING wire `request_id` field: a
`tr=<trace_id>.<span_id>` segment is appended (`tag_request_id`), which
every peer treats as opaque — the reference server relays request_id
verbatim, and our option parser skips unknown `key=value` segments
(lm_server.parse_gen_options) — so tracing is wire-compatible by
construction. Receivers parse the tag (`parse_wire_tag`) and parent
their spans under the sender's span, giving one tree across hops.

Cross-thread use (the LM batcher worker) passes parents EXPLICITLY
(`start_span(..., parent=...)`); same-thread code nests implicitly via
the contextvar-backed `span()` context manager. Everything degrades to
free no-ops when observability is off (dnn_tpu/obs: DNN_TPU_OBS=off).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from typing import Iterator, Optional

__all__ = [
    "Span", "NULL_SPAN", "TraceCollector", "collector", "span",
    "start_span", "record_span", "current_span", "tag_request_id",
    "parse_wire_tag", "strip_wire_tag", "new_trace_id",
]

_rand = random.Random()  # stdlib PRNG: ids need uniqueness, not crypto
_rand.seed(os.urandom(16))
_id_lock = threading.Lock()

# perf_counter -> wall-clock epoch mapping, fixed once so every span of a
# process shares a consistent timeline
_EPOCH0 = time.time() - time.perf_counter()


def new_trace_id() -> str:
    with _id_lock:
        return f"{_rand.getrandbits(64):016x}"


def _new_span_id() -> str:
    with _id_lock:
        return f"{_rand.getrandbits(32):08x}"


class Span:
    """One timed operation. Created by `start_span`/`span`; `end()` stamps
    the duration and commits it to the collector. Attrs are plain
    JSON-able values; setattr-style mutation goes through `set()`."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "dur",
                 "attrs", "tid", "_done")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.t0 = time.perf_counter()
        self.dur: Optional[float] = None
        self.tid = threading.get_ident()
        self._done = False

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def child(self, name: str, **attrs) -> "Span":
        return start_span(name, parent=self, **attrs)

    def end(self, **attrs):
        """Idempotent: the first call stamps and records; later calls are
        no-ops (retire paths and error paths may race to close)."""
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        self.dur = time.perf_counter() - self.t0
        collector().add(self)

    # make `with start_span(...) as s:` work for explicit-parent spans
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "ts": _EPOCH0 + self.t0, "dur": self.dur,
            "tid": self.tid, "attrs": self.attrs,
        }


class _NullSpan:
    """Free no-op stand-in when observability is off: every producer call
    site keeps its unconditional shape (`sp = start_span(...); sp.end()`)
    at the cost of a method dispatch, nothing else."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None
    name = "null"
    attrs: dict = {}
    dur = None

    def set(self, **attrs):
        return self

    def child(self, name, **attrs):
        return self

    def end(self, **attrs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False  # `if span:` selects the real-span path


NULL_SPAN = _NullSpan()

_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "dnn_tpu_obs_span", default=None)


def current_span() -> Optional[Span]:
    return _current.get()


class TraceCollector:
    """Bounded ring of FINISHED spans (ended spans only — an abandoned
    span is dropped, never half-recorded). Capacity bounds memory on a
    week-long daemon; a traced burst beyond it keeps the newest spans."""

    def __init__(self, capacity: int = 16384):
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque(maxlen=capacity)

    def add(self, s: Span):
        with self._lock:
            self._spans.append(s)

    def clear(self):
        with self._lock:
            self._spans.clear()

    def spans(self, trace_id: Optional[str] = None) -> list:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def trace_ids(self) -> list:
        """Distinct trace ids, oldest first."""
        seen: dict = {}
        for s in self.spans():
            seen.setdefault(s.trace_id, None)
        return list(seen)

    # -- exports --------------------------------------------------------

    def jsonl(self, trace_id: Optional[str] = None) -> str:
        return "".join(json.dumps(s.to_dict(), sort_keys=True) + "\n"
                       for s in self.spans(trace_id))

    def dump_jsonl(self, path: str, trace_id: Optional[str] = None):
        with open(path, "w") as f:
            f.write(self.jsonl(trace_id))

    def chrome_trace(self, trace_id: Optional[str] = None) -> dict:
        return spans_to_chrome([s.to_dict() for s in self.spans(trace_id)])


def spans_to_chrome(span_dicts: list) -> dict:
    """Span dicts (the JSONL schema) -> Chrome trace-event JSON: one
    complete ("ph":"X") event per span, timestamps in µs, one tid track
    per (thread, trace). Loads directly in Perfetto / chrome://tracing."""
    events = []
    tracks: dict = {}
    for d in span_dicts:
        key = (d["trace_id"], d["tid"])
        if key not in tracks:
            tracks[key] = len(tracks) + 1
            events.append({
                "ph": "M", "pid": 1, "tid": tracks[key],
                "name": "thread_name",
                "args": {"name": f"trace {d['trace_id'][:8]} "
                                 f"thread {d['tid']}"},
            })
        events.append({
            "name": d["name"], "cat": "dnn_tpu", "ph": "X",
            "ts": round(d["ts"] * 1e6, 3),
            "dur": round((d["dur"] or 0.0) * 1e6, 3),
            "pid": 1, "tid": tracks[key],
            "args": {**d["attrs"], "trace_id": d["trace_id"],
                     "span_id": d["span_id"],
                     "parent_id": d["parent_id"]},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


_collector = TraceCollector(
    int(os.environ.get("DNN_TPU_OBS_SPAN_CAP", "16384")))


def collector() -> TraceCollector:
    return _collector


# ----------------------------------------------------------------------
# producers
# ----------------------------------------------------------------------

def _enabled() -> bool:
    from dnn_tpu import obs

    return obs.enabled()


def start_span(name: str, *, parent: Optional[Span] = None,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None, **attrs):
    """Explicit span creation (cross-thread safe — no contextvar side
    effects). Parent resolution: explicit `parent` span > explicit
    (trace_id, parent_id) pair (a wire tag) > fresh root trace. Returns
    NULL_SPAN when observability is off."""
    if not _enabled():
        return NULL_SPAN
    if parent is not None and parent is not NULL_SPAN:
        trace_id, parent_id = parent.trace_id, parent.span_id
    elif trace_id is None:
        trace_id = new_trace_id()
    return Span(name, trace_id, _new_span_id(), parent_id, attrs)


def record_span(name: str, t0: float, dur: float, *,
                parent: Optional[Span] = None, **attrs):
    """Commit an already-measured interval (t0 = perf_counter at start)
    as a finished span — for producers that learn about an interval after
    the fact (queue wait is measured at dequeue time)."""
    if not _enabled():
        return NULL_SPAN
    s = start_span(name, parent=parent, **attrs)
    if s is not NULL_SPAN:
        s.t0 = t0
        s.tid = threading.get_ident()
        s._done = True
        s.dur = dur
        collector().add(s)
    return s


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Optional[Span]]:
    """Implicitly-nested span: parents under the calling context's span
    (same thread/task), and becomes the ambient parent for the body."""
    if not _enabled():
        yield None
        return
    s = start_span(name, parent=_current.get(), **attrs)
    tok = _current.set(s)
    try:
        yield s
    finally:
        _current.reset(tok)
        s.end()


# ----------------------------------------------------------------------
# wire propagation (the request_id tag)
# ----------------------------------------------------------------------

_TAG_PREFIX = "tr="


def tag_request_id(request_id: str, span) -> str:
    """Append/replace the trace tag on a wire request_id. Reference peers
    and the stage relay treat request_id as opaque; our parsers skip the
    segment — so tagging never changes wire behavior."""
    if span is None or span is NULL_SPAN or span.trace_id is None:
        return request_id
    base = strip_wire_tag(request_id)
    tag = f"{_TAG_PREFIX}{span.trace_id}.{span.span_id}"
    return f"{base}:{tag}" if base else tag


def strip_wire_tag(request_id: str) -> str:
    parts = [p for p in (request_id or "").split(":")
             if not p.startswith(_TAG_PREFIX)]
    return ":".join(parts)


def parse_wire_tag(request_id: str):
    """-> (trace_id, parent_span_id) or None. Tolerates a bare trace id
    (no '.<span_id>')."""
    for seg in (request_id or "").split(":"):
        if seg.startswith(_TAG_PREFIX):
            val = seg[len(_TAG_PREFIX):]
            tid, _, pid = val.partition(".")
            if tid:
                return tid, (pid or None)
    return None


def continue_or_start(name: str, request_id: str, **attrs):
    """Server-side root span for one handled request: CONTINUE the
    sender's trace when the request_id carries a `tr=` tag (the span
    parents under the sender's span, so one tree crosses the wire), else
    start a fresh trace. The one entry point every RPC handler uses
    (StageServer, LMServer). NULL_SPAN when observability is off."""
    link = parse_wire_tag(request_id or "")
    if link is not None:
        return start_span(name, trace_id=link[0], parent_id=link[1],
                          **attrs)
    return start_span(name, **attrs)
