"""Stdlib-HTTP observability endpoint: metrics, traces, flight, status,
profiling.

Attached to the LM daemon (runtime/lm_server.LMServer(metrics_port=...))
and the stage servers (comm/service.serve_stage(metrics_port=...)) — a
ThreadingHTTPServer on a daemon thread, zero dependencies, so any
Prometheus scraper or a plain curl can watch the serving stack:

    GET  /metrics      Prometheus text format (utils.metrics
                       render_prometheus over the shared registry)
    GET  /healthz      liveness, now three-valued: 200 "ok" / 200
                       "degraded" / 503 "wedged" from the watchdog
                       (obs/watchdog.py) when one is attached; an
                       optional `healthy` callable (worker thread
                       liveness) downgrades to 503 "unhealthy"
    GET  /statusz      watchdog state with per-component detail (JSON;
                       ?format=prom re-renders it as Prometheus gauges
                       for scrape-only collectors)
    GET  /debugz       flight-recorder ring as JSONL (obs/flight.py;
                       Content-Type application/x-ndjson); ?format=json
                       returns a proper JSON array (application/json) —
                       pollers never sniff; ?kind= ?trace= filter,
                       ?last=N keeps newest N
    GET  /fleetz       merged fleet view (obs/fleet.py) when a
                       FleetCollector is attached: worst-of health,
                       per-stage tables, totals, clock offsets
                       (?format=prom re-exports it as Prometheus text;
                       ?format=trace returns the stitched cross-host
                       Perfetto JSON, ?id=<trace> for one request;
                       ?format=report the human-readable text)
    GET  /stepz        step-timeline attribution (obs/timeline.py) when
                       a StepClock is attached: per-phase decode-step
                       decomposition (admit/host/dispatch/wait/commit/
                       obs), dispatch-slack, sync-tax, host fraction
                       (JSON; ?format=prom re-renders as gauges,
                       ?format=trace exports the last N steps as a
                       Perfetto-loadable host track, ?last=N bounds
                       the window)
    GET  /trainz       training-step observatory (obs/trainlens.py)
                       when a TrainClock is attached: per-phase
                       training-iteration decomposition (data/dispatch/
                       wait/ckpt/eval/obs), data_stall_fraction, MFU /
                       tokens-per-sec, checkpoint freshness (JSON;
                       ?format=prom re-renders as gauges, ?format=trace
                       exports the last N steps as a Perfetto host
                       track, ?last=N bounds the window)
    GET  /kvz          memory-economy observatory (obs/kvlens.py) when
                       a KVLens is attached: sampled reuse-distance
                       stats, the predicted hit-ratio-vs-capacity
                       curve (0.5x..8x of the pool), block lifecycle
                       counts, thrash pricing, and the bounded
                       per-block ledger tail (JSON; ?format=prom
                       re-renders the curve + thrash as gauges)
    GET  /capz         capacity observatory (obs/caplens.py) when a
                       CapLens is attached: windowed demand (rate,
                       burstiness, change points), learned per-role
                       service capacity, the cold-start ledger, what-if
                       plans at 1/2/4 replicas, the wanted-replicas
                       audit trail (JSON; ?format=prom re-renders the
                       headline series as gauges)
    GET  /trace        Chrome-trace JSON of collected spans; ?id=<trace>
                       filters to one request's tree (load the response
                       in Perfetto / chrome://tracing)
    GET  /trace.jsonl  the same spans as JSONL (one span per line)
    GET  /traces       the distinct trace ids currently in the ring
    GET  /profilez     capture spool + auto-trigger arm state (JSON)
    POST /profilez?ms=N            capture N ms of device+host profile
                       into the bounded spool (obs/profile.py); returns
                       the capture path + Perfetto-loadable trace files
    POST /profilez?auto=1&threshold_ms=T[&ms=N]   arm the auto trigger:
                       capture the next decode step after one exceeds
                       T ms (LM daemon only); ?auto=0 disarms
    POST /drainz       connection draining (LM daemon): stop admission,
                       finish in-flight decodes, hand queued work back
                       retriable, then exit — 202 + drain state JSON;
                       idempotent. /healthz reads 503 "draining" while
                       it runs (runtime/lm_server.LMServer.drain)
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

log = logging.getLogger("dnn_tpu.obs")

_STATE_GAUGE = {"ok": 0.0, "degraded": 1.0, "draining": 1.0,
                "wedged": 2.0}


def _status_prom(status: dict) -> str:
    """Render a /statusz payload (watchdog or fleet shape) as Prometheus
    gauges: dnn_tpu_status_state 0|1|2 (ok|degraded|wedged) plus one
    per-component series — the ?format=prom passthrough for collectors
    that only speak scrapes."""
    from dnn_tpu.utils.metrics import Metrics, labeled, render_prometheus

    m = Metrics()
    m.set("dnn_tpu_status_state",
          _STATE_GAUGE.get(status.get("state"), 1.0))
    for name, comp in (status.get("components") or {}).items():
        m.set(labeled("dnn_tpu_status_component_state", component=name),
              _STATE_GAUGE.get((comp or {}).get("state"), 1.0))
    return render_prometheus(m)


class MetricsHTTPServer:
    """Serve the shared registry + span collector + flight ring (or
    explicit ones) over HTTP. port=0 binds an ephemeral port — read
    `.port` after init.

    Binds LOOPBACK by default: the endpoint is unauthenticated, /trace
    and /debugz expose per-request timelines, and POST /profilez
    triggers device work — so wider exposure (a scrape fleet) is an
    explicit `host="0.0.0.0"` opt-in, not a default.

    `status`: callable -> dict with at least {"state": "ok|degraded|
    wedged"} (obs/watchdog.Watchdog.status), or None to fall back to
    the worker-liveness shape built from `healthy`. `profiler`: an
    obs/profile.Profiler. `flight`: a FlightRecorder (default: the
    process-wide ring)."""

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 registry=None, collector=None,
                 healthy: Optional[Callable[[], bool]] = None,
                 status: Optional[Callable[[], dict]] = None,
                 profiler=None, flight=None, fleet=None,
                 drain: Optional[Callable[[], dict]] = None,
                 stepclock=None, kvlens=None, trainlens=None,
                 caplens=None):
        from dnn_tpu import obs
        from dnn_tpu.obs import flight as _flight
        from dnn_tpu.utils import metrics as _metrics

        self._registry = registry if registry is not None \
            else _metrics.default_metrics
        self._collector = collector if collector is not None \
            else obs.collector()
        self._flight = flight if flight is not None \
            else _flight.recorder()
        self._healthy = healthy
        self._status = status
        self._profiler = profiler
        # fleet collector (obs/fleet.FleetCollector): serves /fleetz;
        # when no explicit `status` is given the fleet's worst-of
        # rollup also becomes /statusz + /healthz (503 on a wedged or
        # unreachable stage — the fleet endpoint's health IS the fleet's)
        self._fleet = fleet
        # POST /drainz (connection draining, ISSUE 8): the serving
        # process's drain kicker — idempotent, returns drain state
        self._drain = drain
        # step-timeline clock (obs/timeline.StepClock): serves /stepz
        self._stepclock = stepclock
        # memory-economy lens (obs/kvlens.KVLens): serves /kvz. The LM
        # daemon attaches it AFTER construction (the batcher — and its
        # lens — is built after the endpoint comes up), so the handler
        # reads it per request rather than capturing it here
        self._kvlens = kvlens
        # training-step clock (obs/trainlens.TrainClock): serves /trainz
        self._trainlens = trainlens
        # capacity observatory (obs/caplens.CapLens): serves /capz
        self._caplens = caplens
        if fleet is not None and status is None:
            self._status = fleet.status
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to logging, not stderr
                log.debug("metrics http: " + fmt, *args)

            def _send(self, code: int, body: str, ctype: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send_json(self, code: int, obj):
                # default=str: flight events may carry exotic values;
                # degrading one to its repr beats failing the dump
                self._send(code, json.dumps(obj, default=str),
                           "application/json")

            def _statusz(self):
                if outer._status is not None:
                    s = outer._status()
                    if s is not None:  # None = "no watchdog: fall back"
                        return s
                # no watchdog attached: report the one component every
                # server has — its worker/liveness callable
                ok = outer._healthy() if outer._healthy else True
                return {"state": "ok" if ok else "wedged",
                        "components": {"worker": {
                            "state": "ok" if ok else "wedged",
                            "detail": "serving worker thread liveness"}}}

            def _healthz(self):
                if outer._healthy is not None and not outer._healthy():
                    self._send(503, "unhealthy\n",
                               "text/plain; charset=utf-8")
                    return
                state = self._statusz()["state"]
                # draining is 503 too: a load balancer must stop
                # routing here while in-flight decodes finish
                self._send(503 if state in ("wedged", "draining")
                           else 200,
                           state + "\n", "text/plain; charset=utf-8")

            def _fleetz(self, q):
                if outer._fleet is None:
                    self._send(404, "no fleet collector attached\n",
                               "text/plain; charset=utf-8")
                    return
                fmt = q.get("format", ["json"])[0]
                if fmt == "json":
                    self._send_json(200, outer._fleet.fleetz())
                elif fmt == "prom":
                    self._send(200, outer._fleet.render_prom(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif fmt == "trace":
                    tid = q.get("id", [None])[0]
                    self._send(200, json.dumps(outer._fleet.stitch(tid)),
                               "application/json")
                elif fmt == "report":
                    tid = q.get("id", [None])[0]
                    self._send(200, outer._fleet.report(tid) + "\n",
                               "text/plain; charset=utf-8")
                else:
                    self._send(400, f"unknown format {fmt!r} "
                               "(json|prom|trace|report)\n",
                               "text/plain; charset=utf-8")

            def _stepz(self, q):
                if outer._stepclock is None:
                    self._send(404, "no step clock attached\n",
                               "text/plain; charset=utf-8")
                    return
                last = None
                if "last" in q:
                    try:
                        last = int(q["last"][0])
                    except ValueError:
                        last = 0
                    if last < 1:
                        # a negative slice bound would silently invert
                        # the window (newest-N becomes all-but-oldest-N)
                        self._send(400, "last must be an int >= 1\n",
                                   "text/plain; charset=utf-8")
                        return
                fmt = q.get("format", ["json"])[0]
                if fmt == "json":
                    self._send_json(200, outer._stepclock.summary(last))
                elif fmt == "prom":
                    self._send(200, outer._stepclock.render_prom(last),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif fmt == "trace":
                    self._send(200, json.dumps(
                        outer._stepclock.chrome_trace(last)),
                        "application/json")
                else:
                    self._send(400, f"unknown format {fmt!r} "
                               "(json|prom|trace)\n",
                               "text/plain; charset=utf-8")

            def _trainz(self, q):
                if outer._trainlens is None:
                    self._send(404, "no train clock attached\n",
                               "text/plain; charset=utf-8")
                    return
                last = None
                if "last" in q:
                    try:
                        last = int(q["last"][0])
                    except ValueError:
                        last = 0
                    if last < 1:
                        self._send(400, "last must be an int >= 1\n",
                                   "text/plain; charset=utf-8")
                        return
                fmt = q.get("format", ["json"])[0]
                if fmt == "json":
                    self._send_json(200, outer._trainlens.summary(last))
                elif fmt == "prom":
                    self._send(200, outer._trainlens.render_prom(last),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif fmt == "trace":
                    self._send(200, json.dumps(
                        outer._trainlens.chrome_trace(last)),
                        "application/json")
                else:
                    self._send(400, f"unknown format {fmt!r} "
                               "(json|prom|trace)\n",
                               "text/plain; charset=utf-8")

            def _kvz(self, q):
                if outer._kvlens is None:
                    self._send(404, "no kvlens attached\n",
                               "text/plain; charset=utf-8")
                    return
                fmt = q.get("format", ["json"])[0]
                if fmt == "json":
                    self._send_json(200, outer._kvlens.summary())
                elif fmt == "prom":
                    self._send(200, outer._kvlens.render_prom(),
                               "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._send(400, f"unknown format {fmt!r} "
                               "(json|prom)\n",
                               "text/plain; charset=utf-8")

            def _capz(self, q):
                if outer._caplens is None:
                    self._send(404, "no caplens attached\n",
                               "text/plain; charset=utf-8")
                    return
                fmt = q.get("format", ["json"])[0]
                if fmt == "json":
                    self._send_json(200, outer._caplens.summary())
                elif fmt == "prom":
                    self._send(200, outer._caplens.render_prom(),
                               "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._send(400, f"unknown format {fmt!r} "
                               "(json|prom)\n",
                               "text/plain; charset=utf-8")

            def do_GET(self):
                try:
                    url = urlparse(self.path)
                    q = parse_qs(url.query)
                    if url.path == "/metrics":
                        self._send(200, _metrics.render_prometheus(
                            outer._registry),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif url.path == "/healthz":
                        self._healthz()
                    elif url.path == "/statusz":
                        fmt = q.get("format", ["json"])[0]
                        if fmt == "prom":
                            # scrape-only collectors ingest status as
                            # gauges instead of sniffing JSON
                            self._send(200,
                                       _status_prom(self._statusz()),
                                       "text/plain; version=0.0.4; "
                                       "charset=utf-8")
                        elif fmt == "json":
                            self._send_json(200, self._statusz())
                        else:
                            self._send(400, f"unknown format {fmt!r} "
                                       "(json|prom)\n",
                                       "text/plain; charset=utf-8")
                    elif url.path == "/debugz":
                        filters = {}
                        if "kind" in q:
                            filters["kind"] = q["kind"][0]
                        if "trace" in q:
                            filters["trace_id"] = q["trace"][0]
                        if "last" in q:
                            try:
                                filters["last"] = int(q["last"][0])
                            except ValueError:
                                self._send(400, "last must be an int\n",
                                           "text/plain; charset=utf-8")
                                return
                        fmt = q.get("format", ["jsonl"])[0]
                        if fmt == "json":
                            # a proper JSON array for pollers; the
                            # JSONL default stays for `obs flight --url`
                            # and log-shipper tails
                            self._send_json(200,
                                            outer._flight.events(**filters))
                        elif fmt == "jsonl":
                            self._send(200,
                                       outer._flight.jsonl(**filters),
                                       "application/x-ndjson")
                        else:
                            self._send(400, f"unknown format {fmt!r} "
                                       "(jsonl|json)\n",
                                       "text/plain; charset=utf-8")
                    elif url.path == "/fleetz":
                        self._fleetz(q)
                    elif url.path == "/stepz":
                        self._stepz(q)
                    elif url.path == "/kvz":
                        self._kvz(q)
                    elif url.path == "/capz":
                        self._capz(q)
                    elif url.path == "/trainz":
                        self._trainz(q)
                    elif url.path == "/profilez":
                        if outer._profiler is None:
                            self._send(404, "no profiler attached\n",
                                       "text/plain; charset=utf-8")
                        else:
                            self._send_json(200, outer._profiler.status())
                    elif url.path == "/trace":
                        tid = q.get("id", [None])[0]
                        self._send(200, json.dumps(
                            outer._collector.chrome_trace(tid)),
                            "application/json")
                    elif url.path == "/trace.jsonl":
                        tid = q.get("id", [None])[0]
                        self._send(200, outer._collector.jsonl(tid),
                                   "application/jsonl")
                    elif url.path == "/traces":
                        self._send(200, json.dumps(
                            outer._collector.trace_ids()),
                            "application/json")
                    else:
                        self._send(404, "not found\n",
                                   "text/plain; charset=utf-8")
                except BrokenPipeError:  # scraper hung up mid-response
                    pass
                except Exception:  # noqa: BLE001 — one bad request must
                    # not kill the observer thread
                    log.exception("metrics endpoint request failed")
                    try:
                        self._send(500, "internal error\n",
                                   "text/plain; charset=utf-8")
                    except Exception:  # noqa: BLE001
                        pass

            def do_POST(self):
                try:
                    url = urlparse(self.path)
                    q = parse_qs(url.query)
                    if url.path == "/drainz":
                        if outer._drain is None:
                            self._send(404, "no drain handler attached "
                                       "(stage servers drain via their "
                                       "supervisor)\n",
                                       "text/plain; charset=utf-8")
                            return
                        self._send_json(202, outer._drain())
                        return
                    if url.path != "/profilez":
                        self._send(404, "not found\n",
                                   "text/plain; charset=utf-8")
                        return
                    if outer._profiler is None:
                        self._send(404, "no profiler attached\n",
                                   "text/plain; charset=utf-8")
                        return
                    from dnn_tpu.obs.profile import ProfilerBusy, trace_files

                    if "auto" in q:
                        arm = q["auto"][0] not in ("0", "false", "off")
                        if not arm:
                            outer._profiler.disarm()
                            self._send_json(200, {"armed": None})
                            return
                        try:
                            outer._profiler.arm_auto(
                                float(q.get("threshold_ms", ["100"])[0]),
                                float(q.get("ms", ["0"])[0]))
                        except ValueError as e:
                            self._send(400, str(e) + "\n",
                                       "text/plain; charset=utf-8")
                            return
                        self._send_json(200, outer._profiler.status())
                        return
                    try:
                        ms = float(q.get("ms", ["1000"])[0])
                    except ValueError:
                        self._send(400, "ms must be a number\n",
                                   "text/plain; charset=utf-8")
                        return
                    try:
                        path = outer._profiler.capture(ms)
                    except ProfilerBusy as e:
                        self._send(409, str(e) + "\n",
                                   "text/plain; charset=utf-8")
                        return
                    self._send_json(200, {
                        "capture": path, "ms": ms,
                        "trace_files": trace_files(path)})
                except BrokenPipeError:
                    pass
                except Exception:  # noqa: BLE001
                    log.exception("profilez request failed")
                    try:
                        self._send(500, "internal error\n",
                                   "text/plain; charset=utf-8")
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"obs-metrics-http:{self.port}")
        self._thread.start()
        log.info("observability endpoint on http://%s:%d/metrics",
                 host or "0.0.0.0", self.port)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
