"""Stdlib-HTTP observability endpoint: /metrics, /trace, /healthz.

Attached to the LM daemon (runtime/lm_server.LMServer(metrics_port=...))
and the stage servers (comm/service.serve_stage(metrics_port=...)) — a
ThreadingHTTPServer on a daemon thread, zero dependencies, so any
Prometheus scraper or a plain curl can watch the serving stack:

    GET /metrics       Prometheus text format (utils.metrics
                       render_prometheus over the shared registry)
    GET /healthz       200 "ok" (liveness — an optional `healthy`
                       callable downgrades to 503 when it returns False)
    GET /trace         Chrome-trace JSON of collected spans; ?id=<trace>
                       filters to one request's tree (load the response
                       in Perfetto / chrome://tracing)
    GET /trace.jsonl   the same spans as JSONL (one span per line)
    GET /traces        the distinct trace ids currently in the ring
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

log = logging.getLogger("dnn_tpu.obs")


class MetricsHTTPServer:
    """Serve the shared registry + span collector (or explicit ones) over
    HTTP. port=0 binds an ephemeral port — read `.port` after init.

    Binds LOOPBACK by default: the endpoint is unauthenticated and
    /trace exposes per-request timelines, so wider exposure (a scrape
    fleet) is an explicit `host="0.0.0.0"` opt-in, not a default."""

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 registry=None, collector=None,
                 healthy: Optional[Callable[[], bool]] = None):
        from dnn_tpu import obs
        from dnn_tpu.utils import metrics as _metrics

        self._registry = registry if registry is not None \
            else _metrics.default_metrics
        self._collector = collector if collector is not None \
            else obs.collector()
        self._healthy = healthy
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to logging, not stderr
                log.debug("metrics http: " + fmt, *args)

            def _send(self, code: int, body: str, ctype: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    url = urlparse(self.path)
                    if url.path == "/metrics":
                        self._send(200, _metrics.render_prometheus(
                            outer._registry),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif url.path == "/healthz":
                        ok = outer._healthy() if outer._healthy else True
                        self._send(200 if ok else 503,
                                   "ok\n" if ok else "unhealthy\n",
                                   "text/plain; charset=utf-8")
                    elif url.path == "/trace":
                        q = parse_qs(url.query)
                        tid = q.get("id", [None])[0]
                        self._send(200, json.dumps(
                            outer._collector.chrome_trace(tid)),
                            "application/json")
                    elif url.path == "/trace.jsonl":
                        q = parse_qs(url.query)
                        tid = q.get("id", [None])[0]
                        self._send(200, outer._collector.jsonl(tid),
                                   "application/jsonl")
                    elif url.path == "/traces":
                        self._send(200, json.dumps(
                            outer._collector.trace_ids()),
                            "application/json")
                    else:
                        self._send(404, "not found\n",
                                   "text/plain; charset=utf-8")
                except BrokenPipeError:  # scraper hung up mid-response
                    pass
                except Exception:  # noqa: BLE001 — one bad request must
                    # not kill the observer thread
                    log.exception("metrics endpoint request failed")
                    try:
                        self._send(500, "internal error\n",
                                   "text/plain; charset=utf-8")
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"obs-metrics-http:{self.port}")
        self._thread.start()
        log.info("observability endpoint on http://%s:%d/metrics",
                 host or "0.0.0.0", self.port)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
