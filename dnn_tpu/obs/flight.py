"""Flight recorder: a bounded ring of structured serving events.

A crash or a missed deadline used to leave no post-mortem record — the
span collector holds *timings* of requests that finished cleanly, and
stdout logs scroll away. This module is the black box: every notable
serving event (admissions, held-back requests, evictions, RPC retries,
deadline misses, compile events, worker errors, watchdog firings) lands
in one process-wide bounded ring, cheap enough to feed from hot paths
(one gate check + one lock + one deque append; the obs overhead probe
covers it), and dumpable three ways:

  * on demand: `GET /debugz` on the obs HTTP endpoint (obs/http.py), or
    `python -m dnn_tpu.obs flight --url http://host:port`;
  * on unhandled crash: `install_crash_dump()` chains sys.excepthook /
    threading.excepthook and writes the ring (plus the crash itself as a
    final event) to a JSONL file before the process dies — the LM daemon
    and the node CLI install it at startup;
  * programmatically: `recorder().jsonl()` / `.dump(path)`.

Event schema (one JSON object per line): {"seq": monotonically
increasing int, "ts": wall-clock epoch seconds, "kind": str, **fields}.
`seq` orders events even when ts ties; ring overflow keeps the newest
events. Producers call the module-level `record(kind, **fields)`, which
degrades to one boolean check when observability is off (DNN_TPU_OBS).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import List, Optional

__all__ = ["FlightRecorder", "recorder", "record", "install_crash_dump",
           "default_dump_dir"]


class FlightRecorder:
    """Bounded, thread-safe event ring. Capacity bounds memory on a
    week-long daemon; the newest events win on overflow."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=int(capacity))
        self._seq = 0

    def record(self, kind: str, **fields):
        """Append one event. Fields must be JSON-able plain values (the
        dump serializes with default=str as a last resort, so a stray
        object degrades to its repr instead of killing the dump)."""
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "ts": time.time(), "kind": kind}
            ev.update(fields)
            self._ring.append(ev)
        return ev

    def clear(self):
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def events(self, *, kind: Optional[str] = None,
               trace_id: Optional[str] = None,
               last: Optional[int] = None) -> List[dict]:
        """Snapshot, oldest first. `kind`/`trace_id` filter; `last` keeps
        only the newest N (applied AFTER filtering)."""
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if trace_id is not None:
            out = [e for e in out if e.get("trace_id") == trace_id]
        if last is not None:
            out = out[-int(last):]
        return out

    def window(self, ts: float, before_s: float = 30.0,
               after_s: float = 5.0) -> List[dict]:
        """Events in [ts - before_s, ts + after_s] — the context window a
        post-mortem wants around one incident (a deadline miss, a
        watchdog firing)."""
        lo, hi = ts - before_s, ts + after_s
        return [e for e in self.events() if lo <= e["ts"] <= hi]

    # -- exports --------------------------------------------------------

    def jsonl(self, **filters) -> str:
        return "".join(
            json.dumps(e, sort_keys=True, default=str) + "\n"
            for e in self.events(**filters))

    def dump(self, path: str, **filters) -> str:
        with open(path, "w") as f:
            f.write(self.jsonl(**filters))
        return path


try:
    _cap = int(os.environ["DNN_TPU_OBS_FLIGHT_CAP"])
    if _cap <= 0:
        raise ValueError(_cap)
except (KeyError, ValueError):
    # a garbage env knob must degrade to the default, not crash every
    # entry point at import (obs is imported by lm_server, node, bench)
    _cap = 4096
_recorder = FlightRecorder(_cap)


def recorder() -> FlightRecorder:
    return _recorder


_obs = None  # lazy: breaks the obs<->flight import cycle once, not
# per call — record() sits on per-admission/retirement hot paths


def record(kind: str, **fields):
    """The producer entry point: appends to the shared ring when
    observability is on, else a single boolean check and out."""
    global _obs
    if _obs is None:
        from dnn_tpu import obs as _o

        _obs = _o
    if not _obs.enabled():
        return None
    return _recorder.record(kind, **fields)


# ----------------------------------------------------------------------
# crash dump: the ring survives the process
# ----------------------------------------------------------------------

def default_dump_dir() -> str:
    """Where crash dumps (and profile spools, obs/profile.py) land:
    $DNN_TPU_OBS_DIR, else <tmp>/dnn_tpu_obs."""
    import tempfile

    return os.environ.get("DNN_TPU_OBS_DIR") or os.path.join(
        tempfile.gettempdir(), "dnn_tpu_obs")


_install_lock = threading.Lock()
_installed_dir: Optional[str] = None


def _dump_crash(origin: str, exc_type, exc, tb) -> Optional[str]:
    """Write the ring + the crash event to a fresh JSONL file. Never
    raises — a failing dump must not mask the original exception."""
    try:
        import traceback

        _recorder.record(
            "crash", origin=origin, exc_type=getattr(
                exc_type, "__name__", str(exc_type)),
            message=str(exc),
            traceback="".join(
                traceback.format_exception(exc_type, exc, tb))[-4000:])
        path = os.path.join(
            _installed_dir or default_dump_dir(),
            f"flight-crash-{os.getpid()}-{int(time.time())}.jsonl")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _recorder.dump(path)
        print(f"[dnn_tpu.obs] flight recorder dumped to {path}",
              file=sys.stderr)
        return path
    except Exception:  # noqa: BLE001 — never mask the real crash
        return None


def install_crash_dump(dump_dir: Optional[str] = None) -> str:
    """Chain sys.excepthook and threading.excepthook so an unhandled
    exception anywhere in the process writes the flight ring to
    `dump_dir` (default `default_dump_dir()`) before dying. Idempotent;
    returns the dump directory in effect. KeyboardInterrupt/SystemExit
    are normal shutdowns, not crashes — they pass through undumped."""
    global _installed_dir
    with _install_lock:
        if _installed_dir is not None:
            return _installed_dir
        _installed_dir = dump_dir or default_dump_dir()
        prev_sys = sys.excepthook
        prev_thread = threading.excepthook

        def _sys_hook(exc_type, exc, tb):
            try:
                if not issubclass(exc_type,
                                  (KeyboardInterrupt, SystemExit)):
                    _dump_crash("main", exc_type, exc, tb)
            except BaseException:  # interpreter teardown: modules may be
                pass               # gone — never shadow the real report
            prev_sys(exc_type, exc, tb)

        def _thread_hook(args):
            try:
                if not issubclass(args.exc_type,
                                  (KeyboardInterrupt, SystemExit)):
                    _dump_crash(
                        f"thread:{args.thread.name if args.thread else '?'}",
                        args.exc_type, args.exc_value, args.exc_traceback)
            except BaseException:
                pass
            prev_thread(args)

        sys.excepthook = _sys_hook
        threading.excepthook = _thread_hook
        return _installed_dir
