"""Live goodput accounting: MFU, MBU, tokens/sec, and SLO burn rates.

The ROADMAP's "fast as the hardware allows" is unverifiable from raw
tokens/sec — the number that proves it is UTILIZATION: what fraction of
the chip's peak FLOPs (MFU) and peak HBM bytes (MBU) the serving stack
actually achieves, live, while real traffic flows. Benchmarks compute
these offline (bench.py, utils/flops.py); this module computes them
continuously from the decode/prefill step stream the batcher already
produces, and exports them as scrape-time gauges:

    dnn_tpu_mfu                     achieved FLOPs/s over the window /
                                    chip peak (0 when the peak is
                                    unknown — see "peaks" below)
    dnn_tpu_mbu                     achieved HBM bytes/s / peak HBM bw
    dnn_tpu_goodput_tokens_per_sec  tokens DELIVERED to callers per
                                    second over the window (first tokens
                                    + decode commits; padding, rejected
                                    speculation, and dropped requests
                                    never count — that's the "good" in
                                    goodput)

Accounting model (utils/flops.py serving-shape helpers): a decode step
charges per-token linear FLOPs + 4*context*C attention FLOPs, and
streams the weights ONCE per step (the whole batch shares the stream —
batching's whole point) plus every live row's KV positions. Prefill
charges the full forward. The numbers are analytic, same convention as
the published MFU bookkeeping (PaLM appendix) — flash kernels that skip
masked tiles simply bank the savings as higher measured throughput.

Peaks: on TPU the per-generation table in utils/flops.py supplies them;
elsewhere they're unknown and the gauges read 0 unless the operator
states a roofline via DNN_TPU_PEAK_FLOPS / DNN_TPU_PEAK_HBM_BW (or the
explicit constructor args) — a stated peak beats no number, and tests
pin the arithmetic with explicit peaks.

SLO tracking: configure objectives (TTFT, inter-token latency,
availability) and the tracker turns the same event stream into
error-budget BURN RATES — the multiple of the sustainable error rate
currently being spent (burn 1.0 = exactly on budget; 14.4 = the classic
"page now" threshold). Exported as dnn_tpu_slo_burn_rate{slo=...}
gauges plus an `slo_breach` flight-recorder event when a burn rate
crosses 1.0 (latched per episode, so a bad hour is one event, not a
thousand).

Everything is gated like the rest of obs: producers feed the tracker
only inside their existing `obs.metrics() is not None` blocks, so
DNN_TPU_OBS=off costs nothing new.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from dnn_tpu.utils.metrics import Throughput, labeled

__all__ = ["ModelCost", "model_cost", "train_step_flops", "SLOConfig",
           "GoodputTracker"]


@dataclass(frozen=True)
class ModelCost:
    """Per-token serving cost model: `flops_per_token(context)` FLOPs to
    decode one token at `context` live positions, `prefill_flops(n)` for
    an n-token prompt, `weight_bytes` total parameter bytes (streamed
    once per decode step), `kv_bytes_per_pos` bytes one cache position
    occupies."""

    flops_per_token: object  # Callable[[float], float]
    prefill_flops: object    # Callable[[int], float]
    weight_bytes: float
    kv_bytes_per_pos: float


def model_cost(cfg, prepared=None, *, kv_bytes: float = 2,
               kv_dtype=None,
               weight_dtype_bytes: int = 2) -> ModelCost:
    """Build a ModelCost from a model config (GPT or LLaMA family,
    sniffed by attributes — n_kv_head/d_ff means LLaMA layout).
    `prepared` (the served param tree) makes weight_bytes EXACT by
    summing the real leaves; without it the analytic param count x
    `weight_dtype_bytes` stands in. `kv_dtype` (a dtype or the cache
    codec strings "int8"/"int4") prices the KV term exactly, packed
    int4 width and quantization scale rows included
    (utils/flops.kv_bytes_per_pos)."""
    from dnn_tpu.utils import flops as F

    if hasattr(cfg, "n_kv_head") and hasattr(cfg, "d_ff"):
        per_tok = lambda ctx: F.llama_decode_token_flops(cfg, ctx)  # noqa: E731
        pf = lambda n: F.llama_forward_flops(cfg, 1, n)  # noqa: E731
        params = F.llama_param_count(cfg)
    else:
        per_tok = lambda ctx: F.gpt_decode_token_flops(cfg, ctx)  # noqa: E731
        pf = lambda n: F.gpt_forward_flops(cfg, 1, n)  # noqa: E731
        params = F.gpt_param_count(cfg)
    wbytes = params * weight_dtype_bytes
    if prepared is not None:
        try:
            # device-layout pricing (int8 kernels at 1 byte, int4 at
            # the packed half byte, scale rows at full width) — the
            # quantized-weights serving path's MBU denominator must
            # shrink with the bytes it actually streams
            wbytes = F.tree_weight_bytes(prepared)
        except Exception:  # noqa: BLE001 — an exotic tree falls back to
            pass           # the analytic count, never breaks serving
    return ModelCost(
        flops_per_token=per_tok, prefill_flops=pf, weight_bytes=wbytes,
        kv_bytes_per_pos=F.kv_bytes_per_pos(cfg, kv_bytes=kv_bytes,
                                            kv_dtype=kv_dtype))


def train_step_flops(cfg, batch: int, seq: int, *, accum_steps: int = 1,
                     remat: bool = False) -> float:
    """Total FLOPs one optimizer step costs for `cfg` at (batch, seq) —
    the TRAINING counterpart of ModelCost, dispatched by the same
    family sniff model_cost uses (n_kv_head/d_ff means LLaMA layout).
    Delegates to utils/flops.{gpt,llama}_train_step_flops so serving
    and training price from ONE analytic walk: trainlens's MFU
    numerator and goodput's serving numerators can never drift onto
    different conventions. `accum_steps` validates divisibility (the
    total is linear in batch, so accumulation leaves it unchanged);
    `remat=True` prices the recompute forward (factor 4x instead of
    3x)."""
    from dnn_tpu.utils import flops as F

    if hasattr(cfg, "n_kv_head") and hasattr(cfg, "d_ff"):
        return F.llama_train_step_flops(cfg, batch, seq,
                                        accum_steps=accum_steps,
                                        remat=remat)
    return F.gpt_train_step_flops(cfg, batch, seq,
                                  accum_steps=accum_steps, remat=remat)


@dataclass(frozen=True)
class SLOConfig:
    """Service-level objectives. Latency objectives are (threshold,
    target-fraction) pairs: `ttft_s=0.5, target=0.99` reads "99% of
    requests see first token within 500 ms", giving an error budget of
    1% of requests. `availability` is the classic success-fraction
    objective (0.999 = three nines, budget 0.1% of requests). Burn rate
    = observed-bad-fraction / budget-fraction over the rolling window —
    dimensionless, 1.0 = spending exactly the budget."""

    ttft_s: Optional[float] = None
    inter_token_s: Optional[float] = None
    availability: Optional[float] = None
    target: float = 0.99
    window_s: float = 300.0


class _BudgetWindow:
    """good/bad event counts over a rolling wall-clock window, and the
    burn rate against `budget_frac`. Thread-safe; `now` injectable.

    Storage is per-SECOND count buckets with running totals, not per
    event: the inter-token objective feeds one event per decoded token,
    so a 300 s window at real decode rates would otherwise hold millions
    of live tuples, and burn_rate is read on the decode hot path (the
    per-step breach check) — both add() and burn_rate() must stay O(1)
    amortized. Eviction granularity is therefore one second, far below
    the window lengths burn rates are read at."""

    def __init__(self, budget_frac: float, window_s: float, now):
        self.budget_frac = max(budget_frac, 1e-9)
        self.window_s = window_s
        self._now = now
        self._buckets: dict = {}  # int second -> [n, bad]
        self._min_sec: Optional[int] = None
        self._n = 0
        self._bad = 0
        self._lock = threading.Lock()

    def add(self, bad: bool):
        self.add_many(1, 1 if bad else 0)

    def add_many(self, n: int, bad: int):
        """Batch feed: one lock for a whole decode step's samples (the
        per-token objective calls this every step on the hot path)."""
        t = self._now()
        sec = int(t)
        with self._lock:
            b = self._buckets.get(sec)
            if b is None:
                b = self._buckets[sec] = [0, 0]
                if self._min_sec is None:
                    self._min_sec = sec
            b[0] += n
            b[1] += bad
            self._n += n
            self._bad += bad
            self._evict(t)

    def _evict(self, t):
        # min_sec gates the sweep: it runs at most once per second that
        # actually expires, and the sweep itself is over <= window_s
        # live buckets
        cutoff = int(t - self.window_s)
        if self._min_sec is None or self._min_sec >= cutoff:
            return
        for sec in [s for s in self._buckets if s < cutoff]:
            n, bad = self._buckets.pop(sec)
            self._n -= n
            self._bad -= bad
        self._min_sec = min(self._buckets) if self._buckets else None

    def burn_rate(self) -> float:
        t = self._now()
        with self._lock:
            self._evict(t)
            if self._n == 0:
                return 0.0
            return (self._bad / self._n) / self.budget_frac


class GoodputTracker:
    """Feed it the step stream, scrape the utilization. Producers call
    `on_prefill` / `on_decode_step` (already inside their obs-gated
    blocks); `install()` registers the gauges on a registry as
    scrape-time callables (weakly bound, like the batcher's pool gauges
    — a dead tracker reads 0, never pins its pool).

    `peak_flops` / `peak_bytes`: explicit rooflines; None consults
    utils/flops.device_peak_flops / device_peak_hbm_bw lazily at first
    read (env overrides included) so construction never imports jax."""

    def __init__(self, cost: ModelCost, *,
                 peak_flops: Optional[float] = None,
                 peak_bytes: Optional[float] = None,
                 window_s: float = 60.0,
                 slo: Optional[SLOConfig] = None,
                 now=time.monotonic):
        self.cost = cost
        self._peak_flops = peak_flops
        self._peak_bytes = peak_bytes
        self._peaks_resolved = (peak_flops is not None
                                and peak_bytes is not None)
        self._flops = Throughput(window_s, now=now)
        self._bytes = Throughput(window_s, now=now)
        self._tokens = Throughput(window_s, now=now)
        # decode-step accumulator, flushed into the windows every
        # _FLUSH_STEPS steps by the ONE producer thread: three
        # locked deque updates per sub-ms step were measurable against
        # the serving obs budget, and a 60 s rate window cannot resolve
        # a <100 ms batching delay anyway. Scrapes read the windows
        # as-is (≤ _FLUSH_STEPS-steps stale, idle decay unaffected);
        # only the producer touches the _acc_* fields, so there is no
        # lock and no race.
        self._acc_flops = 0.0
        self._acc_bytes = 0.0
        self._acc_tokens = 0
        self._acc_steps = 0
        self._acc_t = 0.0  # first-unflushed-step stamp, for readers
        self.slo = slo
        self._slo_windows = {}
        self._breach_latched: dict = {}
        if slo is not None:
            lat_budget = 1.0 - slo.target
            if slo.ttft_s is not None:
                self._slo_windows["ttft"] = _BudgetWindow(
                    lat_budget, slo.window_s, now)
            if slo.inter_token_s is not None:
                self._slo_windows["inter_token"] = _BudgetWindow(
                    lat_budget, slo.window_s, now)
            if slo.availability is not None:
                self._slo_windows["availability"] = _BudgetWindow(
                    1.0 - slo.availability, slo.window_s, now)

    # -- producer feeds (call only when obs.metrics() is not None) -----

    def on_prefill(self, prompt_len: int):
        """One admitted prompt finished prefilling (and sampled its
        first token)."""
        self._flops.add(self.cost.prefill_flops(prompt_len))
        # prefill streams the weights once and WRITES prompt_len cache
        # positions
        self._bytes.add(self.cost.weight_bytes
                        + prompt_len * self.cost.kv_bytes_per_pos)
        self._tokens.add(1)

    def on_decode_step(self, n_tokens: int, live_positions: float):
        """One pool decode step committed `n_tokens` across the active
        slots, whose live cache positions sum to `live_positions`."""
        if n_tokens <= 0:
            return
        mean_ctx = live_positions / n_tokens
        if self._acc_steps == 0:
            # stamp the batch ONCE (readers age pending out of the
            # window by it) — the other 31 steps never read the clock
            self._acc_t = self._flops._now()
        self._acc_flops += n_tokens * self.cost.flops_per_token(mean_ctx)
        self._acc_bytes += (self.cost.weight_bytes
                            + live_positions * self.cost.kv_bytes_per_pos)
        self._acc_tokens += n_tokens
        self._acc_steps += 1
        if self._acc_steps >= self._FLUSH_STEPS:
            self._flush_steps()

    #: decode-step batching cadence (see __init__; StepClock.FLUSH_EVERY
    #: is the same idea for histograms)
    _FLUSH_STEPS = 32

    def _flush_steps(self):
        """Land the accumulated decode-step work in the rate windows —
        one clock read, three locked updates, every _FLUSH_STEPS steps
        instead of every step. Producer-thread only."""
        t = self._flops._now()
        self._flops.add_at(t, self._acc_flops)
        self._bytes.add_at(t, self._acc_bytes)
        self._tokens.add_at(t, self._acc_tokens)
        self._acc_flops = 0.0
        self._acc_bytes = 0.0
        self._acc_tokens = 0
        self._acc_steps = 0

    def on_ttft(self, seconds: float):
        if "ttft" in self._slo_windows:
            self._slo_event("ttft", bad=seconds > self.slo.ttft_s)

    def on_inter_token(self, samples):
        w = self._slo_windows.get("inter_token")
        if w is None:
            return
        thr = self.slo.inter_token_s
        w.add_many(len(samples), sum(1 for s in samples if s > thr))
        self._check_breach("inter_token")

    def on_outcome(self, ok: bool):
        self._slo_event("availability", bad=not ok)

    def _slo_event(self, name: str, *, bad: bool):
        w = self._slo_windows.get(name)
        if w is None:
            return
        w.add(bad)
        self._check_breach(name)

    def _check_breach(self, name: str):
        """Flight event when a burn rate crosses 1.0 — latched per
        episode (set on crossing, cleared when the rate recovers), so a
        sustained breach is ONE event with the rate that tripped it."""
        rate = self._slo_windows[name].burn_rate()
        if rate > 1.0 and not self._breach_latched.get(name):
            self._breach_latched[name] = True
            from dnn_tpu import obs

            obs.flight.record("slo_breach", slo=name,
                              burn_rate=round(rate, 3))
            m = obs.metrics()
            if m is not None:
                m.inc(labeled("dnn_tpu_slo_breach_total", slo=name))
        elif rate <= 1.0:
            self._breach_latched[name] = False

    # -- scrape-time reads ---------------------------------------------

    def _resolve_peaks(self):
        if self._peaks_resolved:
            return
        self._peaks_resolved = True
        try:
            from dnn_tpu.utils import flops as F

            if self._peak_flops is None:
                self._peak_flops = F.device_peak_flops()
            if self._peak_bytes is None:
                self._peak_bytes = F.device_peak_hbm_bw()
        except Exception:  # noqa: BLE001 — no backend at scrape time
            pass           # reads 0, same as "peak unknown"

    def mfu(self) -> float:
        self._resolve_peaks()
        if not self._peak_flops:
            return 0.0
        return self.achieved_flops_per_sec() / self._peak_flops

    def mbu(self) -> float:
        self._resolve_peaks()
        if not self._peak_bytes:
            return 0.0
        return self.achieved_bytes_per_sec() / self._peak_bytes

    # every rate read folds in the pending (unflushed) decode-step
    # batch via per_sec_with — scrapes stay exact between flushes, and
    # stale pending ages out of the window like landed events

    def tokens_per_sec(self) -> float:
        return self._tokens.per_sec_with(self._acc_tokens, self._acc_t)

    def achieved_flops_per_sec(self) -> float:
        return self._flops.per_sec_with(self._acc_flops, self._acc_t)

    def achieved_bytes_per_sec(self) -> float:
        return self._bytes.per_sec_with(self._acc_bytes, self._acc_t)

    def burn_rates(self) -> dict:
        return {k: w.burn_rate() for k, w in self._slo_windows.items()}

    def install(self, registry=None) -> "GoodputTracker":
        """Register the gauges as scrape-time callables on `registry`
        (default: the shared obs registry). Weakly bound: the registry
        must not pin a retired tracker (and its pool) alive — a
        collected tracker's gauges read 0, which is what "no serving"
        means."""
        import weakref

        if registry is None:
            from dnn_tpu.utils.metrics import default_metrics as registry
        ref = weakref.ref(self)

        def reader(method):
            def read():
                t = ref()
                return getattr(t, method)() if t is not None else 0.0
            return read

        fns = {
            "dnn_tpu_mfu": reader("mfu"),
            "dnn_tpu_mbu": reader("mbu"),
            "dnn_tpu_goodput_tokens_per_sec": reader("tokens_per_sec"),
        }
        for name in self._slo_windows:
            def burn(n=name):
                t = ref()
                return (t._slo_windows[n].burn_rate()
                        if t is not None else 0.0)
            fns[labeled("dnn_tpu_slo_burn_rate", slo=name)] = burn
        registry.bulk(gauge_fns=fns)
        return self
