"""Memory observability: device HBM, host RSS, pool watermarks.

Nothing in the tree accounted for memory before this module: an OOM-ing
pool read as a mystery crash, and "how close is the KV pool to full"
had no answer short of a debugger. Three layers, all exported through
the shared registry as scrape-time CALLABLE gauges (stored gauges
freeze on idle processes — the PR-3 lesson):

  * per-device `memory_stats()` (`install_memory_gauges`):
    dnn_tpu_device_bytes_in_use / _peak_bytes_in_use / _bytes_limit,
    labeled {device=}. Platforms whose client exposes no memory_stats
    (some CPU builds) simply register nothing — absence is the honest
    signal. The device list is snapshotted ONCE at install time, after
    the backend is already up: gauges must never be the thing that
    first-touches (and possibly hangs on) a wedged backend at scrape
    time;
  * host RSS (`process_resident_bytes`): /proc-based with a getrusage
    fallback — the host-side complement (tokenizer tables, numpy
    staging, compile cache growth all land here);
  * pool watermarks, registered by their owners against this module's
    naming: the paged block pool's used/free/high-water
    (runtime/paged_kvcache.BlockAllocator grows the accounting;
    runtime/serving registers the gauges), and the dense pool's KV-slot
    and active-slot high-waters (runtime/serving).

Everything is a read-only callable evaluated under the registry lock at
scrape; install is idempotent per registry.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["rss_bytes", "install_memory_gauges", "logical_nbytes"]


def logical_nbytes(tree) -> float:
    """HBM bytes of a pytree of arrays, pricing SUB-BYTE dtypes at their
    packed width: a jnp.int4 element is half a byte in the device layout
    (XLA S4 packs two per byte), while `arr.nbytes` / `itemsize` report
    the UNPACKED 1-byte host representation — an itemsize walk would
    overstate an int4 KV pool's memory 2x, which is exactly the class of
    quantized-cache accounting bug the serving byte gauges
    (serving.kv_cache_bytes) and utils/flops.py's MBU denominators must
    not share. Shape/dtype metadata only — never forces a device sync,
    so it is safe inside scrape-time gauges."""
    import jax

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dt = getattr(leaf, "dtype", None)
        if size is None or dt is None:
            continue
        name = getattr(dt, "name", str(dt))
        if name in ("int4", "uint4"):
            total += size * 0.5
        else:
            total += size * dt.itemsize
    return total


def rss_bytes() -> float:
    """Resident set of this process in bytes; 0.0 when unreadable (a
    gauge must not raise into the scrape)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KB on Linux, bytes on macOS (peak, not current —
        # the best a /proc-less host offers)
        return float(ru if sys.platform == "darwin" else ru * 1024)
    except Exception:  # noqa: BLE001
        return 0.0


def _device_gauge(dev, key: str):
    def read() -> float:
        try:
            stats = dev.memory_stats()
            return float(stats.get(key, 0) if stats else 0)
        except Exception:  # noqa: BLE001 — a dying device must not
            return 0.0     # break every scrape
    return read


_installed_registries: "set[int]" = set()


def install_memory_gauges(registry=None) -> list:
    """Register the device + host memory gauges on `registry` (default:
    the shared obs registry). Returns the list of series registered.
    Idempotent per registry object; safe to call from every server
    constructor. Must be called AFTER the backend is initialized — it
    touches jax.devices() exactly once, here, never at scrape time."""
    from dnn_tpu import obs
    from dnn_tpu.utils.metrics import labeled

    if registry is None:
        registry = obs.metrics()
    if registry is None:  # observability off: nothing to install
        return []
    if id(registry) in _installed_registries:
        # the marker alone is not enough: a registry.clear() (test /
        # benchmark legs reset series wholesale) wipes the installed
        # gauges while this id stays latched, and every LATER server
        # on the same registry would then scrape without host/device
        # memory series — found by the ISSUE-10 tier-1 run as a
        # deterministic cross-module failure (an LMServer installed,
        # a transport test cleared, an obs test scraped). The host
        # gauge is the cheap liveness probe: present means the install
        # survives; absent means re-install.
        if "process_resident_bytes" in registry.gauges:
            return []
        _installed_registries.discard(id(registry))
    registered = []
    registry.set_fn("process_resident_bytes", rss_bytes)
    registered.append("process_resident_bytes")
    try:
        import jax

        devices = list(jax.devices())
    except Exception:  # noqa: BLE001 — no backend, no device gauges
        devices = []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        if not stats:
            continue  # platform exposes no memory accounting
        label = f"{dev.platform}:{dev.id}"
        for series, key in (
                ("dnn_tpu_device_bytes_in_use", "bytes_in_use"),
                ("dnn_tpu_device_peak_bytes_in_use", "peak_bytes_in_use"),
                ("dnn_tpu_device_bytes_limit", "bytes_limit")):
            if key not in stats:
                continue
            name = labeled(series, device=label)
            registry.set_fn(name, _device_gauge(dev, key))
            registered.append(name)
    _installed_registries.add(id(registry))
    return registered


def reset_for_tests(registry=None):
    """Forget the idempotence marker so a test can re-install against a
    fresh registry object reusing a recycled id()."""
    if registry is None:
        _installed_registries.clear()
    else:
        _installed_registries.discard(id(registry))
